"""Single, layered configuration surface for the whole pipeline.

The reference scatters (mismatched) defaults across three files — producer
flags ``queue_name='my'`` / ``namespace='default'`` (``producer.py:26-27``),
``DataReader`` defaults ``queue_name='shared_queue'`` / ``namespace='my'``
(``data_reader.py:5``), and ``create_queue`` defaults that differ again
(``shared_queue.py:33``) — so the documented quickstart never rendezvouses
out of the box (SURVEY.md §3 quirk 3). Here every component reads the same
dataclasses, and the producer/consumer CLIs parse into them.

Covers all 13 reference flags (``producer.py:17-33``) plus the TPU-specific
mesh/batch/infeed knobs the reference has no counterpart for.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple


class RetrievalMode:
    """Event retrieval mode, parity with psana's ImageRetrievalMode
    (reference ``producer.py:156-159``): ``calib`` = calibrated panel stack,
    ``image`` = assembled 2-D image, ``raw`` = uncalibrated ADUs."""

    CALIB = "calib"
    IMAGE = "image"
    RAW = "raw"

    ALL = (CALIB, IMAGE, RAW)


@dataclasses.dataclass
class SourceConfig:
    """What to read. Reference flags: --exp --run --detector_name --calib
    --max_steps (``producer.py:19-22,30``)."""

    exp: str = "synthetic"
    run: int = 1
    detector_name: str = "epix10k2M"
    mode: str = RetrievalMode.CALIB
    max_steps: Optional[int] = None
    # synthetic-source extras (no reference counterpart)
    num_events: int = 1024
    seed: int = 0
    dtype: str = "float32"
    # resume support (reference absent: "a restarted producer restarts the
    # run from the beginning", SURVEY.md §5). start_event is a scalar floor
    # applied to every shard; cursor_path points at a StreamCursor JSON
    # (checkpoint.py) written by a consumer — on restart each shard resumes
    # from its own contiguous watermark, re-producing anything not durably
    # processed (at-least-once).
    start_event: int = 0
    cursor_path: Optional[str] = None

    def __post_init__(self):
        if self.mode not in RetrievalMode.ALL:
            raise ValueError(f"mode must be one of {RetrievalMode.ALL}, got {self.mode!r}")


@dataclasses.dataclass
class MaskConfig:
    """Masking. Reference flags: --uses_bad_pixel_mask --manual_mask_path
    (``producer.py:23-24``); applied as ``np.where(mask, data, 0)``
    (``producer.py:92-95``)."""

    uses_bad_pixel_mask: bool = False
    manual_mask_path: Optional[str] = None


@dataclasses.dataclass
class TransportConfig:
    """Queue/rendezvous. Reference flags: --ray_address --ray_namespace
    --queue_name --queue_size --num_consumers (``producer.py:25-29``).
    ONE set of defaults shared by producer, queue, and consumer."""

    address: str = "auto"
    namespace: str = "default"
    queue_name: str = "shared_queue"
    queue_size: int = 100
    num_consumers: int = 1
    # backpressure envelope, parity with producer.py:85-86,108-110
    backoff_base_s: float = 0.1
    backoff_cap_s: float = 2.0
    backoff_jitter_s: float = 0.5
    # rendezvous retry loop, parity with producer.py:56-67
    rendezvous_retries: int = 10
    rendezvous_interval_s: float = 1.0
    # consumer poll interval when starved (reference hardcodes 1 s,
    # psana_consumer.py:40 — far too coarse; default 10 ms here)
    poll_interval_s: float = 0.01
    # producer-side frames per wire round trip on transports with batched
    # puts (TCP): 1 = per-event puts (the reference's per-event RPC,
    # producer.py:101, survives only on in-process/shm paths where a put
    # is a memcpy, not a round trip)
    put_batch_size: int = 16
    # sharded queue cluster (cluster:// addresses, psana_ray_tpu.cluster):
    # how many partitions the logical queue shards into (placement is
    # rendezvous-hashed over the live server set; fixed for the life of
    # a stream — every producer and consumer must agree on it)
    cluster_partitions: int = 8
    # consumer-group name ("" = no group: every consumer competes on all
    # partitions) and this member's stable id ("" = random per process)
    group: str = ""
    member_id: str = ""
    # durable replay (ISSUE 8, server started with --durable_dir): open
    # the queue's retained segment-log range NON-destructively instead
    # of competing on the live queue. "" = live consumption; "begin" =
    # earliest retained record; "resume" = this replay group's committed
    # offset; a digit string = explicit offset. replay_group names the
    # second consumer group whose committed offset the replay advances.
    replay_from: str = ""
    replay_group: str = "replay"
    # wire compression (ISSUE 9, tcp:// and cluster:// transports):
    # codec(s) this endpoint ADVERTISES for its connections — the server
    # picks per connection (opcode 'Z'). "" = never negotiate (wire
    # bytes identical to pre-codec builds); "auto" = decide per
    # connection from a measured link-rate probe at (re)connect —
    # compression on through slow links, off on fast LANs (ISSUE 15);
    # or an explicit name / comma list. Old peers degrade the
    # connection to uncompressed, loudly but not fatally.
    wire_codec: str = ""
    # opt-in LOSSY wire dtype narrowing applied by the PRODUCER before
    # encode ("" = off): e.g. "uint16" halves f32 frame bytes before
    # compression even starts (records.narrow_panels — integer targets
    # round + clip to the representable range)
    wire_dtype: str = ""
    # serving fair-share (ISSUE 12, tcp:// and cluster:// transports):
    # the tenant identity + weight this endpoint's connections announce
    # on the 'Z' capability exchange. The event loop's stream pump is
    # weighted deficit round-robin over tenants, so one greedy tenant
    # cannot starve the rest. "" = the shared default tenant (weight 1,
    # pre-ISSUE-12 behavior). Weight range 1-64.
    tenant: str = ""
    tenant_weight: int = 1


@dataclasses.dataclass
class DurabilityConfig:
    """Queue-server segment-log knobs (ISSUE 8; ``queue_server.py
    --durable_dir ...``). No reference counterpart — the reference's
    queues die with the actor."""

    durable_dir: Optional[str] = None  # None = memory-only (the default)
    segment_bytes: int = 64 * 1024 * 1024  # pre-allocated segment size
    retain_segments: int = 8  # consumed-history segments kept for replay
    fsync: str = "batch"  # none | batch | always (see storage.log)
    fsync_batch_n: int = 64  # appends per fsync under the batch policy
    # RAM-resident records per queue before spill-to-disk (0 = the
    # queue's own maxsize — spill only past the nominal depth)
    ram_items: int = 0


@dataclasses.dataclass
class InfeedConfig:
    """Host->TPU infeed (no reference counterpart; replaces the per-event
    blocking RPC of reference producer.py:101 / data_reader.py:35)."""

    batch_size: int = 32
    prefetch_depth: int = 2
    compute_dtype: str = "bfloat16"
    drop_remainder: bool = False  # False => pad + mask the final partial batch


@dataclasses.dataclass
class MeshConfig:
    """Device mesh layout for pjit'd consumers. Axes follow the scaling-book
    convention: data (DP across hosts/chips), model (TP within)."""

    axis_names: Tuple[str, ...] = ("data", "model")
    # -1 = infer that axis so prod(shape) == device count
    axis_shape: Tuple[int, ...] = (-1, 1)


@dataclasses.dataclass
class LogConfig:
    """Reference flag: --log_level (``producer.py:31-32``)."""

    level: str = "INFO"
    fmt: str = "%(asctime)s - %(levelname)s - %(message)s"


@dataclasses.dataclass
class PipelineConfig:
    """Aggregate config: one object, one source of truth."""

    source: SourceConfig = dataclasses.field(default_factory=SourceConfig)
    mask: MaskConfig = dataclasses.field(default_factory=MaskConfig)
    transport: TransportConfig = dataclasses.field(default_factory=TransportConfig)
    infeed: InfeedConfig = dataclasses.field(default_factory=InfeedConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    log: LogConfig = dataclasses.field(default_factory=LogConfig)

    def replace(self, **kw) -> "PipelineConfig":
        return dataclasses.replace(self, **kw)
