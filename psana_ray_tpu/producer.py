"""Producer runtime + CLI: sharded ingest into a named, backpressured queue.

The reference's producer (``producer.py``) is an MPI program: N ranks, each
reading its psana shard and pushing framed events through a blocking RPC,
with barriers at bootstrap/shutdown and rank 0 emitting one EOS sentinel
per consumer (``producer.py:119-130``). This runtime keeps every protocol —
shard-per-worker ingest, get-or-create rendezvous, backpressure with the
same backoff envelope, barrier-then-EOS, dead-queue detection, SIGINT
handling, ``--max_steps`` — but as an explicit, testable object that runs
shards as threads in one process (TPU hosts are fed per-process; event
generation releases the GIL in numpy) or as one shard of a multi-host
deployment via ``shard_rank/num_shards``.

All 13 reference flags (``producer.py:17-33``) are covered by
:class:`PipelineConfig`; the CLI exposes them with the same names.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
import time
from typing import List, Optional

import numpy as np

from psana_ray_tpu.config import MaskConfig, PipelineConfig, RetrievalMode, SourceConfig, TransportConfig
from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.obs.profiling.stagetag import TAG_ENQUEUE, set_stage, swap_stage
from psana_ray_tpu.obs.stages import HOP_ENQ, HOP_SRC, STAGE_ENQUEUE
from psana_ray_tpu.obs.tracing import SPAN_PRODUCE, TRACER
from psana_ray_tpu.records import EndOfStream, FrameRecord, mark_hop, narrow_panels
from psana_ray_tpu.sources import open_source
from psana_ray_tpu.transport import BackoffPolicy, Registry, TransportClosed, TransportWedged
from psana_ray_tpu.transport.addressing import open_queue
from psana_ray_tpu.utils.metrics import PipelineMetrics

logger = logging.getLogger(__name__)


class _Sender:
    """Backpressured frame sender, preferring the fastest path the
    transport offers:

    - **windowed pipelined PUT** (TCP, ``put_pipelined``): each record
      goes out immediately, up to W sequence-numbered puts in flight
      before blocking on acknowledgements — the link stays full instead
      of paying one round trip per flush, backpressure arrives as
      delayed acks from the server's blocking enqueue (no refusal/retry
      spin), and a reconnect resends exactly the unacked tail;
    - **batched puts** (``put_batch``): one round trip per N frames
      (the pre-streaming TCP path, kept for transports without the
      windowed opcode);
    - per-event puts otherwise (in-process/shm — a put is a memcpy).

    Over TCP every variant leaves via ``sendmsg`` scatter-gather
    straight from each record's panel memory (``FrameRecord.
    wire_parts``): a producer put performs ZERO payload copies."""

    def __init__(self, queue, backoff, stop_event, metrics, batch_size: int = 16):
        self.queue = queue
        self.backoff = backoff
        self.stop = stop_event
        self.metrics = metrics
        self.windowed = hasattr(queue, "put_pipelined")
        self.batch_size = (
            batch_size if (not self.windowed and hasattr(queue, "put_batch")) else 1
        )
        self.pending: List[FrameRecord] = []

    def send(self, rec) -> bool:
        """Buffer + flush when full (windowed: ship immediately, blocking
        only when the in-flight window is full). False = transport
        closed/stopped."""
        prev = swap_stage(TAG_ENQUEUE)
        try:
            if self.windowed:
                return self._send_windowed(rec)
            self.pending.append(rec)
            if len(self.pending) >= self.batch_size:
                return self.flush()
            return True
        finally:
            set_stage(prev)

    def _send_windowed(self, rec) -> bool:
        t_try = time.monotonic()
        if rec.hops is not None:
            rec.hops[HOP_ENQ] = t_try
        while not self.stop.is_set():
            try:
                # bounded slices so stop() stays responsive while the
                # window is full (server blocked on a full queue)
                if self.queue.put_pipelined(
                    rec, deadline=time.monotonic() + 0.5
                ):
                    break
            except TransportWedged:
                raise  # a crashed peer wedged the ring: error, not clean exit
            except TransportClosed:
                return False
        else:
            return False
        self.metrics.observe_frame(rec.nbytes)
        h = rec.hops
        if h is not None and HOP_SRC in h:
            self.metrics.stages.observe(STAGE_ENQUEUE, t_try - h[HOP_SRC])
        trace = rec.trace
        if trace is not None and trace.sampled and TRACER.enabled:
            t_src = h[HOP_SRC] if h and HOP_SRC in h else t_try
            TRACER.instant(trace.trace_id, SPAN_PRODUCE, t_src)
            TRACER.span(trace.trace_id, STAGE_ENQUEUE, t_src, t_try)
        return True

    def flush(self) -> bool:
        """Drain the buffer with the backpressure envelope (parity:
        producer.py:106-111). Windowed: block until every in-flight put
        is acknowledged (the durability point before EOS/barrier).
        False = transport closed/stopped (records may remain pending —
        the stream is dead either way)."""
        prev = swap_stage(TAG_ENQUEUE)
        try:
            return self._drain_buffered()
        finally:
            set_stage(prev)

    def _drain_buffered(self) -> bool:
        if self.windowed:
            while not self.stop.is_set():
                try:
                    if self.queue.flush_puts(
                        deadline=time.monotonic() + 0.5
                    ):
                        return True
                except TransportWedged:
                    raise
                except TransportClosed:
                    return False
            return False
        while self.pending:
            if self.stop.is_set():
                return False
            # enqueue hop stamp goes on BEFORE the put so an in-process
            # consumer can never pop a record that lacks it (it re-stamps
            # on each backpressure retry, so the final value is just-
            # before-the-successful-put); producer-side enqueue latency
            # (source read done -> accepted, incl. backpressure wait)
            # lands in this process's stage histogram below
            t_try = time.monotonic()
            attempt = self.pending if self.batch_size > 1 else self.pending[:1]
            for r in attempt:
                if r.hops is not None:
                    r.hops[HOP_ENQ] = t_try
            try:
                if self.batch_size > 1:
                    accepted = self.queue.put_batch(self.pending)
                else:
                    accepted = 1 if self.queue.put(self.pending[0]) else 0
            except TransportWedged:
                raise  # a crashed peer wedged the ring: error, not clean exit
            except TransportClosed:
                return False
            if accepted:
                for r in self.pending[:accepted]:
                    self.metrics.observe_frame(r.nbytes)
                    h = r.hops
                    if h is not None and HOP_SRC in h:
                        self.metrics.stages.observe(STAGE_ENQUEUE, t_try - h[HOP_SRC])
                    trace = r.trace
                    if trace is not None and trace.sampled and TRACER.enabled:
                        # producer-side spans: frame birth (instant) +
                        # enqueue (source read done -> accepted, incl.
                        # backpressure wait) — sampled frames only
                        t_src = h[HOP_SRC] if h and HOP_SRC in h else t_try
                        TRACER.instant(trace.trace_id, SPAN_PRODUCE, t_src)
                        TRACER.span(trace.trace_id, STAGE_ENQUEUE, t_src, t_try)
                del self.pending[:accepted]
                self.backoff.reset()
            else:
                self.backoff.wait()
        return True


class ProducerRuntime:
    """Drives ``num_shards`` ingest workers into one named queue."""

    def __init__(
        self,
        config: PipelineConfig,
        registry: Optional[Registry] = None,
        num_local_shards: int = 1,
        shard_rank_offset: int = 0,
        total_shards: Optional[int] = None,
        stage_timing: bool = False,
    ):
        """``stage_timing`` stamps hop timestamps on every record
        (records.mark_hop) feeding the enqueue-stage histogram and — over
        in-process transports — downstream stage decomposition. Off by
        default: the per-frame dict + monotonic stamps are only worth
        paying when something exports them (the CLI enables it with
        ``--metrics_port``)."""
        self.config = config
        self.registry = registry or Registry.default()
        self.num_local_shards = num_local_shards
        self.shard_rank_offset = shard_rank_offset
        self.total_shards = total_shards or num_local_shards
        self.stage_timing = stage_timing
        self.metrics = PipelineMetrics()
        self._queue = None
        self._barrier = threading.Barrier(num_local_shards)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._errors: List[BaseException] = []

    # -- rendezvous (parity: producer.py:35-71) ---------------------------
    def bootstrap(self):
        if self._queue is not None:
            # idempotent: the CLI may bootstrap early (autotune knobs
            # wrap the data client) and run()/the tracer path bootstrap
            # again — re-opening would orphan the connection the knobs
            # actuate while the pumps send on a fresh one
            return self._queue
        t = self.config.transport
        self._queue = open_queue(t, role="producer", registry=self.registry)
        if not self.metrics.has_queue:
            # depth in status/snapshot — unless the CLI already attached a
            # dedicated monitor handle (over TCP a scrape on the DATA
            # connection would block behind a put's reconnect backoff,
            # serialized under the client lock)
            self.metrics.attach_queue(self._queue)
        logger.info(
            "queue %r ready (namespace=%r address=%r size=%d)",
            t.queue_name, t.namespace, t.address, t.queue_size,
        )
        return self._queue

    # -- per-shard event pump (parity: produce_data, producer.py:78-130) --
    def _pump(self, local_idx: int):
        cfg = self.config
        rank = self.shard_rank_offset + local_idx
        t = cfg.transport
        try:
            start_event = self._resume_point(rank)
            source = open_source(
                cfg.source.exp,
                cfg.source.run,
                cfg.source.detector_name,
                shard_rank=rank,
                num_shards=self.total_shards,
                num_events=cfg.source.num_events,
                seed=cfg.source.seed,
                dtype=cfg.source.dtype,
                start_event=start_event,
            )
            if start_event:
                logger.info("rank %d resuming at event >= %d", rank, start_event)
            mask = self._load_mask(source)
            backoff = BackoffPolicy(t.backoff_base_s, t.backoff_cap_s, t.backoff_jitter_s)
            sender = _Sender(
                self._queue, backoff, self._stop, self.metrics, t.put_batch_size
            )
            produced = 0
            wire_dtype = t.wire_dtype  # opt-in LOSSY narrowing (ISSUE 9)
            for idx, data, energy in source.iter_indexed_events(cfg.source.mode):
                if self._stop.is_set():
                    break
                if cfg.source.max_steps is not None and produced >= cfg.source.max_steps:
                    logger.info("rank %d: reached max_steps=%d", rank, cfg.source.max_steps)
                    break
                if mask is not None:
                    data = np.where(mask, data, 0)  # parity: producer.py:92-95
                if wire_dtype:
                    # narrow BEFORE encode: half (or less) the wire bytes
                    # before the codec even runs — records.narrow_panels
                    # rounds + clips integer targets
                    data = narrow_panels(np.asarray(data), wire_dtype)
                # sampled tracing gate: None on the unsampled hot path
                # (zero allocations — counter arithmetic only)
                trace_ctx = TRACER.maybe_trace()
                rec = FrameRecord(
                    rank, int(idx), data, energy, timestamp=time.time(),
                    trace=trace_ctx,
                )
                if self.stage_timing or trace_ctx is not None:
                    mark_hop(rec, HOP_SRC)  # source read done
                if not sender.send(rec):
                    logger.warning("rank %d: queue dead, exiting", rank)
                    return  # parity: producer.py:112-114
                produced += 1
                logger.debug(
                    "rank %d produced idx=%d shape=%s energy=%.2f",
                    rank, idx, rec.panels.shape, energy,
                )
            if not sender.flush():  # tail of the batch buffer precedes EOS
                logger.warning("rank %d: queue dead at flush, exiting", rank)
                return
            # barrier so EOS follows ALL shards' data (parity: producer.py:120)
            self._barrier.wait(timeout=600)
            if local_idx == 0:
                self._emit_eos()
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised in run()
            self._errors.append(e)
            logger.exception("rank %d failed", rank)
            try:
                self._barrier.abort()
            except Exception:
                pass

    def _emit_eos(self):
        """Local rank 0 puts one typed EOS per expected consumer
        (parity: producer.py:121-126, tolerating a dead queue :127-130).

        The marker carries this runtime's shard coverage so consumers with
        an :class:`EosTally` stop only when EVERY runtime feeding the queue
        has finished — the role the reference's global MPI barrier played
        (``producer.py:119-126``)."""
        t = self.config.transport
        eos = EndOfStream(
            producer_rank=self.shard_rank_offset,
            shards_done=self.num_local_shards,
            total_shards=self.total_shards,
        )
        for _ in range(t.num_consumers):
            try:
                while not self._queue.put_wait(eos, timeout=5.0):
                    if self._stop.is_set():
                        return
            except TransportWedged:
                raise  # crashed-peer wedge: surface it, don't log-and-exit
            except TransportClosed:
                logger.warning("queue died before EOS could be delivered")
                return
        FLIGHT.record(
            "eos_emitted",
            producer_rank=self.shard_rank_offset,
            consumers=t.num_consumers,
        )
        logger.info("EOS delivered to %d consumer(s)", t.num_consumers)

    def _resume_point(self, rank: int) -> int:
        """Where shard ``rank`` should (re)start: the scalar
        ``start_event`` floor, raised to the cursor's per-shard contiguous
        watermark when ``cursor_path`` names a consumer-written
        :class:`~psana_ray_tpu.checkpoint.StreamCursor`. At-least-once:
        events pending above the watermark at crash time are re-produced."""
        cfg = self.config.source
        start = cfg.start_event
        if cfg.cursor_path:
            from psana_ray_tpu.checkpoint import StreamCursor

            cursor = StreamCursor.load(cfg.cursor_path)
            if cursor.positions:
                if cursor.stride != self.total_shards:
                    # a mismatched stride would compute wrong per-shard
                    # resume points and silently SKIP events — refuse
                    raise ValueError(
                        f"cursor {cfg.cursor_path!r} was written for "
                        f"stride={cursor.stride} but this producer topology "
                        f"has total_shards={self.total_shards}"
                    )
                start = max(start, cursor.resume_point(rank))
        return start

    def _load_mask(self, source) -> Optional[np.ndarray]:
        m = self.config.mask
        mask = None
        if m.uses_bad_pixel_mask:
            mask = source.create_bad_pixel_mask()  # parity: producer.py:81
        if m.manual_mask_path:
            manual = np.load(m.manual_mask_path)  # parity: producer.py:82
            mask = manual if mask is None else (mask.astype(bool) & manual.astype(bool))
        return mask

    # -- lifecycle --------------------------------------------------------
    def run(self, block: bool = True):
        if self._queue is None:
            self.bootstrap()
        self._threads = [
            threading.Thread(target=self._pump, args=(i,), name=f"producer-shard-{i}")
            for i in range(self.num_local_shards)
        ]
        for t in self._threads:
            t.start()
        if block:
            self.join()

    def join(self):
        for t in self._threads:
            t.join()
        if self._errors:
            raise self._errors[0]

    def stop(self):
        self._stop.set()


def parse_arguments(argv=None):
    """All 13 reference flags (``producer.py:17-33``), same spellings."""
    p = argparse.ArgumentParser(prog="psana-ray-tpu-producer")
    p.add_argument("--exp", default="synthetic")
    p.add_argument("--run", type=int, default=1)
    p.add_argument("--detector_name", default="epix10k2M")
    p.add_argument("--calib", action="store_true", help="calibrated mode (else raw)")
    p.add_argument("--uses_bad_pixel_mask", action="store_true")
    p.add_argument("--manual_mask_path", default=None)
    p.add_argument("--ray_address", "--address", dest="address", default="auto")
    p.add_argument("--ray_namespace", "--namespace", dest="namespace", default="default")
    p.add_argument("--queue_name", default="shared_queue")
    p.add_argument("--queue_size", type=int, default=100)
    p.add_argument("--num_consumers", type=int, default=1)
    p.add_argument("--max_steps", type=int, default=None)
    p.add_argument("--log_level", default="INFO")
    from psana_ray_tpu.autotune import add_autotune_args
    from psana_ray_tpu.obs import (
        add_history_args,
        add_metrics_args,
        add_profile_args,
        add_trace_args,
    )
    from psana_ray_tpu.transport.addressing import add_cluster_args, add_wire_args

    add_metrics_args(p)
    add_trace_args(p)
    add_history_args(p)
    add_profile_args(p)
    add_cluster_args(p)
    add_wire_args(p, producer=True)
    add_autotune_args(p)
    p.add_argument("--num_shards", type=int, default=1, help="local ingest workers")
    p.add_argument("--num_events", type=int, default=1024, help="synthetic events")
    p.add_argument(
        "--shard_rank_offset", type=int, default=None,
        help="global shard offset of this process (default: auto from MPI/SLURM env)",
    )
    p.add_argument(
        "--total_shards", type=int, default=None,
        help="global shard count across all producer processes (default: auto)",
    )
    p.add_argument(
        "--start_event", type=int, default=0,
        help="skip events below this index in every shard (resume floor; "
        "the reference restarts from zero, SURVEY.md §5)",
    )
    p.add_argument(
        "--cursor_path", default=None,
        help="StreamCursor JSON written by a consumer (--cursor_path on "
        "psana-ray-tpu-consumer): on restart each shard resumes from its "
        "contiguous processed watermark (at-least-once)",
    )
    a = p.parse_args(argv)
    from psana_ray_tpu.transport.addressing import apply_cluster_args, apply_wire_args

    return PipelineConfig(
        source=SourceConfig(
            exp=a.exp,
            run=a.run,
            detector_name=a.detector_name,
            # reference parity: absence of --calib selects assembled-image
            # mode, not raw ADUs (reference producer.py:156-159)
            mode=RetrievalMode.CALIB if a.calib else RetrievalMode.IMAGE,
            max_steps=a.max_steps,
            num_events=a.num_events,
            start_event=a.start_event,
            cursor_path=a.cursor_path,
        ),
        mask=MaskConfig(a.uses_bad_pixel_mask, a.manual_mask_path),
        transport=apply_wire_args(
            apply_cluster_args(
                TransportConfig(
                    address=a.address,
                    namespace=a.namespace,
                    queue_name=a.queue_name,
                    queue_size=a.queue_size,
                    num_consumers=a.num_consumers,
                ),
                a,
            ),
            a,
        ),
    ), a


def detect_process_rank() -> tuple:
    """(process_rank, world_size) from the launcher environment.

    The reference gets these from ``MPI.COMM_WORLD`` (``producer.py:
    138-140``); here they come from the env vars every common launcher
    exports (Open MPI, MPICH/PMI, Slurm), so ``mpirun -n 4
    psana-ray-tpu-producer ...`` shards rank-derived with no mpi4py."""
    import os

    for rank_var, size_var in (
        ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
        ("PMI_RANK", "PMI_SIZE"),
        ("SLURM_PROCID", "SLURM_NTASKS"),
    ):
        if rank_var in os.environ:
            return int(os.environ[rank_var]), int(os.environ.get(size_var, 1))
    return 0, 1


def shard_topology(args) -> tuple:
    """(shard_rank_offset, total_shards) for this process: explicit flags
    win; otherwise derived from the launcher rank/size so N processes x
    ``--num_shards`` local workers tile the global event space."""
    rank, world = detect_process_rank()
    offset = (
        args.shard_rank_offset
        if args.shard_rank_offset is not None
        else rank * args.num_shards
    )
    total = (
        args.total_shards if args.total_shards is not None else world * args.num_shards
    )
    return offset, total


def main(argv=None):
    from psana_ray_tpu.utils.hostmem import enable_large_alloc_reuse

    enable_large_alloc_reuse()  # MB-scale frame buffers: heap reuse, no re-faulting
    config, args = parse_arguments(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format=config.log.fmt,  # parity: producer.py:135-136
    )
    offset, total = shard_topology(args)
    runtime = ProducerRuntime(
        config,
        num_local_shards=args.num_shards,
        shard_rank_offset=offset,
        total_shards=total,
        stage_timing=args.metrics_port > 0,
    )

    def _sigint(signum, frame):  # parity: producer.py:73-76,142-143
        logger.info("SIGINT — stopping producer")
        runtime.stop()

    signal.signal(signal.SIGINT, _sigint)
    from psana_ray_tpu.obs import MetricsRegistry, start_metrics_server

    MetricsRegistry.default().register("producer", runtime.metrics)
    metrics_server = start_metrics_server(args.metrics_port, host=args.metrics_host)
    # history ring (ISSUE 13): feeds flight-dump tails + the /federate
    # endpoint's consumers; one daemon thread, --history_interval 0 = off
    from psana_ray_tpu.obs import configure_history_from_args, configure_profiling_from_args

    history = configure_history_from_args(args)
    # continuous profiler (ISSUE 16): flame sampler + per-frame cost
    # model; one daemon thread, --profile_hz 0 = off
    profiler = configure_profiling_from_args(args, "producer")
    monitor = None
    if metrics_server is not None and str(config.transport.address).startswith(
        ("tcp://", "cluster://")
    ):
        # depth for scrapes over a DEDICATED connection: on the data
        # connection a stats() probe would queue behind a put's reconnect
        # backoff under the client lock, hanging /metrics for the whole
        # outage (in-process/shm handles have no such serialization and
        # bootstrap attaches them directly)
        try:
            monitor = open_queue(
                config.transport, role="consumer", address=config.transport.address
            )
            runtime.metrics.attach_queue(monitor)
        except Exception as e:  # noqa: BLE001 — depth is optional
            logger.debug("queue monitor unavailable: %s", e)
    from psana_ray_tpu.obs.tracing import configure_from_args, exchange_anchors

    tracer = configure_from_args(args, "producer", queue=monitor)
    # autotune (ISSUE 15): close the loop on the producer-side knobs —
    # the windowed-PUT depth and the wire codec on/off — judged by the
    # measured produce rate. An explicitly-set --wire_codec pins that
    # knob (the operator's value is a decision, not a default).
    autotune = None
    if args.autotune != "off":
        from psana_ray_tpu.autotune import Objective, configure_autotune_from_args
        from psana_ray_tpu.autotune.knobs import put_window_knob, wire_codec_knob

        q = runtime.bootstrap()
        pinned = {}
        wc = config.transport.wire_codec
        # an explicit codec name AND an explicit "none" are both
        # operator decisions ("auto" delegates, "" is the default)
        if wc and wc != "auto":
            pinned["wire_codec_on"] = "--wire_codec set explicitly"
        autotune = configure_autotune_from_args(
            args,
            [put_window_knob(q), wire_codec_knob(q)],
            Objective("producer.frames_total"),
            pinned=pinned,
        )
    try:
        if tracer is not None and monitor is None:
            # clock alignment against the queue server (tcp opcode 'A'):
            # configure_from_args already exchanged over the monitor when
            # one exists; otherwise the data client speaks it too —
            # harmless pre-stream (a producer connection never holds
            # in-flight deliveries an opcode could ACK)
            runtime.bootstrap()
            exchange_anchors(runtime._queue)
        runtime.run(block=True)
    finally:
        if autotune is not None:
            autotune.stop()
        if history is not None:
            history.stop()
        if metrics_server is not None:
            metrics_server.close()
        if monitor is not None and hasattr(monitor, "disconnect"):
            try:
                monitor.disconnect()
            except Exception:  # noqa: BLE001 — already closing
                pass
    logger.info("producer done: %s", runtime.metrics.status_line())


if __name__ == "__main__":
    main()
