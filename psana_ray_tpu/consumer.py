"""Consumer client: the reference ``DataReader`` surface, TPU-era semantics.

Parity with reference ``data_reader.py:4-48``:
- ``DataReader(address, queue_name, namespace)`` context manager;
- ``connect()`` — idempotent, resolves the named queue (with the
  producer-side retry semantics the reference gave only to producers);
- ``read()`` — one item, or None when momentarily empty (kept for drop-in
  familiarity) — but EOS is a typed :class:`EndOfStream`, never None;
- ``read_wait(timeout)`` — blocking read, replacing the example consumer's
  1 s poll-sleep (``psana_consumer.py:38-40``);
- dead transport raises :class:`DataReaderError` (parity:
  ``data_reader.py:36-37``);
- ``close()`` — release the connection.

``address='auto'`` resolves through the in-process :class:`Registry`;
``address='shm://...'`` / ``'tcp://host:port'`` select the cross-process /
cross-host transports.
"""

from __future__ import annotations

from typing import Any, Optional

from psana_ray_tpu.config import TransportConfig
from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.transport import EMPTY, Registry, RendezvousTimeout, TransportClosed


class DataReaderError(RuntimeError):
    """The transport died (parity: reference ``data_reader.py:46-48``)."""


class DataReader:
    def __init__(
        self,
        address: str = "auto",
        queue_name: Optional[str] = None,
        namespace: Optional[str] = None,
        config: Optional[TransportConfig] = None,
    ):
        self.config = config or TransportConfig()
        self.address = address if address != "auto" else self.config.address
        self.queue_name = queue_name or self.config.queue_name
        self.namespace = namespace or self.config.namespace
        self._queue = None

    # -- lifecycle (parity: data_reader.py:11-29,39-44) -------------------
    def connect(self) -> "DataReader":
        if self._queue is not None:
            return self
        try:
            if self.address in ("auto", "local"):
                self._queue = Registry.default().resolve(
                    self.namespace,
                    self.queue_name,
                    retries=self.config.rendezvous_retries,
                    interval_s=self.config.rendezvous_interval_s,
                )
            elif self.address.startswith("tcp://"):
                from psana_ray_tpu.transport.tcp import TcpQueueClient

                host, _, port = self.address[len("tcp://"):].partition(":")
                self._queue = TcpQueueClient(host, int(port))
            elif self.address.startswith("shm://"):
                from psana_ray_tpu.transport.shm_ring import ShmRingBuffer

                self._queue = ShmRingBuffer.attach(self.address[len("shm://"):])
            else:
                raise ValueError(f"unknown address scheme {self.address!r}")
        except RendezvousTimeout as e:
            raise DataReaderError(f"could not find queue {self.queue_name!r}: {e}") from e
        return self

    def close(self):
        q = self._queue
        self._queue = None
        if q is not None and hasattr(q, "disconnect"):
            q.disconnect()

    def __enter__(self) -> "DataReader":
        return self.connect()

    def __exit__(self, *exc):
        self.close()

    # -- reads ------------------------------------------------------------
    def read(self) -> Any:
        """Non-blocking read: FrameRecord | EndOfStream | None (empty).
        Parity: data_reader.py:31-37, with typed EOS instead of None."""
        self._check_connected()
        try:
            item = self._queue.get()
        except TransportClosed as e:
            raise DataReaderError(str(e)) from e
        return None if item is EMPTY else item

    def read_wait(self, timeout: Optional[float] = None) -> Any:
        """Blocking read (no 1 s poll-sleep). None only on timeout."""
        self._check_connected()
        try:
            item = self._queue.get_wait(timeout=timeout)
        except TransportClosed as e:
            raise DataReaderError(str(e)) from e
        return None if item is EMPTY else item

    def read_batch(self, max_items: int, timeout: Optional[float] = None) -> list:
        self._check_connected()
        try:
            return self._queue.get_batch(max_items, timeout=timeout)
        except TransportClosed as e:
            raise DataReaderError(str(e)) from e

    def __iter__(self):
        """Iterate FrameRecords until EOS (the loop the reference's example
        couldn't write correctly — psana_consumer.py:38-40 spins forever)."""
        self._check_connected()
        while True:
            item = self.read_wait(timeout=1.0)
            if item is None:
                continue
            if is_eos(item):
                return
            yield item

    def size(self) -> int:
        self._check_connected()
        try:
            return self._queue.size()
        except TransportClosed as e:
            raise DataReaderError(str(e)) from e

    def _check_connected(self):
        if self._queue is None:
            raise DataReaderError("not connected — call connect() or use as context manager")
