"""Consumer client: the reference ``DataReader`` surface, TPU-era semantics.

Parity with reference ``data_reader.py:4-48``:
- ``DataReader(address, queue_name, namespace)`` context manager;
- ``connect()`` — idempotent, resolves the named queue (with the
  producer-side retry semantics the reference gave only to producers);
- ``read()`` — one item, or None when momentarily empty (kept for drop-in
  familiarity) — but EOS is a typed :class:`EndOfStream`, never None;
- ``read_wait(timeout)`` — blocking read, replacing the example consumer's
  1 s poll-sleep (``psana_consumer.py:38-40``);
- dead transport raises :class:`DataReaderError` (parity:
  ``data_reader.py:36-37``);
- ``close()`` — release the connection.

``address='auto'`` resolves through the in-process :class:`Registry`;
``address='shm://...'`` / ``'tcp://host:port'`` select the cross-process /
cross-host transports.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from psana_ray_tpu.config import TransportConfig
from psana_ray_tpu.obs.profiling.stagetag import TAG_DEQUEUE, set_stage, swap_stage
from psana_ray_tpu.records import EndOfStream, EosTally, FrameRecord, is_eos
from psana_ray_tpu.transport import EMPTY, RendezvousTimeout, TransportClosed


class DataReaderError(RuntimeError):
    """The transport died (parity: reference ``data_reader.py:46-48``)."""


class DataReader:
    def __init__(
        self,
        address: str = "auto",
        queue_name: Optional[str] = None,
        namespace: Optional[str] = None,
        config: Optional[TransportConfig] = None,
        streaming: bool = False,
        stream_window: int = 32,
        replay_from: Optional[str] = None,
        replay_group: Optional[str] = None,
    ):
        """``streaming=True`` (TCP transports) subscribes the data
        connection to server-push delivery with a ``stream_window``-frame
        credit window (transport.tcp streaming contract): ``read_wait``/
        ``read_batch``/``iter_records`` then drain pushed frames with no
        per-read round trip and no empty-queue polling — the pull RTT
        disappears and the credit window bounds client memory like a
        prefetch depth. Delivery stays at-least-once: frames this reader
        consumed-but-not-yet-acked redeliver to another consumer on a
        crash. Ignored (plain reads) on transports without streaming.

        ``replay_from`` (ISSUE 8, servers started with --durable_dir)
        opens the queue's retained segment-log range NON-destructively
        for a second consumer group instead of competing on the live
        queue: ``"begin"`` starts at the earliest retained record,
        ``"resume"`` at ``replay_group``'s committed offset, a digit
        string at an explicit offset. Delivered records commit the
        group's offset at the connection's implicit-ACK points, so a
        crashed replay consumer reconnects at resume — duplicates
        possible, loss never. Implies plain (pull) reads."""
        self.config = config or TransportConfig()
        self.address = address if address != "auto" else self.config.address
        self.queue_name = queue_name or self.config.queue_name
        self.namespace = namespace or self.config.namespace
        self.streaming = streaming
        self.stream_window = stream_window
        self.replay_from = (
            replay_from if replay_from is not None else
            (self.config.replay_from or None)
        )
        self.replay_group = replay_group or self.config.replay_group
        if self.replay_from is not None:
            self.streaming = False  # replay is pull-mode by design
        self._queue = None

    # -- lifecycle (parity: data_reader.py:11-29,39-44) -------------------
    def _open(self):
        import dataclasses

        from psana_ray_tpu.transport.addressing import open_queue

        cfg = dataclasses.replace(
            self.config, queue_name=self.queue_name, namespace=self.namespace
        )
        return open_queue(cfg, role="consumer", address=self.address)

    def connect(self) -> "DataReader":
        if self._queue is not None:
            return self
        try:
            self._queue = self._open()
        except RendezvousTimeout as e:
            raise DataReaderError(f"could not find queue {self.queue_name!r}: {e}") from e
        if self.replay_from is not None:
            if not hasattr(self._queue, "replay_open"):
                raise DataReaderError(
                    f"transport {self.address!r} does not support replay "
                    f"(need a tcp:// or cluster:// durable queue server)"
                )
            start = (
                self.replay_from
                if self.replay_from in ("begin", "resume")
                else int(self.replay_from)
            )
            try:
                self._queue.replay_open(start, group=self.replay_group)
            except TransportClosed as e:
                raise DataReaderError(str(e)) from e
            except RuntimeError as e:  # server refused: not durable
                raise DataReaderError(str(e)) from e
        if self.streaming and hasattr(self._queue, "stream_open"):
            try:
                self._queue.stream_open(self.stream_window)
            except TransportClosed as e:
                raise DataReaderError(str(e)) from e
        return self

    @property
    def queue(self) -> Any:
        """The underlying transport handle once connected (None before)
        — what the autotune knob factories wrap (ISSUE 15)."""
        return self._queue

    def close(self):
        q = self._queue
        self._queue = None
        if q is not None and hasattr(q, "disconnect"):
            q.disconnect()

    def __enter__(self) -> "DataReader":
        return self.connect()

    def __exit__(self, *exc):
        self.close()

    # -- reads ------------------------------------------------------------
    # Ownership note (zero-copy datapath, ISSUE 2): over the pooled TCP
    # transport a returned FrameRecord's panels may VIEW a recycled
    # receive buffer, kept checked out by ``rec.lease`` for the record's
    # lifetime (released on GC, or eagerly by the batcher's push_view).
    # Reading ``rec.panels`` while you hold the record is always safe;
    # to retain the pixels past the record, copy them (or call
    # ``rec.materialize()``).
    def read(self) -> Any:
        """Non-blocking read: FrameRecord | EndOfStream | None (empty).
        Parity: data_reader.py:31-37, with typed EOS instead of None."""
        self._check_connected()
        prev = swap_stage(TAG_DEQUEUE)
        try:
            item = self._queue.get()
        except TransportClosed as e:
            raise DataReaderError(str(e)) from e
        finally:
            set_stage(prev)
        return None if item is EMPTY else item

    def read_wait(self, timeout: Optional[float] = None) -> Any:
        """Blocking read (no 1 s poll-sleep). None only on timeout."""
        self._check_connected()
        prev = swap_stage(TAG_DEQUEUE)
        try:
            item = self._queue.get_wait(timeout=timeout)
        except TransportClosed as e:
            raise DataReaderError(str(e)) from e
        finally:
            set_stage(prev)
        return None if item is EMPTY else item

    def read_batch(self, max_items: int, timeout: Optional[float] = None) -> list:
        self._check_connected()
        prev = swap_stage(TAG_DEQUEUE)
        try:
            return self._queue.get_batch(max_items, timeout=timeout)
        except TransportClosed as e:
            raise DataReaderError(str(e)) from e
        finally:
            set_stage(prev)

    def __iter__(self):
        """Iterate FrameRecords until the stream completes (the loop the
        reference's example couldn't write correctly — psana_consumer.py:
        38-40 spins forever)."""
        return self.iter_records()

    def iter_records(self, stop=None):
        """Yield FrameRecords until the stream completes or ``stop()``
        returns True (checked between reads, so breaking never discards a
        frame a sibling consumer could have processed).

        With multiple producer runtimes feeding one queue, stops only once
        EOS markers cover every global shard (:class:`EosTally`); duplicate
        markers destined for sibling consumers are held and returned to
        the queue (never dropped, even against a momentarily full queue)."""
        self._check_connected()
        tally = EosTally()
        try:
            while not (stop is not None and stop()):
                item = self.read_wait(timeout=1.0)
                if item is None:
                    # starved while holding a sibling's marker: put it back
                    # NOW — two consumers each holding the marker the other
                    # needs would otherwise deadlock, both waiting on an
                    # empty queue with flush gated on a successful read.
                    # When we DID return markers, sleep before reading
                    # again: the flush and our next pop share one GIL
                    # slice, so without the yield we snatch our own
                    # marker back before the blocked sibling ever wakes —
                    # the measured 60+ s livelock behind the
                    # test_two_consumers_two_runtimes flake
                    if tally.flush_duplicates(self._queue):
                        time.sleep(0.05)
                    continue
                tally.flush_duplicates(self._queue)  # a slot just freed
                if is_eos(item):
                    if tally.process(item):
                        from psana_ray_tpu.obs.flight import FLIGHT

                        FLIGHT.record("eos_complete", queue=self.queue_name)
                        return
                    continue
                yield item
        finally:
            tally.flush_duplicates(self._queue, final=True)

    def size(self) -> int:
        self._check_connected()
        try:
            return self._queue.size()
        except TransportClosed as e:
            raise DataReaderError(str(e)) from e

    def open_monitor(self):
        """Open an INDEPENDENT queue handle for metrics polling.

        Never hand the data connection to a monitoring thread: over TCP
        the server treats the next opcode on a connection as the implicit
        ACK of that connection's in-flight deliveries (transport.tcp), so
        a ``size()`` probe from a heartbeat thread would confirm frames
        the main thread is still processing and forfeit crash-redelivery.
        A separate connection never GETs, so it has nothing to ACK."""
        return self._open()

    def _check_connected(self):
        if self._queue is None:
            raise DataReaderError("not connected — call connect() or use as context manager")


def main(argv=None):
    """Console consumer — the reference example (``psana_consumer.py:49-55``)
    as an installed entry point, with typed EOS termination."""
    import argparse
    import logging
    import signal
    import threading

    from psana_ray_tpu.utils.hostmem import enable_large_alloc_reuse

    enable_large_alloc_reuse()  # MB-scale frame buffers: heap reuse, no re-faulting
    p = argparse.ArgumentParser(prog="psana-ray-tpu-consumer")
    p.add_argument("consumer_id", type=int, nargs="?", default=0)
    p.add_argument("--ray_address", "--address", dest="address", default="auto")
    p.add_argument("--ray_namespace", "--namespace", dest="namespace", default="default")
    p.add_argument("--queue_name", default="shared_queue")
    p.add_argument(
        "--stream", action="store_true",
        help="subscribe the data connection to server-push streaming "
        "(TCP transports): frames are pushed as they arrive under a "
        "credit window instead of pulled one round trip at a time — "
        "RTT-independent throughput, same at-least-once redelivery",
    )
    p.add_argument(
        "--stream_window", type=int, default=32,
        help="streaming credit window (frames in flight before the "
        "server blocks on this consumer's acks); bounds consumer-side "
        "memory like a prefetch depth",
    )
    p.add_argument(
        "--replay", default=None, metavar="from=<offset|begin|resume>",
        help="durable servers (--durable_dir) only: read the queue's "
        "RETAINED segment-log range non-destructively instead of "
        "competing on the live queue — 'from=begin' replays the "
        "earliest retained record (a new model revision re-reads "
        "yesterday's run), 'from=resume' continues at --replay_group's "
        "committed offset, 'from=<N>' starts at offset N. Live "
        "consumers are undisturbed; progress commits per batch "
        "(at-least-once on crash). Implies pull-mode reads",
    )
    p.add_argument(
        "--replay_group", default="replay",
        help="consumer-group name whose committed offset --replay "
        "advances (a second group, independent of live consumption)",
    )
    p.add_argument("--max_frames", type=int, default=None)
    p.add_argument("--quiet", action="store_true", help="suppress per-frame lines")
    p.add_argument("--log_level", default="INFO")
    p.add_argument(
        "--profile_dir", default=None,
        help="capture a jax.profiler trace of the consume loop into this "
        "directory (view in TensorBoard's Profile tab)",
    )
    p.add_argument(
        "--status_interval", type=float, default=0.0,
        help="log a metrics heartbeat (PipelineMetrics.status_line: "
        "frames/s, Gbit/s, latency quantiles, queue depth) every N "
        "seconds — the consumer-side mirror of the producer's end-of-run "
        "summary; 0 = off",
    )
    from psana_ray_tpu.autotune import add_autotune_args
    from psana_ray_tpu.obs import (
        add_history_args,
        add_metrics_args,
        add_profile_args,
        add_trace_args,
    )
    from psana_ray_tpu.transport.addressing import (
        add_cluster_args,
        add_tenant_args,
        add_wire_args,
    )

    add_metrics_args(p)
    add_trace_args(p)
    add_history_args(p)
    add_profile_args(p)
    add_cluster_args(p, consumer=True)
    add_wire_args(p)
    add_tenant_args(p)
    add_autotune_args(p)
    p.add_argument(
        "--cursor_path", default=None,
        help="persist a StreamCursor (contiguous per-shard watermark of "
        "processed events, checkpoint.py) here; a restarted producer with "
        "the same --cursor_path resumes past it (at-least-once). The "
        "cursor tracks THIS consumer's progress — with multiple competing "
        "consumers give each its own file (resuming a producer from one "
        "consumer's cursor re-produces whatever the others handled: "
        "duplicates, never gaps)",
    )
    p.add_argument(
        "--cursor_stride", type=int, default=1,
        help="total producer shards feeding this stream (the cursor's "
        "watermark arithmetic needs the shard stride; must match the "
        "producer's total_shards)",
    )
    p.add_argument(
        "--cursor_save_every", type=int, default=32,
        help="persist the cursor every N processed frames (and at exit); "
        "<= 0 saves at exit only",
    )
    a = p.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, a.log_level.upper(), logging.INFO),
        format="%(asctime)s - %(levelname)s - %(message)s",
    )
    log = logging.getLogger("consumer")
    from psana_ray_tpu.transport.addressing import (
        apply_cluster_args,
        apply_tenant_args,
        apply_wire_args,
    )

    # --cluster rewrites the address (and carries partitions/group); the
    # DataReader below sees the sharded service as just another address.
    # --wire_codec and --tenant ride the same config into open_queue
    reader_config = apply_tenant_args(
        apply_wire_args(
            apply_cluster_args(TransportConfig(address=a.address), a), a
        ),
        a,
    )
    a.address = reader_config.address

    stop = False

    def _sigint(sig, frame):  # parity: psana_consumer.py:24-26
        nonlocal stop
        stop = True

    signal.signal(signal.SIGINT, _sigint)
    n = 0

    def _should_stop():
        # checked between reads: breaking never discards an already-read
        # frame, and SIGINT exits even while starved (no yield to reach)
        return stop or (a.max_frames is not None and n >= a.max_frames)

    from psana_ray_tpu.utils.trace import trace

    cursor = None
    if a.cursor_path:
        from psana_ray_tpu.checkpoint import StreamCursor

        cursor = StreamCursor.load(a.cursor_path)
        if not cursor.positions:
            cursor.stride = a.cursor_stride
        elif cursor.stride != a.cursor_stride:
            log.error(
                "cursor %s has stride=%d but --cursor_stride=%d; refusing "
                "(wrong stride computes wrong watermarks and can skip data)",
                a.cursor_path, cursor.stride, a.cursor_stride,
            )
            return 1

    # Observability: per-frame counters always (they also feed the final
    # "end of stream" line); the heartbeat thread and the HTTP endpoint
    # only exist when their flags ask for them (zero cost disabled).
    # Started AFTER every early-return validation above, so a refused run
    # never leaks the bound port or the heartbeat thread.
    from psana_ray_tpu.obs import MetricsRegistry, start_metrics_server
    from psana_ray_tpu.obs.stages import STAGE_QUEUE_DWELL
    from psana_ray_tpu.utils.metrics import PipelineMetrics

    metrics = PipelineMetrics()
    observe_dwell = a.status_interval > 0 or a.metrics_port > 0
    MetricsRegistry.default().register("consumer", metrics)
    metrics_server = start_metrics_server(a.metrics_port, host=a.metrics_host)
    # history ring (ISSUE 13): flight-dump tails + /federate consumers
    from psana_ray_tpu.obs import configure_history_from_args, configure_profiling_from_args

    history = configure_history_from_args(a)
    # continuous profiler (ISSUE 16): --profile_hz 0 = off; the spool
    # shares --profile_dir with the jax device trace
    profiler = configure_profiling_from_args(a, "consumer")
    heartbeat_done = threading.Event()
    heartbeat = None
    if a.status_interval > 0:
        from psana_ray_tpu.obs.tracing import obs_status_suffix

        def _heartbeat():
            # the suffix shows tracing is actually ON in a live run:
            # sample rate, spans emitted so far, flight-recorder events
            while not heartbeat_done.wait(a.status_interval):
                log.info(
                    "consumer %d status: %s%s",
                    a.consumer_id, metrics.status_line(), obs_status_suffix(),
                )

        heartbeat = threading.Thread(target=_heartbeat, daemon=True, name="consumer-heartbeat")
        heartbeat.start()

    from psana_ray_tpu.obs.tracing import TRACER, configure_from_args
    from psana_ray_tpu.obs.stages import STAGE_DEQUEUE

    monitor = None
    autotune = None
    try:
        replay_from = None
        if a.replay is not None:
            replay_from = a.replay[5:] if a.replay.startswith("from=") else a.replay
            if replay_from not in ("begin", "resume") and not replay_from.isdigit():
                log.error(
                    "--replay wants from=<offset|begin|resume>, got %r", a.replay
                )
                return 1
        with trace(a.profile_dir), DataReader(
            address=a.address, queue_name=a.queue_name, namespace=a.namespace,
            config=reader_config,
            streaming=a.stream, stream_window=a.stream_window,
            replay_from=replay_from, replay_group=a.replay_group,
        ) as reader:
            if observe_dwell or a.trace_dir:
                # depth in the heartbeat — over a DEDICATED handle, never
                # the data connection (see DataReader.open_monitor: a
                # size() probe there would ACK in-flight deliveries).
                # Tracing reuses the same handle for its clock-anchor
                # exchanges (an anchor RPC on the data connection would
                # ACK in-flight deliveries the same way)
                try:
                    monitor = reader.open_monitor()
                    metrics.attach_queue(monitor)
                except Exception as e:  # noqa: BLE001 — depth is optional
                    log.debug("queue monitor unavailable: %s", e)
            configure_from_args(a, "consumer", queue=monitor)
            # autotune (ISSUE 15): consumer-side knobs — the stream
            # credit window (when --stream subscribed), the wire codec
            # on pull-mode connections, and the recv-pool retention
            # floor — judged by the measured consume rate. An explicit
            # --stream_window / --wire_codec pins its knob.
            if a.autotune != "off":
                from psana_ray_tpu.autotune import (
                    Objective,
                    configure_autotune_from_args,
                )
                from psana_ray_tpu.autotune.knobs import (
                    bufpool_retention_knob,
                    stream_window_knob,
                    wire_codec_knob,
                )
                from psana_ray_tpu.utils.bufpool import BufferPool

                knobs = [bufpool_retention_knob(BufferPool.default())]
                pinned = {}
                if a.stream:
                    knobs.append(stream_window_knob(reader.queue))
                    if a.stream_window != p.get_default("stream_window"):
                        pinned["stream_window"] = "--stream_window set explicitly"
                else:
                    # a streamed connection's codec is decided at
                    # (re)connect; only pull-mode renegotiates live
                    knobs.append(wire_codec_knob(reader.queue))
                    # an explicit name AND an explicit "none" are both
                    # operator decisions ("auto" delegates)
                    if a.wire_codec and a.wire_codec != "auto":
                        pinned["wire_codec_on"] = "--wire_codec set explicitly"
                autotune = configure_autotune_from_args(
                    a, knobs, Objective("consumer.frames_total"), pinned=pinned
                )
            try:
                for rec in reader.iter_records(stop=_should_stop):
                    t_rec = time.monotonic()
                    n += 1
                    metrics.observe_frame(rec.nbytes)
                    if observe_dwell and rec.timestamp:
                        # wall-clock dwell (producer stamp -> this read):
                        # exact same-host, approximate cross-host (NTP).
                        # A sampled frame's trace id rides the bucket as
                        # its exemplar (trace_merge --exemplar, ISSUE 13)
                        _tr = rec.trace
                        metrics.stages.observe(
                            STAGE_QUEUE_DWELL,
                            max(0.0, time.time() - rec.timestamp),
                            exemplar=_tr.trace_id
                            if _tr is not None and _tr.sampled else None,
                        )
                    if not a.quiet:
                        log.info(
                            "consumer %d: rank=%d idx=%d shape=%s energy=%.2f",
                            a.consumer_id, rec.shard_rank, rec.event_idx,
                            rec.panels.shape, rec.photon_energy,
                        )
                    if cursor is not None:
                        # advance AFTER the record is fully handled: the
                        # watermark must never run ahead of processing.
                        # ValueError = stride/shard misconfiguration —
                        # surfaced immediately, not after a wasted run
                        cursor.advance(rec.shard_rank, rec.event_idx)
                        if a.cursor_save_every > 0 and n % a.cursor_save_every == 0:
                            cursor.save(a.cursor_path)
                    rec_trace = rec.trace
                    if rec_trace is not None and rec_trace.sampled and TRACER.enabled:
                        # consumer-side span: read done -> record fully
                        # handled (log + cursor) — strictly after the
                        # server's relay span on the merged timeline
                        TRACER.span(
                            rec_trace.trace_id, STAGE_DEQUEUE,
                            t_rec, time.monotonic(),
                        )
            finally:
                if cursor is not None:
                    cursor.save(a.cursor_path)
        log.info(
            "consumer %d: end of stream after %d frames (%s)",
            a.consumer_id, n, metrics.status_line(),
        )
    except DataReaderError as e:  # parity: psana_consumer.py:41-44
        log.error("consumer %d: queue is dead (%s); exiting", a.consumer_id, e)
        return 1
    except ValueError as e:  # cursor stride/shard misconfiguration
        log.error("consumer %d: %s", a.consumer_id, e)
        return 1
    finally:
        heartbeat_done.set()
        if autotune is not None:
            autotune.stop()
        if history is not None:
            history.stop()
        if heartbeat is not None:
            heartbeat.join(timeout=1.0)
        metrics.attach_queue(None)  # monitor handle is about to die
        if monitor is not None and hasattr(monitor, "disconnect"):
            try:
                monitor.disconnect()
            except Exception:  # noqa: BLE001 — already closing
                pass
        if metrics_server is not None:
            metrics_server.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
