"""DurableRingBuffer: the log-backed RingBuffer variant.

Drop-in for :class:`~psana_ray_tpu.transport.ring.RingBuffer` anywhere
the transport mounts a queue (the event-loop TCP server's default and
OPENed named queues under ``--durable_dir``). Semantics added on top of
the base contract:

- **Every put is logged first.** ``_box`` appends the record to the
  :class:`~psana_ray_tpu.storage.log.SegmentLog` (one ``encode_into``
  memcpy into the mmap'd segment — the same encode-into-slot plumbing
  the shm ring uses, no intermediate bytes) and the assigned offset
  rides the queue entry.
- **Bounded spill.** While the RAM-resident count fits ``ram_items``
  the item itself stays queued (delivery is the usual zero-copy path);
  beyond that the RAM copy is RELEASED (its pooled lease returns to the
  BufferPool immediately — a deep queue must not pin the pool) and the
  entry spills: delivery re-reads the record from the log.
- **Committed offsets.** Delivery tracks each popped item as
  OUTSTANDING until :meth:`ack_delivered` (the event-loop server calls
  it at exactly its implicit-ACK points); the committed floor — the
  highest offset below every queued/outstanding record — is persisted
  through the log. A restart re-exposes exactly ``(floor, tail]``:
  crash-redelivery across process death is "rewind to the last
  committed offset", not "whatever RAM remembered" (which is nothing).
  ``commit_on_get=True`` restores memory-only semantics (commit at
  delivery) for direct in-process consumers that never ack.
- **Replay.** :meth:`open_replay` hands out a non-destructive
  :class:`~psana_ray_tpu.storage.log.ReplayCursor` over the retained
  range for a named consumer group — a second group re-reads
  yesterday's stream without disturbing live consumers.

``put_front`` (the transport's requeue-at-head recovery path)
reinstates a still-outstanding item under its ORIGINAL offset — no
duplicate log append, and the floor stays pinned below it.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.storage.log import ReplayCursor, SegmentLog
from psana_ray_tpu.storage.telemetry import DURABLE
from psana_ray_tpu.transport.ring import RingBuffer


class _Entry:
    """Stored form of one queued record: its log offset plus the RAM
    copy (None when spilled — delivery re-reads the log)."""

    __slots__ = ("offset", "item")

    def __init__(self, offset: int, item: Any):
        self.offset = offset
        self.item = item


class SpilledRecord:
    """A delivered spilled record that has NOT been read into the
    interpreter (``lazy_spill`` queues only).

    The evloop server never interprets queue items — it frames and
    relays them — so delivery can hand it this handle instead of the
    decoded record: the kernel pass-through path asks
    :meth:`payload_span` for a (file, pos, nbytes) sendfile span and
    the payload bytes go mmap->socket without a Python copy;
    :meth:`materialize` is the fallback (compressed connection, no
    sendfile) and behaves exactly like the eager ``log.read``.

    Identity-stable on purpose: every delivery contract in the server
    is keyed by ``id(item)`` (``_outstanding``, ``_box_front`` requeue,
    stream unacked tails, in-flight ack), and while this object is
    outstanding the commit floor stays pinned at or below ``offset`` —
    which is precisely what keeps the span's segment from being
    recycled mid-send (see ``SegmentLog.payload_span``).
    """

    __slots__ = ("log", "offset", "_item")

    def __init__(self, log: SegmentLog, offset: int):
        self.log = log
        self.offset = offset
        self._item = None

    def payload_span(self):
        """``(file, file_pos, nbytes)`` of the raw tagged payload, or
        None (offset no longer retained — caller materializes)."""
        return self.log.payload_span(self.offset)

    def materialize(self) -> Any:
        """Decode the record (cached): the copying path, for consumers
        that need the bytes in Python after all."""
        if self._item is None:
            DURABLE.spill_read()
            self._item = self.log.read(self.offset)
        return self._item


class DurableRingBuffer(RingBuffer):
    def __init__(
        self,
        log: SegmentLog,
        maxsize: int = 100,
        name: str = "durable_queue",
        ram_items: Optional[int] = None,
        commit_on_get: bool = False,
        lazy_spill: bool = False,
    ):
        super().__init__(maxsize=maxsize, name=name)
        self.log = log
        self.ram_items = int(ram_items) if ram_items else int(maxsize)
        self.commit_on_get = commit_on_get
        # lazy_spill: deliver spilled entries as SpilledRecord handles
        # instead of eagerly decoding (the evloop server's kernel
        # pass-through). Only meaningful with ack-based commits: a
        # commit-on-get consumer lets the floor pass the offset before
        # the handle is read, so that mode stays eager.
        self.lazy_spill = bool(lazy_spill) and not commit_on_get
        self._resident = 0  # RAM-held entries in _q  # guarded-by: _lock
        self._spilled = 0  # log-only entries in _q  # guarded-by: _lock
        # delivered-but-unacked: id(item) -> entry. Strong item refs on
        # purpose — they pin the id()s against reuse AND keep the floor
        # honest until the ack (or put_front) resolves each delivery.
        self._outstanding: dict = {}  # guarded-by: _lock
        self._floor = log.committed("")  # guarded-by: _lock
        DURABLE.ensure_registered()
        self._reexpose()

    # -- recovery ----------------------------------------------------------
    def _reexpose(self) -> None:
        """Boot: everything the log retains above the committed floor is
        unconsumed — queue it (spilled; reads hydrate from the log).
        Depth may exceed maxsize here, exactly like put_front: the
        records were admitted in a previous life."""
        with self._lock:
            offsets = self.log.offsets_after(self._floor)
            if not offsets:
                return
            for off in offsets:
                self._q.append(_Entry(off, None))
            self._spilled += len(offsets)
            if len(self._q) > self._high_water:
                self._high_water = len(self._q)
            self._not_empty.notify_all()
            self._notify_listeners()
        DURABLE.spill_delta(len(offsets))
        FLIGHT.record(
            "durable_reexpose", queue=self.name, records=len(offsets),
            from_offset=offsets[0], to_offset=offsets[-1],
        )

    # -- storage hooks (see RingBuffer._box/_unbox) ------------------------
    def _box(self, item: Any) -> Any:
        # guarded-by-caller: _lock
        offset = self.log.append(item)
        if self._resident < self.ram_items:
            self._resident += 1
            return _Entry(offset, item)
        # spill: the log holds the bytes; release the RAM copy's pooled
        # lease NOW (a deep durable queue must not pin the BufferPool)
        if self._spilled == 0:
            FLIGHT.record("spill_enter", queue=self.name, depth=len(self._q))
        self._spilled += 1
        DURABLE.spill_delta(1)
        release = getattr(item, "release", None)
        if release is not None:
            release()
        return _Entry(offset, None)

    def _box_front(self, item: Any) -> Any:
        """Head re-insertion: an OUTSTANDING item comes back under its
        original offset (no new log append — the floor never advanced
        past it); anything else (e.g. a sibling EOS marker flushed back,
        or a materialized copy) is a fresh logged record."""
        # guarded-by-caller: _lock
        entry = self._outstanding.pop(id(item), None)
        if entry is not None:
            entry.item = item
            self._resident += 1
            return entry
        offset = self.log.append(item)
        self._resident += 1
        return _Entry(offset, item)

    def _unbox(self, stored: Any) -> Any:
        # guarded-by-caller: _lock
        entry: _Entry = stored
        if entry.item is None:
            if self.lazy_spill:
                # no read, no copy: the handle carries the offset and
                # the evloop moves the payload kernel-side (or
                # materializes — which is when spill_read is counted)
                entry.item = SpilledRecord(self.log, entry.offset)
            else:
                DURABLE.spill_read()
                entry.item = self.log.read(entry.offset)
            self._spilled -= 1
            if self._spilled == 0:
                FLIGHT.record("spill_exit", queue=self.name)
        else:
            self._resident -= 1
        item = entry.item
        if self.commit_on_get:
            # immediate commit (memory-only delivery semantics): floor is
            # still min-pending-based — a head-requeued FRESH item carries
            # a high offset at the queue head, so committing this entry's
            # own offset could leap past unconsumed records. The entry
            # being delivered is excluded: unboxing runs BEFORE the pop
            # (transactional get), so it still sits in _q here.
            self._commit_floor(exclude=entry)
        else:
            self._outstanding[id(item)] = entry
        return item

    def set_ram_items(self, n: int) -> None:
        """Live spill-threshold dial (ISSUE 15 autotune): RAM-resident
        records admitted before new puts spill to log-only entries.
        Applies to FUTURE puts — shrinking never evicts already-resident
        entries (they drain through delivery), so the transition is
        monotone and alloc-free."""
        with self._lock:
            self.ram_items = max(1, int(n))

    # -- replicated ack floor support (ISSUE 11) ---------------------------
    def put(self, item: Any) -> bool:
        """One admission implementation: :meth:`put_offset` is the
        primitive (the event-loop's replicated-ack-floor gate needs the
        offset); ``put`` is its offset-discarding face."""
        return self.put_offset(item)[0]

    def put_offset(self, item: Any):
        """``put`` that also reports the appended record's log offset —
        the event-loop server's replicated-ack-floor gate needs it to
        hold the producer's ack until the follower has logged exactly
        this record. Returns ``(ok, offset)``; ``(False, None)`` when
        full."""
        with self._lock:
            self._check_open()
            self._check_accepting()
            if len(self._q) >= self.maxsize:
                self._n_put_rejected += 1
                return False, None
            entry = self._box(item)
            self._q.append(entry)
            self._note_put()
            self._not_empty.notify()
            return True, entry.offset

    @property
    def committed_floor(self) -> int:
        """The live committed floor — piggybacked on replica appends so
        a promoted follower re-exposes only ``(floor, tail]``."""
        with self._lock:
            return self._floor

    # -- committed offsets -------------------------------------------------
    def ack_delivered(self, items) -> int:
        """The delivery of ``items`` is confirmed (the event-loop server
        calls this at its implicit-ACK points: next-opcode, stream
        cumulative ack, clean BYE). Advances and persists the committed
        floor. Unknown items (already acked, or not from this queue) are
        ignored. Returns the new floor."""
        with self._lock:
            changed = False
            for item in items:
                if self._outstanding.pop(id(item), None) is not None:
                    changed = True
            if changed:
                self._commit_floor()
            return self._floor

    def _commit_floor(self, exclude=None) -> None:
        """floor = (lowest offset still queued or outstanding) - 1; when
        nothing is pending, everything assigned is consumed. O(depth) —
        called per ack batch, bounded by maxsize."""
        # guarded-by-caller: _lock
        pending = [e.offset for e in self._q if e is not exclude]
        pending.extend(e.offset for e in self._outstanding.values())
        floor = (min(pending) - 1) if pending else (self.log.next_offset - 1)
        self._advance_floor_to(floor)

    def _advance_floor_to(self, floor: int) -> None:
        # guarded-by-caller: _lock
        if floor > self._floor:
            self._floor = floor
            self.log.commit(floor, "")

    def commit_offset(self, offset: int, group: str) -> bool:
        """Explicit offset commit for a NAMED group (the 'J' opcode's
        backing; the live floor is group ``""`` and owned by acks)."""
        if not group:
            return False
        return self.log.commit(offset, group)

    # -- replay ------------------------------------------------------------
    def open_replay(self, group: str, requested: int) -> ReplayCursor:
        """A non-destructive cursor over the retained range for
        ``group`` (position sentinels: storage.log.REPLAY_BEGIN /
        REPLAY_RESUME). Live consumption is untouched."""
        start = self.log.resolve_start(requested, group)
        return ReplayCursor(self.log, group, start)

    # -- lifecycle / observability ----------------------------------------
    def close(self):
        super().close()
        try:
            self.log.sync()
        except (RuntimeError, OSError):
            pass  # log already closed / disk fault already breadcrumbed

    def stats(self) -> dict:
        out = super().stats()
        with self._lock:
            out.update(
                durable=True,
                spilled=self._spilled,
                resident=self._resident,
                outstanding=len(self._outstanding),
                committed_offset=self._floor,
                log=self.log.stats(),
            )
        return out
