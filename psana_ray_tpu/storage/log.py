"""SegmentLog: an offset-addressed, append-only record log over a ring
of recycled mmap'd segments, with committed offsets and crash recovery.

One SegmentLog backs one queue (``DurableRingBuffer``). Records are
assigned monotonically increasing offsets at append; consumers'
positions are COMMITTED OFFSETS persisted in a small sidecar store, so
a restart re-exposes exactly the ``(committed, tail]`` range —
at-least-once across process death: duplicates possible (anything
delivered after the last commit redelivers), holes never, loss never.

Layout of the log directory::

    seg-<base_offset>.seg     pre-allocated mmap'd segments (storage.segment)
    offsets.jsonl             committed offsets per consumer group (appended
                              JSON lines, compacted in place when large; a
                              torn final line from a crash is ignored)

``fsync`` policy (the classic durability/throughput dial):

- ``none``   — never fsync. Survives PROCESS death (kill -9): the
  mmap'd writes live in page cache, which outlives the process. A
  MACHINE crash may lose the un-flushed tail — the producer-side
  windowed-put retention (PR 5/7) is the backstop there.
- ``batch``  — fsync the active segment every ``fsync_batch_n``
  appends, on segment roll, and on every commit. Bounds machine-crash
  loss to one batch.
- ``always`` — fsync after every append. The measured-overhead row in
  the bench exists so nobody picks this by accident.

Retention: segments whose every record sits below the LIVE committed
floor (group ``""`` — the queue's own consumption cursor) are kept
until more than ``retain_segments`` sealed segments of consumed
history exist, then recycled (reset + renamed to the new tail,
DALI-style, never deleted/reallocated). Unconsumed records are NEVER
recycled regardless of count — loss never — so disk usage is bounded
by (queued backlog + retain_segments of replayable history).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.storage.segment import (
    Segment,
    parse_base_offset,
    record_nbytes,
    segment_filename,
)
from psana_ray_tpu.storage.telemetry import DURABLE
from psana_ray_tpu.transport.codec import decode_payload

FSYNC_NONE = "none"
FSYNC_BATCH = "batch"
FSYNC_ALWAYS = "always"
FSYNC_POLICIES = (FSYNC_NONE, FSYNC_BATCH, FSYNC_ALWAYS)

DEFAULT_SEGMENT_BYTES = 64 * 1024 * 1024
DEFAULT_RETAIN_SEGMENTS = 8
DEFAULT_FSYNC_BATCH_N = 64

# replay_open() position sentinels (also u64-encoded on the wire, 'R'):
REPLAY_BEGIN = (1 << 64) - 1  # earliest retained offset
REPLAY_RESUME = (1 << 64) - 2  # this group's committed offset + 1

# commit_offset() sentinel ('J'): commit everything the server has
# DELIVERED to this connection's replay cursor so far (the client never
# learns raw offsets; delivery order is the shared truth)
COMMIT_DELIVERED = (1 << 64) - 1

_OFFSETS_FILE = "offsets.jsonl"
_OFFSETS_COMPACT_BYTES = 64 * 1024
# recycled-but-unneeded segments kept mapped for reuse before they are
# truly unlinked — the free list that makes a roll an O(1) rename
_FREE_SEGMENTS_MAX = 2

# Patchable disk-fault hook (tests/faultproxy.DiskFaultInjector): called
# with the op name ("append"/"sync") before the segment write or flush;
# raising OSError simulates a failing/full disk. The log degrades LOUDLY
# on it — DURABLE counter + flight breadcrumb + the OSError surfacing to
# the caller (the event-loop server answers the producer 'E') — instead
# of wedging or killing the serving loop.
_DISK_FAULT_HOOK = None


def set_disk_fault_hook(hook) -> None:
    """Install (or clear, with None) the process-wide disk-fault hook."""
    global _DISK_FAULT_HOOK
    _DISK_FAULT_HOOK = hook


def _disk_fault_check(op: str) -> None:
    hook = _DISK_FAULT_HOOK
    if hook is not None:
        hook(op)


class SegmentLog:
    """See module docstring. Thread-safe behind one lock."""

    def __init__(
        self,
        dirpath: str,
        segment_bytes: int = DEFAULT_SEGMENT_BYTES,
        retain_segments: int = DEFAULT_RETAIN_SEGMENTS,
        fsync: str = FSYNC_BATCH,
        fsync_batch_n: int = DEFAULT_FSYNC_BATCH_N,
        name: str = "queue",
    ):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync must be one of {FSYNC_POLICIES}, got {fsync!r}"
            )
        self.dir = dirpath
        self.name = name
        self.segment_bytes = int(segment_bytes)
        self.retain_segments = max(1, int(retain_segments))
        self.fsync = fsync
        self.fsync_batch_n = max(1, int(fsync_batch_n))
        self._lock = threading.RLock()
        self._segments: List[Segment] = []  # oldest..active  # guarded-by: _lock
        self._free: List[Segment] = []  # recycled, awaiting reuse  # guarded-by: _lock
        self._committed: Dict[str, int] = {}  # group -> offset  # guarded-by: _lock
        self._next_offset = 0  # guarded-by: _lock
        self._appends_since_sync = 0  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self.torn_tail_repaired = False
        self._free_id = 0  # guarded-by: _lock
        os.makedirs(dirpath, exist_ok=True)
        with self._lock:  # no peer can hold the object yet; keeps the
            self._recover()  # guarded-by annotations honest
        DURABLE.ensure_registered()

    # -- recovery ----------------------------------------------------------
    def _recover(self) -> None:
        """Boot scan: load committed offsets, walk every segment file in
        base-offset order validating records, repair a torn tail by
        truncation, and resume appends after the last valid record."""
        # guarded-by-caller: _lock
        t0 = time.monotonic()
        self._committed = _load_offsets(os.path.join(self.dir, _OFFSETS_FILE))
        for n in os.listdir(self.dir):
            # a crash can leave retired (scrubbed, renamed) segments on
            # the free list's namespace; they hold nothing — drop them
            if n.startswith("free-") and n.endswith(".seg"):
                try:
                    os.unlink(os.path.join(self.dir, n))
                except OSError:
                    pass
        names = sorted(
            n for n in os.listdir(self.dir) if parse_base_offset(n) is not None
        )
        torn = False
        records = 0
        next_offset = 0
        for fname in names:
            base = parse_base_offset(fname)
            if not self._segments:
                next_offset = base
            seg = Segment.open_existing(os.path.join(self.dir, fname), base)
            try:
                seg_next, seg_torn = seg.scan(next_offset)
            except BaseException:
                # a scan failure mid-recovery must not strand the
                # mapping: close before propagating (the caller decides
                # whether recovery as a whole survives)
                seg.close()
                raise
            torn = torn or seg_torn
            records += len(seg.index)
            next_offset = seg_next
            if not seg.index and len(names) > 1 and fname != names[-1]:
                # an empty non-tail segment (e.g. created then never
                # written before the crash): recycle it rather than
                # carrying a hole in the ring
                seg.close()
                os.unlink(seg.path)
                continue
            self._segments.append(seg)
        self._next_offset = next_offset
        if not self._segments:
            self._segments.append(self._new_segment(self._next_offset))
        ms = (time.monotonic() - t0) * 1000.0
        self.torn_tail_repaired = torn
        DURABLE.recovered(ms, records, torn)
        if records or torn:
            FLIGHT.record(
                "recovery_scan", log=self.name, records=records,
                next_offset=self._next_offset, torn_tail=torn,
                ms=round(ms, 3),
            )
        if torn:
            FLIGHT.record(
                "torn_tail_repair", log=self.name,
                truncated_at_offset=self._next_offset,
            )

    # -- segment ring ------------------------------------------------------
    def _new_segment(self, base_offset: int) -> Segment:
        # guarded-by-caller: _lock
        path = os.path.join(self.dir, segment_filename(base_offset))
        if self._free:
            seg = self._free.pop()
            seg.reset(base_offset, path)
            DURABLE.rolled(recycled=True)
            return seg
        DURABLE.rolled(recycled=False)
        return Segment.allocate(path, self.segment_bytes, base_offset)

    def _roll(self) -> Segment:
        # guarded-by-caller: _lock
        active = self._segments[-1]
        if self.fsync != FSYNC_NONE:
            active.sync()
            DURABLE.fsynced()
        seg = self._new_segment(self._next_offset)
        self._segments.append(seg)
        FLIGHT.record(
            "segment_rollover", log=self.name, base_offset=self._next_offset,
            segments=len(self._segments),
        )
        self._maybe_recycle()
        return seg

    def _maybe_recycle(self) -> None:
        """Recycle fully consumed history beyond the retention window.
        Only the LIVE cursor's committed floor gates this: unconsumed
        records are never recycled (loss never); named replay groups
        read best-effort within the retained window."""
        # guarded-by-caller: _lock
        floor = self._committed.get("", -1)
        while len(self._segments) > self.retain_segments + 1:
            seg = self._segments[0]
            last = seg.last_offset
            if last is None or last > floor:
                break
            self._segments.pop(0)
            if len(self._free) < _FREE_SEGMENTS_MAX:
                self._free_id += 1
                seg.retire(
                    os.path.join(self.dir, f"free-{self._free_id}.seg")
                )
                self._free.append(seg)
            else:
                seg.close()
                os.unlink(seg.path)

    def set_fsync_batch_n(self, n: int) -> None:
        """Live fsync-batching dial (ISSUE 15 autotune): appends per
        fsync under the ``batch`` policy. The pending-appends counter is
        untouched, so a shrink takes effect at the very next append and
        a grow simply stretches the current batch — durability
        semantics (what a machine crash can lose) scale with the value,
        exactly as the ``--fsync_batch_n`` flag documents."""
        with self._lock:
            self.fsync_batch_n = max(1, int(n))

    # -- append ------------------------------------------------------------
    def append(self, item) -> int:
        """Append one record; returns its assigned offset."""
        need = self._check_fits(item)
        with self._lock:
            self._check_open()
            offset = self._next_offset
            self._append_locked(offset, item, need)
            self._next_offset = offset + 1
            return offset

    def append_at(self, offset: int, item) -> int:
        """Append one record under an EXPLICIT offset — the replica path
        (ISSUE 11): a follower mirrors the owner's offset space so a
        promoted replica serves the same addresses. ``offset`` must equal
        the tail; the caller reconciles divergence first
        (:meth:`truncate_to` / :meth:`reset_to`)."""
        need = self._check_fits(item)
        with self._lock:
            self._check_open()
            if offset != self._next_offset:
                raise ValueError(
                    f"append_at out of order: offset {offset} vs tail "
                    f"{self._next_offset} (reconcile with truncate_to/"
                    f"reset_to first)"
                )
            self._append_locked(offset, item, need)
            self._next_offset = offset + 1
            return offset

    def _check_fits(self, item) -> int:
        need = record_nbytes(item)
        if need > self.segment_bytes:
            raise ValueError(
                f"record of {need} framed bytes exceeds segment_bytes="
                f"{self.segment_bytes}"
            )
        return need

    def _append_locked(self, offset: int, item, need: int) -> None:
        # guarded-by-caller: _lock
        try:
            _disk_fault_check("append")
            seg = self._segments[-1]
            if seg.append(offset, item) is None:
                seg = self._roll()
                if seg.append(offset, item) is None:
                    raise RuntimeError(
                        f"record did not fit a fresh segment ({need} bytes)"
                    )
            DURABLE.appended(need)
            if self.fsync == FSYNC_ALWAYS:
                seg.sync()
                DURABLE.fsynced()
            elif self.fsync == FSYNC_BATCH:
                self._appends_since_sync += 1
                if self._appends_since_sync >= self.fsync_batch_n:
                    self._appends_since_sync = 0
                    seg.sync()
                    DURABLE.fsynced()
        except OSError as e:
            # a failing/full disk degrades LOUDLY: counter + breadcrumb
            # + the exception surfacing as THIS append's failure (the
            # event-loop server answers the producer 'E' and lives on)
            DURABLE.disk_faulted()
            FLIGHT.record(
                "disk_fault", log=self.name, op="append", error=repr(e)
            )
            raise

    # -- replica reconciliation (ISSUE 11) ---------------------------------
    def truncate_to(self, offset: int) -> None:
        """Discard every record with offset >= ``offset`` so the next
        append lands there. The follower's torn-tail sibling: after an
        owner reconnect, the owner's view of the unacknowledged suffix
        WINS — the replica rewinds and the overwriting appends (and any
        later recovery scan) see a clean end. Committed floors are
        untouched (monotonic, and always at or below the acked range)."""
        with self._lock:
            self._check_open()
            if offset >= self._next_offset:
                return
            if offset <= self.first_retained_offset():
                self._reset_locked(offset)
            else:
                while self._segments:
                    seg = self._segments[-1]
                    first = seg.first_offset
                    if first is not None and first < offset:
                        pos = seg.find(offset)
                        if pos is not None:
                            seg.truncate_from(pos)
                        break
                    # the whole tail segment goes (including empty ones)
                    self._segments.pop()
                    seg.close()
                    os.unlink(seg.path)
                if not self._segments:
                    self._segments.append(self._new_segment(offset))
                self._next_offset = offset
            DURABLE.truncated()
        FLIGHT.record("replica_truncate", log=self.name, to_offset=offset)

    def reset_to(self, offset: int) -> None:
        """Forget everything and restart the offset space at ``offset``
        (the owner's earliest shippable record lies beyond our tail — a
        contiguous local copy is impossible, so the replica restarts
        there; loudly breadcrumbed, consumed-history-only by the owner's
        retention contract)."""
        with self._lock:
            self._check_open()
            self._reset_locked(offset)
        FLIGHT.record("replica_reset", log=self.name, to_offset=offset)

    def _reset_locked(self, offset: int) -> None:
        # guarded-by-caller: _lock
        for seg in self._segments:
            seg.close()
            os.unlink(seg.path)
        self._segments = []
        self._segments.append(self._new_segment(offset))
        self._next_offset = offset

    # -- read --------------------------------------------------------------
    def read(self, offset: int):
        """Decode the record at ``offset``. The returned item OWNS its
        data (panels copied out of the mmap — a spilled record's segment
        may be recycled once consumption passes it, so views must not
        escape the lock)."""
        with self._lock:
            self._check_open()
            seg = self._find_segment(offset)
            if seg is None:
                raise KeyError(
                    f"offset {offset} is not retained (earliest "
                    f"{self.first_retained_offset()}, next {self._next_offset})"
                )
            pos = seg.find(offset)
            if pos is None:
                raise KeyError(f"offset {offset} missing from {seg!r}")
            mv = seg.payload_at(pos)
            try:
                return decode_payload(mv)
            finally:
                mv.release()

    def payload_span(self, offset: int):
        """The record's on-disk payload as a sendfile span: a
        :class:`~psana_ray_tpu.transport.splice.FileSpan`-shaped tuple
        ``(file, file_pos, nbytes)``, or None when the offset is not
        retained. Unlike :meth:`read`, NOTHING is copied — the caller
        (the evloop's kernel pass-through) moves the bytes file->socket
        without the interpreter touching them. Safe only for a record
        whose delivery pins the commit floor at or below ``offset``
        (the durable queue's ``_outstanding`` contract): that pin is
        what keeps ``_maybe_recycle`` from retiring the segment while
        the span is queued. Replay cursors have no such pin and must
        stay on the copying :meth:`read` path."""
        with self._lock:
            self._check_open()
            seg = self._find_segment(offset)
            if seg is None:
                return None
            pos = seg.find(offset)
            if pos is None:
                return None
            return seg.payload_extent(pos)

    def _find_segment(self, offset: int) -> Optional[Segment]:
        # guarded-by-caller: _lock
        for seg in reversed(self._segments):
            first = seg.first_offset
            if first is not None and first <= offset:
                last = seg.last_offset
                return seg if last is not None and offset <= last else None
        return None

    def offsets_after(self, floor: int) -> List[int]:
        """Every retained offset strictly above ``floor`` — the
        unconsumed range a recovering queue re-exposes."""
        with self._lock:
            out: List[int] = []
            for seg in self._segments:
                out.extend(off for (off, _pos) in seg.index if off > floor)
            return out

    # -- offsets -----------------------------------------------------------
    def committed(self, group: str = "") -> int:
        with self._lock:
            return self._committed.get(group, -1)

    def commit(self, offset: int, group: str = "") -> bool:
        """Persist ``group``'s committed offset (monotonic: a stale
        commit is a no-op). Returns True when the floor advanced."""
        with self._lock:
            self._check_open()
            cur = self._committed.get(group, -1)
            if offset <= cur:
                return False
            self._committed[group] = offset
            _append_offset(
                os.path.join(self.dir, _OFFSETS_FILE), group, offset,
                self._committed, durable=self.fsync != FSYNC_NONE,
            )
            DURABLE.committed()
            if not group:
                self._maybe_recycle()
            return True

    def first_retained_offset(self) -> int:
        """Earliest offset still readable (``replay from=begin``);
        equals next_offset when the log holds nothing."""
        with self._lock:
            for seg in self._segments:
                first = seg.first_offset
                if first is not None:
                    return first
            return self._next_offset

    @property
    def next_offset(self) -> int:
        with self._lock:
            return self._next_offset

    def resolve_start(self, requested: int, group: str = "") -> int:
        """Map a replay-open position (offset or sentinel) onto the
        retained range: ``REPLAY_BEGIN`` -> earliest retained,
        ``REPLAY_RESUME`` -> the group's committed offset + 1, an
        explicit offset is clamped into the retained range."""
        with self._lock:
            earliest = self.first_retained_offset()
            if requested == REPLAY_BEGIN:
                return earliest
            if requested == REPLAY_RESUME:
                return max(self._committed.get(group, -1) + 1, earliest)
            return min(max(int(requested), earliest), self._next_offset)

    # -- lifecycle ---------------------------------------------------------
    def sync(self) -> None:
        with self._lock:
            if self._closed:
                return
            try:
                _disk_fault_check("sync")
                self._segments[-1].sync()
            except OSError as e:
                DURABLE.disk_faulted()
                FLIGHT.record(
                    "disk_fault", log=self.name, op="sync", error=repr(e)
                )
                raise
            DURABLE.fsynced()
            self._appends_since_sync = 0

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for seg in self._segments + self._free:
                try:
                    seg.sync()
                except (ValueError, OSError):
                    pass
                seg.close()
            self._segments = []
            self._free = []

    def _check_open(self):
        # guarded-by-caller: _lock
        if self._closed:
            raise RuntimeError(f"segment log {self.name!r} is closed")

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "next_offset": self._next_offset,
                "first_retained_offset": self.first_retained_offset()
                if self._segments
                else self._next_offset,
                "committed": dict(self._committed),
                "segments": len(self._segments),
                "free_segments": len(self._free),
                "segment_bytes": self.segment_bytes,
                "fsync": self.fsync,
                "torn_tail_repaired": self.torn_tail_repaired,
            }


class ReplayCursor:
    """A non-destructive reader over a log's retained range for one
    consumer group: live consumers are undisturbed (nothing is popped),
    and the cursor follows the tail — a replay of a finished stream
    terminates naturally on the logged EndOfStream markers. Commit via
    :meth:`commit` persists the group's position; crash-redelivery is
    re-open at ``REPLAY_RESUME``."""

    def __init__(self, log: SegmentLog, group: str, start: int):
        self.log = log
        self.group = group
        self.position = start  # next offset to read
        self.delivered = start - 1  # last offset handed out
        DURABLE.replay_opened()
        FLIGHT.record(
            "replay_open", log=log.name, group=group, start=start,
            end=log.next_offset,
        )

    def next_batch(self, max_items: int) -> list:
        out = []
        while len(out) < int(max_items):
            with self.log._lock:
                if self.log._closed:
                    break
                tail = self.log._next_offset
                if self.position >= tail:
                    break
                earliest = self.log.first_retained_offset()
                if self.position < earliest:
                    # retention passed us while we lagged: skip forward
                    # (consumed history only — never unconsumed records)
                    FLIGHT.record(
                        "replay_gap", log=self.log.name, group=self.group,
                        skipped_from=self.position, resumed_at=earliest,
                    )
                    self.position = earliest
                    continue
                try:
                    item = self.log.read(self.position)
                except KeyError:
                    self.position += 1
                    continue
            out.append(item)
            self.delivered = self.position
            self.position += 1
        return out

    def caught_up(self) -> bool:
        return self.position >= self.log.next_offset

    def commit(self, through: Optional[int] = None) -> bool:
        """Persist the group's position (default: everything delivered)."""
        through = self.delivered if through is None else through
        if through < 0:
            return False
        return self.log.commit(through, self.group)


# -- committed-offset sidecar store -----------------------------------------
def _load_offsets(path: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    try:
        with open(path, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    group, off = rec["g"], int(rec["o"])
                except (ValueError, KeyError, TypeError):
                    continue  # torn final line from a crash: ignore
                if off > out.get(group, -1):
                    out[group] = off
    except FileNotFoundError:
        pass
    return out


def _append_offset(
    path: str, group: str, offset: int, current: Dict[str, int], durable: bool
) -> None:
    """Append one commit line; compact (atomic rewrite of the latest
    per-group map) when the file grows past the threshold."""
    line = json.dumps({"g": group, "o": offset}) + "\n"
    try:
        size = os.path.getsize(path)
    except OSError:
        size = 0
    if size > _OFFSETS_COMPACT_BYTES:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for g, o in sorted(current.items()):
                f.write(json.dumps({"g": g, "o": o}) + "\n")
            if durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        return
    with open(path, "a") as f:
        f.write(line)
        if durable:
            f.flush()
            os.fsync(f.fileno())
