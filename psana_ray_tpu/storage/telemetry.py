"""Durability gauges: the ``durable`` obs source.

One process-wide instance (:data:`DURABLE`) shared by every SegmentLog /
DurableRingBuffer in the process, registered in the default
MetricsRegistry on first durable use — the same self-registration
pattern as the stream and evloop sources, so ``--metrics_port`` and the
bench artifact pick it up with zero wiring.
"""

from __future__ import annotations

import threading


class DurabilityTelemetry:
    def __init__(self):
        self._lock = threading.Lock()
        self._registered = False  # guarded-by: _lock
        self.appends_total = 0  # guarded-by: _lock
        self.append_bytes_total = 0  # guarded-by: _lock
        self.commits_total = 0  # guarded-by: _lock
        self.fsyncs_total = 0  # guarded-by: _lock
        self.segments_rolled = 0  # guarded-by: _lock
        self.segments_recycled = 0  # guarded-by: _lock
        self.spilled_now = 0  # RAM-evicted records currently queued  # guarded-by: _lock
        self.spilled_peak = 0  # guarded-by: _lock
        self.spill_reads_total = 0  # guarded-by: _lock
        self.recovery_scans = 0  # guarded-by: _lock
        self.recovery_ms_last = 0.0  # guarded-by: _lock
        self.recovered_records_last = 0  # guarded-by: _lock
        self.torn_tail_repairs = 0  # guarded-by: _lock
        self.replay_opens = 0  # guarded-by: _lock
        self.disk_faults_total = 0  # guarded-by: _lock
        self.replica_truncates = 0  # guarded-by: _lock

    def ensure_registered(self):
        with self._lock:
            if self._registered:
                return
            self._registered = True
        try:
            from psana_ray_tpu.obs import MetricsRegistry

            MetricsRegistry.default().register("durable", self)
        except Exception:  # obs optional: storage must work without it
            pass

    def appended(self, nbytes: int):
        with self._lock:
            self.appends_total += 1
            self.append_bytes_total += nbytes

    def committed(self):
        with self._lock:
            self.commits_total += 1

    def fsynced(self):
        with self._lock:
            self.fsyncs_total += 1

    def rolled(self, recycled: bool):
        with self._lock:
            self.segments_rolled += 1
            if recycled:
                self.segments_recycled += 1

    def spill_delta(self, delta: int):
        with self._lock:
            self.spilled_now += delta
            if self.spilled_now > self.spilled_peak:
                self.spilled_peak = self.spilled_now

    def spill_read(self):
        with self._lock:
            self.spill_reads_total += 1

    def recovered(self, ms: float, records: int, torn: bool):
        with self._lock:
            self.recovery_scans += 1
            self.recovery_ms_last = ms
            self.recovered_records_last = records
            if torn:
                self.torn_tail_repairs += 1

    def replay_opened(self):
        self.ensure_registered()
        with self._lock:
            self.replay_opens += 1

    def disk_faulted(self):
        with self._lock:
            self.disk_faults_total += 1

    def truncated(self):
        with self._lock:
            self.replica_truncates += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "appends_total": self.appends_total,
                "append_bytes_total": self.append_bytes_total,
                "commits_total": self.commits_total,
                "fsyncs_total": self.fsyncs_total,
                "segments_rolled": self.segments_rolled,
                "segments_recycled": self.segments_recycled,
                "spilled_now": self.spilled_now,
                "spilled_peak": self.spilled_peak,
                "spill_reads_total": self.spill_reads_total,
                "recovery_scans": self.recovery_scans,
                "recovery_ms_last": round(self.recovery_ms_last, 3),
                "recovered_records_last": self.recovered_records_last,
                "torn_tail_repairs": self.torn_tail_repairs,
                "replay_opens": self.replay_opens,
                "disk_faults_total": self.disk_faults_total,
                "replica_truncates": self.replica_truncates,
            }

    # obs registry source protocol
    def snapshot(self) -> dict:
        return self.stats()


DURABLE = DurabilityTelemetry()
