"""One mmap'd segment file: pre-allocated, CRC-framed, recycled.

Segments follow the BufferPool discipline on disk (PAPERS.md, DALI's
pre-allocated recycled staging): a segment is allocated ONCE at its
fixed size (``ftruncate`` + ``mmap``), filled with framed records, and
— once every record in it has fallen below the committed floor and out
of the retention window — RESET and renamed to become the log's new
tail instead of being deleted and reallocated. The hot append path is
therefore one ``encode_into`` memcpy into already-mapped page cache:
no per-frame file creation, no intermediate bytes object, no allocator
traffic.

Record framing (little-endian, 20-byte header)::

    magic:u32  payload_len:u32  crc32:u32  offset:u64  payload bytes

``payload`` is the same tagged codec payload the wire carries
(``transport.codec``: tag byte + records wire format / pickle), so a
logged record and a transmitted record are byte-compatible. The CRC
covers the payload; a crash mid-append leaves either an all-zero
header (clean end — pre-allocated segments start zeroed) or a record
whose length/CRC/offset fails validation (a TORN TAIL, truncated by
the recovery scan — see :meth:`Segment.scan`). Offsets are strictly
increasing within and across segments, which also guards the scan
against stale bytes from a recycled segment's previous life.

A segment object must deterministically reach :meth:`close` or
:meth:`reset` (recycle) on every path — enforced by the
``segment-lifecycle`` lint checker the same way lease-lifecycle guards
pool buffers.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import zlib
from typing import List, Optional, Tuple

from psana_ray_tpu.records import EndOfStream, FrameRecord, encode_into, encoded_size
from psana_ray_tpu.transport.codec import TAG_PICKLE, TAG_RECORD

_SEG_REC_MAGIC = 0x50525453  # "PRTS" — psana-ray-tpu segment record
_REC_HEADER = struct.Struct("<IIIQ")
REC_OVERHEAD = _REC_HEADER.size

# zero block reused when scrubbing a recycled segment's previous records
_ZEROS = bytes(1 << 20)


def segment_filename(base_offset: int) -> str:
    return f"seg-{base_offset:020d}.seg"


def parse_base_offset(filename: str) -> Optional[int]:
    if not (filename.startswith("seg-") and filename.endswith(".seg")):
        return None
    try:
        return int(filename[4:-4])
    except ValueError:
        return None


def record_nbytes(item) -> int:
    """Framed size of ``item`` in a segment (header + tag + payload),
    serializing only when the codec must (pickle fallback)."""
    if isinstance(item, (FrameRecord, EndOfStream)):
        return REC_OVERHEAD + 1 + encoded_size(item)
    return REC_OVERHEAD + 1 + len(
        pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)
    )


class Segment:
    """One pre-allocated mmap'd segment. Create with :meth:`allocate` (new
    or recycled file) or :meth:`open_existing` (recovery scan)."""

    def __init__(self, path: str, f, mm: mmap.mmap, base_offset: int):
        self.path = path
        self._f = f
        self._mm = mm
        self._mv = memoryview(mm)
        self.base_offset = base_offset
        self.capacity = len(mm)
        self.write_pos = 0
        # (offset, file position) per record, append order — offsets are
        # strictly increasing so readers bisect
        self.index: List[Tuple[int, int]] = []
        self.closed = False

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def allocate(cls, path: str, nbytes: int, base_offset: int) -> "Segment":
        f = open(path, "a+b")
        try:
            f.truncate(nbytes)
            mm = mmap.mmap(f.fileno(), nbytes)
        except BaseException:
            f.close()
            raise
        return cls(path, f, mm, base_offset)

    @classmethod
    def open_existing(cls, path: str, base_offset: int) -> "Segment":
        f = open(path, "r+b")
        try:
            size = os.fstat(f.fileno()).st_size
            mm = mmap.mmap(f.fileno(), size)
        except BaseException:
            f.close()
            raise
        return cls(path, f, mm, base_offset)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._mv.release()
        self._mm.close()
        self._f.close()

    def retire(self, free_path: str) -> None:
        """Move to the free list: scrub the written region (stale
        records must never survive into the next life) and rename OUT of
        the ``seg-*`` namespace, so a crash with free segments on disk
        cannot poison the next boot's recovery scan (a stale ``seg-``
        file would scan as valid history)."""
        self._scrub(self.write_pos)
        os.rename(self.path, free_path)
        self.path = free_path
        self.write_pos = 0
        self.index = []

    def reset(self, new_base_offset: int, new_path: str) -> None:
        """Reuse a retired (already scrubbed) segment as the log's new
        tail: rename into position and rewind."""
        os.rename(self.path, new_path)
        self.path = new_path
        self.base_offset = new_base_offset
        self.write_pos = 0
        self.index = []

    def _scrub(self, nbytes: int) -> None:
        pos = 0
        while pos < nbytes:
            n = min(len(_ZEROS), nbytes - pos)
            self._mv[pos : pos + n] = _ZEROS[:n]
            pos += n

    # -- append ------------------------------------------------------------
    def remaining(self) -> int:
        return self.capacity - self.write_pos

    def append(self, offset: int, item) -> Optional[int]:
        """Frame ``item`` at the write position; returns the record's
        file position, or None when it does not fit (roll the log). The
        payload lands via ONE ``encode_into`` memcpy for records (the
        scatter-gather encode-into-slot path the shm ring uses); the
        header is written AFTER the payload so a crash mid-memcpy leaves
        an all-zero header, not a half-framed record."""
        pos = self.write_pos
        data_start = pos + REC_OVERHEAD
        if isinstance(item, (FrameRecord, EndOfStream)):
            need = 1 + encoded_size(item)
            if data_start + need > self.capacity:
                return None
            self._mv[data_start : data_start + 1] = TAG_RECORD
            n = encode_into(item, self._mv[data_start + 1 :])
            payload_len = n + 1
        else:
            data = TAG_PICKLE + pickle.dumps(
                item, protocol=pickle.HIGHEST_PROTOCOL
            )
            payload_len = len(data)
            if data_start + payload_len > self.capacity:
                return None
            self._mv[data_start : data_start + payload_len] = data
        crc = zlib.crc32(self._mv[data_start : data_start + payload_len])
        _REC_HEADER.pack_into(
            self._mv, pos, _SEG_REC_MAGIC, payload_len, crc, offset
        )
        self.write_pos = data_start + payload_len
        self.index.append((offset, pos))
        return pos

    # -- read --------------------------------------------------------------
    def payload_at(self, pos: int) -> memoryview:
        """Zero-copy view of the record payload at ``pos``. The view is
        TRANSIENT: decode (which copies the panels out) before any
        operation that could reset or close this segment."""
        magic, payload_len, _crc, _off = _REC_HEADER.unpack_from(self._mv, pos)
        if magic != _SEG_REC_MAGIC:
            raise ValueError(f"bad segment record magic {magic:#x} at {pos}")
        start = pos + REC_OVERHEAD
        return self._mv[start : start + payload_len]

    def payload_extent(self, pos: int):
        """``(file, file_pos, nbytes)`` of the payload at ``pos`` — the
        sendfile span for the kernel pass-through path (the on-disk
        payload IS the raw tagged wire payload, written verbatim by
        :meth:`append`). The file object is the segment's own open
        handle; callers rely on the durable queue's commit-floor pin to
        keep this segment live while the span is queued."""
        magic, payload_len, _crc, _off = _REC_HEADER.unpack_from(self._mv, pos)
        if magic != _SEG_REC_MAGIC:
            raise ValueError(f"bad segment record magic {magic:#x} at {pos}")
        return self._f, pos + REC_OVERHEAD, payload_len

    def find(self, offset: int) -> Optional[int]:
        """File position of the record with exactly ``offset``."""
        import bisect

        i = bisect.bisect_left(self.index, (offset, -1))
        if i < len(self.index) and self.index[i][0] == offset:
            return self.index[i][1]
        return None

    def truncate_from(self, pos: int) -> None:
        """Discard every record at file position >= ``pos`` (the replica
        reconciliation path, ISSUE 11: a promoted-then-deposed or
        diverged suffix is scrubbed so the overwriting appends — and any
        later recovery scan — see a clean end, exactly like a torn-tail
        repair)."""
        if pos >= self.write_pos:
            return
        cursor = pos
        while cursor < self.write_pos:
            n = min(len(_ZEROS), self.write_pos - cursor)
            self._mv[cursor : cursor + n] = _ZEROS[:n]
            cursor += n
        self.index = [(off, p) for (off, p) in self.index if p < pos]
        self.write_pos = pos

    # -- recovery ----------------------------------------------------------
    def scan(self, expect_from: int) -> Tuple[int, bool]:
        """Rebuild the index from disk after a restart: walk records from
        position 0, validating magic, bounds, CRC, and strictly
        increasing offsets starting at ``expect_from`` (the previous
        segment's last offset + 1 — also what stops the scan cold on a
        recycled segment's stale bytes). Sets ``write_pos`` to the end
        of the last valid record. Returns ``(last_valid_offset + 1,
        torn)`` where ``torn`` reports a tail that had to be discarded
        (nonzero bytes that failed validation — crash mid-append)."""
        self.index = []
        pos = 0
        next_offset = expect_from
        torn = False
        while pos + REC_OVERHEAD <= self.capacity:
            header = bytes(self._mv[pos : pos + REC_OVERHEAD])
            if header == b"\0" * REC_OVERHEAD:
                break  # clean end (pre-allocated segments start zeroed)
            magic, payload_len, crc, offset = _REC_HEADER.unpack(header)
            data_start = pos + REC_OVERHEAD
            if (
                magic != _SEG_REC_MAGIC
                or payload_len == 0
                or data_start + payload_len > self.capacity
                or offset != next_offset
                or zlib.crc32(self._mv[data_start : data_start + payload_len])
                != crc
            ):
                torn = True
                break
            self.index.append((offset, pos))
            next_offset = offset + 1
            pos = data_start + payload_len
        self.write_pos = pos
        if torn:
            # truncate the torn tail: scrub to capacity so the repaired
            # region reads as a clean end on any later scan
            cursor = pos
            while cursor < self.capacity:
                n = min(len(_ZEROS), self.capacity - cursor)
                self._mv[cursor : cursor + n] = _ZEROS[:n]
                cursor += n
        return next_offset, torn

    # -- durability --------------------------------------------------------
    def sync(self) -> None:
        self._mm.flush()

    @property
    def first_offset(self) -> Optional[int]:
        return self.index[0][0] if self.index else None

    @property
    def last_offset(self) -> Optional[int]:
        return self.index[-1][0] if self.index else None

    def __repr__(self):
        return (
            f"<Segment {os.path.basename(self.path)} base={self.base_offset} "
            f"records={len(self.index)} used={self.write_pos}/{self.capacity}>"
        )
