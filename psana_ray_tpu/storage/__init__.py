"""Durable segment-log storage under the queue server (ISSUE 8).

The transports of PRs 1-7 are memory-only: a queue server restart takes
its queue depth with it, and delivery is destructive — there is no
"replay yesterday's run". This package adds the missing persistence
layer with the same host-path discipline the datapath already follows
(PAPERS.md: DALI-style pre-allocated recycled staging, tf.data's
host-side robustness):

- :mod:`psana_ray_tpu.storage.segment` — fixed-size, PRE-ALLOCATED,
  RECYCLED mmap'd segment files with per-record CRC framing (a torn
  tail from a crash is detected and truncated on the next boot, never
  silently served);
- :mod:`psana_ray_tpu.storage.log` — :class:`~psana_ray_tpu.storage.
  log.SegmentLog`: an append-only offset-addressed record log over a
  ring of segments, with bounded retention, a committed-offset store
  per consumer group, and a crash-recovery scan;
- :mod:`psana_ray_tpu.storage.durable` — :class:`~psana_ray_tpu.
  storage.durable.DurableRingBuffer`: the log-backed RingBuffer
  variant the queue server mounts under ``--durable_dir``. Appends go
  to the log via the existing encode-into-slot scatter-gather plumbing
  (one memcpy into the page cache, no intermediate bytes), reads serve
  from RAM while depth fits and spill to log reads when it does not,
  and consumer positions are committed offsets — crash-redelivery
  across a server restart is "rewind to the last committed offset".
- :mod:`psana_ray_tpu.storage.telemetry` — the ``durable`` obs source
  (log depth, spill, recovery time, torn-tail repairs).

At-least-once is preserved end to end: duplicates possible, holes
never, loss never — including across kill -9 (page-cache writes
survive process death; ``fsync`` policy ``none|batch|always`` chooses
how much a MACHINE crash may lose).
"""

from psana_ray_tpu.storage.durable import DurableRingBuffer
from psana_ray_tpu.storage.log import (
    FSYNC_ALWAYS,
    FSYNC_BATCH,
    FSYNC_NONE,
    REPLAY_BEGIN,
    REPLAY_RESUME,
    SegmentLog,
)
from psana_ray_tpu.storage.telemetry import DURABLE

__all__ = [
    "DurableRingBuffer",
    "SegmentLog",
    "DURABLE",
    "FSYNC_NONE",
    "FSYNC_BATCH",
    "FSYNC_ALWAYS",
    "REPLAY_BEGIN",
    "REPLAY_RESUME",
]
