"""Per-process metrics HISTORY: a bounded ring of registry snapshots.
# lint: hot-path

PR 1/PR 4 gave every process counters, stage histograms, traces and a
flight recorder — each an INSTANTANEOUS, single-process view. ISSUE 13
adds the time axis: a :class:`HistorySampler` thread periodically
flattens the process's :class:`~psana_ray_tpu.obs.registry.
MetricsRegistry` snapshot (the exact flattening grammar the Prometheus
renderer uses — :func:`~psana_ray_tpu.obs.registry.flatten_numeric`)
into per-key :class:`SeriesRing` buffers.

Design rules (the self-tuning controller of ROADMAP item 3 reads these
rings at high rate, and the sampler rides every process):

- **bounded**: one ring per key, fixed capacity, preallocated
  ``array('d')`` storage — memory is ``O(keys x capacity)`` forever;
- **zero-alloc on sample**: :meth:`SeriesRing.append` is index
  arithmetic into the preallocated arrays (``# lint: sample-path``,
  enforced by the ``telemetry-discipline`` checker). A ring is
  allocated ONCE, the first time its key appears;
- **views at read time**: delta / windowed rate / EWMA / percentile are
  computed from the ring when ASKED (:meth:`TimeSeriesStore.rate` and
  friends) — the sample path stays counter arithmetic, the analysis
  cost lands on the reader (console, controller, collector), never the
  pipeline.

The flight recorder appends :meth:`TimeSeriesStore.tail` to every dump
(ISSUE 13 satellite): a postmortem shows the minutes BEFORE the
trigger, not just the instant.

Pure stdlib, importable without numpy/jax (every process pays the
import).
"""

from __future__ import annotations

import threading
import time
from array import array
from typing import Dict, List, Optional, Tuple

from psana_ray_tpu.obs.registry import MetricsRegistry, flatten_numeric

__all__ = [
    "SeriesRing",
    "TimeSeriesStore",
    "HistorySampler",
    "add_history_args",
    "configure_history_from_args",
    "default_history",
]

DEFAULT_CAPACITY = 600  # 10 min of history at the default 1 s interval
DEFAULT_INTERVAL_S = 1.0


class SeriesRing:
    """Fixed-capacity (t, value) ring for ONE key: preallocated twin
    ``array('d')`` columns, append = two indexed stores + counter
    arithmetic (no allocation — pinned by the telemetry-discipline
    checker's sample-path rule and tests/test_timeseries.py)."""

    __slots__ = ("_t", "_v", "_cap", "_n", "_i")

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity <= 1:
            raise ValueError("SeriesRing capacity must be > 1")
        self._cap = int(capacity)
        self._t = array("d", [0.0]) * self._cap
        self._v = array("d", [0.0]) * self._cap
        self._n = 0  # samples held (saturates at _cap)
        self._i = 0  # next write slot

    def append(self, t: float, v: float) -> None:  # lint: sample-path
        i = self._i
        self._t[i] = t
        self._v[i] = v
        self._i = i + 1 if i + 1 < self._cap else 0
        if self._n < self._cap:
            self._n += 1

    def __len__(self) -> int:
        return self._n

    @property
    def capacity(self) -> int:
        return self._cap

    def samples(self, n: Optional[int] = None) -> List[Tuple[float, float]]:
        """The last ``n`` (t, value) pairs in time order (all when None).
        Read-time allocation is fine — this is the VIEW side."""
        count = self._n if n is None else min(int(n), self._n)
        if count <= 0:
            return []
        start = (self._i - count) % self._cap
        out = []
        for k in range(count):
            j = (start + k) % self._cap
            out.append((self._t[j], self._v[j]))
        return out

    def last(self) -> Optional[Tuple[float, float]]:
        if not self._n:
            return None
        j = (self._i - 1) % self._cap
        return (self._t[j], self._v[j])


class TimeSeriesStore:
    """``{key: SeriesRing}`` + the read-time views (delta / rate / EWMA /
    percentile). One per process (:func:`default_history`), one per
    federated peer inside the collector."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._capacity = int(capacity)
        self._lock = threading.Lock()
        self._rings: Dict[str, SeriesRing] = {}  # guarded-by: _lock
        self._samples_total = 0  # sweeps recorded  # guarded-by: _lock
        self._last_t = 0.0  # guarded-by: _lock

    # -- sample path -------------------------------------------------------
    def record(self, tree: dict, now: Optional[float] = None) -> int:
        """Flatten one registry snapshot tree and append every numeric
        leaf to its ring (allocating a ring only on FIRST sight of a
        key). Returns the number of keys written."""
        now = time.time() if now is None else now
        leaves: List[Tuple[str, float]] = []
        flatten_numeric((), tree, leaves)
        with self._lock:
            rings = self._rings
            for key, value in leaves:
                ring = rings.get(key)
                if ring is None:  # first sight only: steady state allocates nothing
                    ring = rings[key] = SeriesRing(self._capacity)
                ring.append(now, value)
            self._samples_total += 1
            self._last_t = now
        return len(leaves)

    # -- read-time views ---------------------------------------------------
    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def series(self, key: str, n: Optional[int] = None) -> List[Tuple[float, float]]:
        # the copy-out happens UNDER the lock: a concurrent record()
        # advancing the ring head mid-read would otherwise tear the view
        with self._lock:
            ring = self._rings.get(key)
            return ring.samples(n) if ring is not None else []

    def last(self, key: str) -> Optional[float]:
        with self._lock:
            ring = self._rings.get(key)
            lt = ring.last() if ring is not None else None
        return lt[1] if lt is not None else None

    def delta(self, key: str, window_s: Optional[float] = None) -> Optional[float]:
        """value[last] - value[first sample inside the window] (whole ring
        when ``window_s`` is None). None with <2 samples."""
        pts = self._window(key, window_s)
        if len(pts) < 2:
            return None
        return pts[-1][1] - pts[0][1]

    def rate(self, key: str, window_s: Optional[float] = None) -> Optional[float]:
        """delta / elapsed over the window — the counter-to-rate view
        (e.g. ``queue_server.default.puts`` -> puts/s)."""
        pts = self._window(key, window_s)
        if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
            return None
        return (pts[-1][1] - pts[0][1]) / (pts[-1][0] - pts[0][0])

    def ewma(self, key: str, alpha: float = 0.2,
             window_s: Optional[float] = None) -> Optional[float]:
        pts = self._window(key, window_s)
        if not pts:
            return None
        acc = pts[0][1]
        for _, v in pts[1:]:
            acc += alpha * (v - acc)
        return acc

    def percentile(self, key: str, q: float,
                   window_s: Optional[float] = None) -> Optional[float]:
        pts = self._window(key, window_s)
        if not pts:
            return None
        vals = sorted(v for _, v in pts)
        return vals[min(len(vals) - 1, max(0, int(q * len(vals))))]

    def _window(self, key: str, window_s: Optional[float]) -> List[Tuple[float, float]]:
        pts = self.series(key)
        if window_s is None or not pts:
            return pts
        cutoff = pts[-1][0] - window_s
        return [p for p in pts if p[0] >= cutoff]

    def tail(self, n: int = 32, keys: Optional[List[str]] = None) -> Dict[str, list]:
        """The last ``n`` samples per key as JSON-safe rows — what the
        flight recorder appends to a dump (the minutes BEFORE the
        trigger)."""
        out: Dict[str, list] = {}
        for key in (keys if keys is not None else self.keys()):
            pts = self.series(key, n)
            if pts:
                out[key] = [[round(t, 3), v] for t, v in pts]
        return out

    # -- registry source ---------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "keys": len(self._rings),
                "capacity": self._capacity,
                "samples_total": self._samples_total,
                "last_sample_age_s": round(time.time() - self._last_t, 3)
                if self._last_t else -1.0,
            }


class HistorySampler:
    """The per-process sampling loop: every ``interval_s`` take ONE
    registry snapshot and record it into the store. A daemon thread with
    a bounded Event wait; ``sample_once`` is exposed so tests (and the
    bench A/B) drive time explicitly."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        store: Optional[TimeSeriesStore] = None,
        interval_s: float = DEFAULT_INTERVAL_S,
        capacity: int = DEFAULT_CAPACITY,
    ):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive (0 = don't build one)")
        self.registry = registry  # None = resolve default() per sample
        self.store = store if store is not None else TimeSeriesStore(capacity)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._sweeps = 0  # guarded-by: _lock
        self._last_ms = 0.0  # cost of the last sweep  # guarded-by: _lock
        self._max_ms = 0.0  # guarded-by: _lock

    def sample_once(self, now: Optional[float] = None) -> int:
        reg = self.registry if self.registry is not None else MetricsRegistry.default()
        t0 = time.perf_counter()
        n = self.store.record(reg.snapshot(), now=now)
        ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self._sweeps += 1
            self._last_ms = ms
            if ms > self._max_ms:
                self._max_ms = ms
        return n

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — history must outlive a bad source
                pass

    def start(self) -> "HistorySampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="history-sampler"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "HistorySampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- registry source (the observer observes itself) --------------------
    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "interval_s": self.interval_s,
                "sweeps_total": self._sweeps,
                "sweep_last_ms": round(self._last_ms, 3),
                "sweep_max_ms": round(self._max_ms, 3),
            }
        out.update(self.store.snapshot())
        return out


# -- process-global wiring ---------------------------------------------------
_default_lock = threading.Lock()
_default_sampler: Optional[HistorySampler] = None


def default_history() -> Optional[TimeSeriesStore]:
    """The process's history store, or None when no sampler was started
    (the flight recorder asks on every dump — absent history must cost
    nothing and fail nothing)."""
    with _default_lock:
        return _default_sampler.store if _default_sampler is not None else None


def start_default_history(
    interval_s: float = DEFAULT_INTERVAL_S,
    capacity: int = DEFAULT_CAPACITY,
    registry: Optional[MetricsRegistry] = None,
) -> HistorySampler:
    """Start (or return) THE process-global sampler and register it as
    the ``timeseries`` registry source. Idempotent: the first caller's
    interval/capacity win (one history per process)."""
    global _default_sampler
    with _default_lock:
        if _default_sampler is None:
            _default_sampler = HistorySampler(
                registry=registry, interval_s=interval_s, capacity=capacity
            ).start()
            reg = registry if registry is not None else MetricsRegistry.default()
            reg.register("timeseries", _default_sampler)
        return _default_sampler


def stop_default_history() -> None:
    """Stop + forget the process-global sampler (tests)."""
    global _default_sampler
    with _default_lock:
        sampler, _default_sampler = _default_sampler, None
    if sampler is not None:
        sampler.stop()


# -- CLI wiring --------------------------------------------------------------
def add_history_args(parser) -> None:
    """The shared ``--history_interval`` / ``--history_samples`` pair
    every long-running CLI exposes (one definition, like
    ``add_metrics_args``)."""
    parser.add_argument(
        "--history_interval", type=float, default=DEFAULT_INTERVAL_S,
        help="sample the metrics registry into the in-process "
        "time-series history ring every N seconds (feeds flight-dump "
        "tails, the federation collector, and `python -m "
        "psana_ray_tpu.obs.top`); 0 = off",
    )
    parser.add_argument(
        "--history_samples", type=int, default=DEFAULT_CAPACITY,
        help="bounded per-key ring capacity for --history_interval "
        "(memory is O(keys x samples), preallocated)",
    )


def configure_history_from_args(args) -> Optional[HistorySampler]:
    """CLI entry: start the process-global history sampler from the
    ``add_history_args`` flags (None when ``--history_interval 0``)."""
    interval = getattr(args, "history_interval", 0.0) or 0.0
    if interval <= 0:
        return None
    return start_default_history(
        interval_s=interval,
        capacity=max(2, int(getattr(args, "history_samples", DEFAULT_CAPACITY))),
    )
