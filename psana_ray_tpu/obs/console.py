"""Live operator console over the federated metrics history.

``python -m psana_ray_tpu.obs.top --peers host:port,http://host:port``
polls the ISSUE 13 :class:`~psana_ray_tpu.obs.collector.
ClusterCollector` and renders ONE pane over the fleet: a row per peer
(queue servers over the 'N' metrics RPC, producer/consumer CLIs over
their ``/federate`` endpoint) with the numbers an operator triages by —
fps, queue depth, stream credit occupancy, live codec ratio, gateway
shed rate, replication lag — plus an fps sparkline from the host-tagged
history rings and the active SLO alerts.

Plain-ANSI refresh (home + clear between frames, no curses dependency);
``--once`` renders a single frame without escapes for scripting and the
tier-1 golden test. Everything here is READ-side: rendering allocates
freely, the sampled processes pay nothing.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

from psana_ray_tpu.obs.collector import ClusterCollector, PEER_UP

__all__ = ["sparkline", "render", "main"]

_SPARK = "▁▂▃▄▅▆▇█"

# fps resolution: CLI processes publish PipelineMetrics frame counters;
# a queue server's "fps" is the sum of its per-queue get rates (frames
# leaving the relay toward consumers)
_FRAME_COUNTER_KEYS = (
    "producer.frames_total",
    "consumer.frames_total",
    "sfx.frames_total",
    "gateway.completed_total",
)


def sparkline(values: List[float], width: int = 24) -> str:
    """Last ``width`` values as a unicode sparkline (empty-safe,
    flat-safe)."""
    vals = [v for v in values[-width:] if v == v]  # drop NaNs
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[0] * len(vals)
    span = hi - lo
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / span * len(_SPARK)))]
        for v in vals
    )


def _fmt(v: Optional[float], digits: int = 1) -> str:
    if v is None:
        return "-"
    if abs(v) >= 1000:
        return f"{v:,.0f}"
    return f"{v:.{digits}f}"


def _sum_rates(store, suffix: str, prefix: str, window_s: float) -> Optional[float]:
    total = None
    for key in store.keys():
        if key.startswith(prefix) and key.endswith(suffix):
            r = store.rate(key, window_s)
            if r is not None:
                total = (total or 0.0) + max(0.0, r)
    return total


def peer_row(label: str, state, store, window_s: float = 30.0,
             profile: Optional[dict] = None) -> dict:
    """Extract one display row from a peer's series store (None = the
    peer never published that subsystem). ``profile`` is the peer's
    federated profile summary (ISSUE 16) — CPU% prefers its live
    cpu_frac, falling back to the ``prof.cpu_frac`` series for peers
    whose summary aged out of the payload."""
    fps = None
    fps_key = None
    for key in _FRAME_COUNTER_KEYS:
        r = store.rate(key, window_s)
        if r is not None:
            fps, fps_key = max(0.0, r), key
            break
    if fps is None:
        fps = _sum_rates(store, ".gets", "queue_server.", window_s)
        fps_key = "queue_server.*.gets" if fps is not None else None
    depth = None
    for key in store.keys():
        if key.endswith(".depth") or key.endswith(".queue.depth"):
            depth = (depth or 0.0) + (store.last(key) or 0.0)
    # fps history for the sparkline: successive deltas of the frame
    # counter over the ring (a rate series computed at read time)
    spark_vals: List[float] = []
    if fps_key and fps_key != "queue_server.*.gets":
        pts = store.series(fps_key)
        spark_vals = [
            (b[1] - a[1]) / (b[0] - a[0])
            for a, b in zip(pts, pts[1:]) if b[0] > a[0]
        ]
    elif fps_key:  # queue server: spark the first queue's gets series
        for key in sorted(store.keys()):
            if key.startswith("queue_server.") and key.endswith(".gets"):
                pts = store.series(key)
                spark_vals = [
                    (b[1] - a[1]) / (b[0] - a[0])
                    for a, b in zip(pts, pts[1:]) if b[0] > a[0]
                ]
                break
    cpu_frac = None
    hot = ""
    if isinstance(profile, dict):
        cpu_frac = profile.get("cpu_frac")
        hot_list = profile.get("hot") or []
        if hot_list:
            top = hot_list[0]
            hot = f"{top.get('frame', '?')} {top.get('pct', 0.0):.0f}%"
    if cpu_frac is None:
        cpu_frac = store.last("prof.cpu_frac")
    return {
        "label": label,
        "state": state,
        "fps": fps,
        "depth": depth,
        "credit": store.last("stream.credit_window"),
        "ratio": store.last("wire_codec.ratio_in")
        or store.last("wire_codec.ratio_out"),
        "shed_rate": store.rate("gateway.shed_total", window_s),
        "lag": store.last("replication.lag_records"),
        "spark": sparkline(spark_vals),
        "cpu_pct": None if cpu_frac is None else 100.0 * cpu_frac,
        # evloop duty cycle (ISSUE 17): with --workers each peer row is
        # one worker, so this is the per-worker saturation signal the
        # scaling runbook reads ("which worker is pegged?")
        "busy_pct": None if (b := store.last("evloop.busy_frac_ewma")
                             or store.last("evloop.busy_frac")) is None
        else 100.0 * b,
        "hot": hot,
    }


def render(collector: ClusterCollector, window_s: float = 30.0,
           now: Optional[float] = None) -> str:
    """One frame of the console as plain text (the ``--once`` output and
    the body of every ANSI refresh)."""
    now = time.time() if now is None else now
    peers = collector.peers()
    up = sum(1 for p in peers if p.state == PEER_UP)
    alerts = collector.active_alerts()
    lines = [
        f"psana-ray obs.top — {len(peers)} peer(s), {up} up, "
        f"{len(alerts)} alert(s) active   "
        f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(now))}",
        f"{'PEER':<28} {'ST':<9} {'HOST:PID':<18} {'FPS':>9} "
        f"{'DEPTH':>7} {'CREDIT':>7} {'RATIO':>6} {'SHED/s':>7} "
        f"{'LAG':>6} {'CPU%':>5} {'BUSY%':>5}  FPS HISTORY",
    ]
    for p in sorted(peers, key=lambda p: p.label):
        store = collector.store(p.label)
        row = peer_row(p.label, p.state, store, window_s,
                       profile=getattr(p, "profile", None))
        hostpid = f"{p.host}:{p.pid}" if p.host else "-"
        # a --workers peer identifies its worker (ISSUE 17): the pulled
        # connection pins to one worker, so the tag is row-stable
        wid = getattr(p, "worker", None)
        if wid is not None:
            hostpid += f"/w{wid}"
        hot = f"  hot: {row['hot']}" if row["hot"] else ""
        lines.append(
            f"{row['label']:<28.28} {row['state']:<9} {hostpid:<18.18} "
            f"{_fmt(row['fps']):>9} {_fmt(row['depth'], 0):>7} "
            f"{_fmt(row['credit'], 0):>7} {_fmt(row['ratio'], 2):>6} "
            f"{_fmt(row['shed_rate']):>7} {_fmt(row['lag'], 0):>6} "
            f"{_fmt(row['cpu_pct'], 0):>5} {_fmt(row['busy_pct'], 0):>5}  "
            f"{row['spark']}{hot}"
        )
        if p.state != PEER_UP and p.error:
            lines.append(f"  └─ {p.error[:100]}")
    if alerts:
        lines.append("alerts:")
        for a in alerts:
            lines.append(
                f"  ! {a['alert']} on {a['peer']} (active {a['for_s']}s)"
            )
    snap = collector.snapshot()
    lines.append(
        f"sweeps={snap['sweeps_total']} pulls_ok={snap['pulls_ok_total']} "
        f"pulls_failed={snap['pulls_failed_total']} "
        f"alerts_fired={snap['alerts_fired_total']}"
    )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m psana_ray_tpu.obs.top",
        description="live federated console over queue servers ('N' "
        "metrics RPC) and CLI metrics endpoints (/federate)",
    )
    p.add_argument(
        "--peers", required=True,
        help="comma-separated peer list: host:port (queue server) and/or "
        "http://host:port (a CLI's --metrics_port endpoint)",
    )
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh/poll interval in seconds")
    p.add_argument("--window", type=float, default=30.0,
                   help="rate window in seconds for the fps/shed columns")
    p.add_argument(
        "--once", action="store_true",
        help="two quick sweeps, one plain frame to stdout, exit 0 — for "
        "scripts and tests (no ANSI escapes)",
    )
    p.add_argument(
        "--settle", type=float, default=0.3,
        help="--once only: gap between the two sweeps (rates need two "
        "samples)",
    )
    a = p.parse_args(argv)
    peers = [s for s in a.peers.split(",") if s.strip()]
    collector = ClusterCollector(peers, interval_s=a.interval)
    try:
        if a.once:
            collector.poll_once()
            time.sleep(max(0.0, a.settle))
            collector.poll_once()
            print(render(collector, window_s=a.window))
            return 0
        collector.poll_once()
        while True:
            time.sleep(a.interval)
            collector.poll_once()
            frame = render(collector, window_s=a.window)
            sys.stdout.write("\x1b[H\x1b[2J" + frame + "\n")
            sys.stdout.flush()
    except KeyboardInterrupt:
        return 0
    finally:
        collector.stop()


if __name__ == "__main__":
    raise SystemExit(main())
