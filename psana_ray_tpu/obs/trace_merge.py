"""Merge per-process trace spools into one Chrome trace-event JSON.

``python -m psana_ray_tpu.obs.trace_merge <spool-dir-or-files...>
[--out merged_trace.json]`` reads the JSONL spools written by
:class:`psana_ray_tpu.obs.tracing.Tracer` (one per process: producer,
queue server, consumer, ...), estimates each process's clock offset, and
emits the Chrome trace-event format that Perfetto (https://ui.perfetto.dev)
and TensorBoard load directly: one track per process, frame spans linked
across tracks by trace id (flow arrows).

Clock alignment, two layers:

- **monotonic -> wall** per process: spans are recorded in that process's
  ``time.monotonic()`` domain; the spool's (wall, mono) anchor pairs give
  ``offset = median(wall - mono)``, robust to scheduling jitter at any
  single anchor.
- **wall -> server wall** per process (cross-host): peer-anchor
  exchanges (tcp opcode ``A``) sandwich the server's wallclock between a
  local send/recv pair; ``skew = median(local_wall_mid - peer_wall)``
  estimates this host's wallclock skew against the queue server, bounded
  by the RTT. Processes without exchanges (same-host deployments, shm
  transports) get skew 0 — their wall clocks are literally the same clock.

Unified timeline: ``ts = mono + offset - skew`` (seconds since the
server's wallclock epoch), emitted in microseconds as the trace format
requires.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

__all__ = ["load_spool", "merge", "main"]


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def load_spool(path: str) -> dict:
    """Parse one spool: ``{"meta": {...}, "anchors": [...], "peers":
    [...], "spans": [...], "instants": [...]}``. Tolerates a truncated
    final line (the process may have died mid-write — that is exactly
    when these files matter)."""
    meta: dict = {}
    anchors: List[dict] = []
    peers: List[dict] = []
    spans: List[dict] = []
    instants: List[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail write from a crashed process
            t = rec.get("t")
            if t == "m":
                meta = rec
            elif t == "a":
                anchors.append(rec)
            elif t == "p":
                peers.append(rec)
            elif t == "s":
                spans.append(rec)
            elif t == "i":
                instants.append(rec)
    return {
        "path": path,
        "meta": meta,
        "anchors": anchors,
        "peers": peers,
        "spans": spans,
        "instants": instants,
    }


def clock_offset(spool: dict) -> float:
    """monotonic -> wall offset for this process (median over anchors;
    falls back to the meta line's start pair)."""
    pairs = [(a["wall"], a["mono"]) for a in spool["anchors"]]
    meta = spool["meta"]
    if not pairs and "start_wall" in meta:
        pairs = [(meta["start_wall"], meta["start_mono"])]
    if not pairs:
        return 0.0
    return _median([w - m for w, m in pairs])


def clock_skew(spool: dict, offset: float) -> float:
    """This process's wallclock skew vs the queue server (0 without
    peer-anchor exchanges). Positive = this host's clock runs ahead."""
    ests = []
    for p in spool["peers"]:
        try:
            mid_mono = 0.5 * (p["send_mono"] + p["recv_mono"])
            ests.append((offset + mid_mono) - p["peer_wall"])
        except KeyError:
            continue
    return _median(ests) if ests else 0.0


def _expand(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*.trace.jsonl"))))
        else:
            out.append(p)
    return out


def merge(paths: List[str], only_trace: Optional[int] = None) -> dict:
    """Merge spool files (or directories of them) into a Chrome
    trace-event document (the ``json.dump``-ready dict).

    ``only_trace`` filters to ONE trace id — the ``--exemplar`` lookup
    (ISSUE 13): a latency-histogram bucket's retained exemplar resolves
    to just that frame's cross-host timeline."""
    files = _expand(paths)
    if not files:
        raise FileNotFoundError(f"no trace spools found under {paths!r}")
    spools = [load_spool(p) for p in files]
    if only_trace is not None:
        for spool in spools:
            spool["spans"] = [
                s for s in spool["spans"] if s.get("id") == only_trace
            ]
            spool["instants"] = [
                i for i in spool["instants"] if i.get("id") == only_trace
            ]
    events: List[dict] = []
    flows: Dict[int, List[dict]] = {}  # trace_id -> [(ts, pid)] span starts
    summary = []
    for pid, spool in enumerate(spools, start=1):
        meta = spool["meta"]
        offset = clock_offset(spool)
        skew = clock_skew(spool, offset)
        name = (
            f"{meta.get('process', 'proc')} "
            f"{meta.get('host', '?')}:{meta.get('pid', '?')}"
        )
        summary.append(
            {
                "track": pid,
                "process": name,
                "spool": spool["path"],
                "spans": len(spool["spans"]),
                "instants": len(spool["instants"]),
                "mono_to_wall_offset_s": offset,
                "skew_vs_server_s": skew,
                "peer_anchor_exchanges": len(spool["peers"]),
            }
        )
        events.append(
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": name}}
        )
        base = offset - skew

        def us(mono: float, _base=base) -> float:
            return (mono + _base) * 1e6

        for s in spool["spans"]:
            tid = s.get("id", 0)
            ts = us(s["a"])
            events.append(
                {
                    "ph": "X", "name": s["n"], "cat": "frame",
                    "pid": pid, "tid": 0,
                    "ts": ts, "dur": max(0.0, us(s["b"]) - ts),
                    "args": {"trace_id": f"{tid:#x}"},
                }
            )
            flows.setdefault(tid, []).append({"ts": ts, "pid": pid})
        for i in spool["instants"]:
            tid = i.get("id", 0)
            events.append(
                {
                    "ph": "i", "name": i["n"], "cat": "frame", "s": "t",
                    "pid": pid, "tid": 0, "ts": us(i["a"]),
                    "args": {"trace_id": f"{tid:#x}"},
                }
            )
    # flow arrows: one chain per trace id through its span starts in
    # unified-time order — the cross-track "this frame went here next"
    # links Perfetto draws
    for tid, starts in flows.items():
        starts.sort(key=lambda e: e["ts"])
        if len(starts) < 2:
            continue
        for i, st in enumerate(starts):
            ph = "s" if i == 0 else ("f" if i == len(starts) - 1 else "t")
            evt = {
                "ph": ph, "id": tid, "name": "frame", "cat": "flow",
                "pid": st["pid"], "tid": 0, "ts": st["ts"],
            }
            if ph == "f":
                evt["bp"] = "e"  # bind to the enclosing slice
            events.append(evt)
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"tool": "psana_ray_tpu.obs.trace_merge", "tracks": summary},
    }


def exemplar_timeline(doc: dict) -> List[dict]:
    """The filtered merged doc's frame spans in unified-time order —
    one row per (process, span) with aligned start/duration, the
    human-readable half of ``--exemplar``."""
    tracks = {
        t["track"]: t["process"] for t in doc["otherData"]["tracks"]
    }
    rows = []
    for e in doc["traceEvents"]:
        if e.get("ph") in ("X", "i") and e.get("cat") == "frame":
            rows.append(
                {
                    "process": tracks.get(e["pid"], str(e["pid"])),
                    "span": e["name"],
                    "ts_us": e["ts"],
                    "dur_us": e.get("dur", 0.0),
                }
            )
    rows.sort(key=lambda r: r["ts_us"])
    return rows


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m psana_ray_tpu.obs.trace_merge",
        description="merge per-process trace spools into Chrome trace-event "
        "JSON (open in https://ui.perfetto.dev or TensorBoard)",
    )
    p.add_argument(
        "inputs", nargs="+",
        help="spool files (*.trace.jsonl) or directories containing them",
    )
    p.add_argument("--out", default="merged_trace.json", help="output path")
    p.add_argument(
        "--exemplar", default=None, metavar="TRACE_ID",
        help="resolve ONE trace id (hex 0x... or decimal — the form a "
        "latency histogram's exemplars dict retains) to its merged "
        "cross-host timeline: prints the span table and writes the "
        "filtered trace doc to --out (ISSUE 13)",
    )
    a = p.parse_args(argv)
    only_trace = None
    if a.exemplar is not None:
        try:
            only_trace = int(a.exemplar, 0)
        except ValueError:
            print(f"error: --exemplar {a.exemplar!r} is not a trace id "
                  f"(want 0x... or decimal)", file=sys.stderr)
            return 2
    try:
        doc = merge(a.inputs, only_trace=only_trace)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if only_trace is not None:
        rows = exemplar_timeline(doc)
        if not rows:
            print(
                f"exemplar {only_trace:#x}: no spans in the given spools "
                f"(sampled out, or the wrong spool directory)",
                file=sys.stderr,
            )
            return 1
        print(f"exemplar {only_trace:#x}: {len(rows)} span(s) across "
              f"{len({r['process'] for r in rows})} process(es)")
        t0 = rows[0]["ts_us"]
        for r in rows:
            print(
                f"  +{(r['ts_us'] - t0) / 1e3:9.3f}ms "
                f"{r['span']:<12} {r['dur_us'] / 1e3:9.3f}ms  "
                f"[{r['process']}]"
            )
    with open(a.out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    tracks = doc["otherData"]["tracks"]
    n_spans = sum(t["spans"] for t in tracks)
    print(f"merged {len(tracks)} process track(s), {n_spans} span(s) -> {a.out}")
    for t in tracks:
        print(
            f"  [{t['track']}] {t['process']}: {t['spans']} spans, "
            f"offset {t['mono_to_wall_offset_s']:.3f}s, "
            f"skew {t['skew_vs_server_s'] * 1e3:.3f}ms "
            f"({t['peer_anchor_exchanges']} anchor exchanges)"
        )
    print("open in Perfetto: https://ui.perfetto.dev -> Open trace file")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
