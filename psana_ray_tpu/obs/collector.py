"""Cluster federation: pull every process's metrics into ONE store.

PR 1 gave each process a ``/metrics`` island; ISSUE 13 makes the fleet
one pane. A :class:`ClusterCollector` polls a static peer list over the
EXISTING control surfaces:

- ``host:port`` / ``tcp://host:port`` — a queue server: the 'N' JSON
  RPC with ``{"op": "metrics"}`` answers its whole registry snapshot
  host-tagged (:func:`psana_ray_tpu.obs.registry.federation_payload`).
  A pre-ISSUE-13 server answers the op with ``{"ok": False, ...}`` —
  the peer is marked **degraded** loudly (breadcrumb + gauge), never
  silently dropped (the 'Z' old-peer precedent);
- ``http://host:port`` — a producer/consumer/sfx CLI's
  ``--metrics_port`` endpoint: ``GET /federate`` (same payload), with a
  ``/healthz`` fallback for peers predating the route (degraded: the
  snapshot still merges, host-tagged only by its address).

Each successful pull lands in a per-peer
:class:`~psana_ray_tpu.obs.timeseries.TimeSeriesStore` — the federated,
host-tagged series history that ``python -m psana_ray_tpu.obs.top``
renders and ROADMAP item 3's controller will read.

After every sweep the collector evaluates SLO alert rules over the
merged history (gateway error-budget burn rate, replication lag, stall
episodes). Alerts are EDGE-TRIGGERED flight-recorder breadcrumbs plus a
``degraded``-style active-alert gauge on the collector's own registry
source — firing is loud once, the gauge stays up for the episode.

Pure stdlib (urllib for the HTTP peers), importable without jax.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.obs.timeseries import DEFAULT_CAPACITY, TimeSeriesStore

__all__ = ["ClusterCollector", "PeerState", "parse_peer"]

# peer states (the collector's own gauge vocabulary)
PEER_UP = "up"
PEER_DEGRADED = "degraded"  # reachable but pre-federation (old peer)
PEER_DOWN = "down"

# alert kinds
ALERT_SLO_BURN = "slo_burn"
ALERT_REPLICATION_LAG = "replication_lag"
ALERT_STALL = "stall"

# error-budget burn-rate arithmetic (Google SRE workbook shape): over
# the short window, burn = (1 - measured attainment) / (1 - SLO
# target). Burning at 1.0 spends exactly the budget; the default
# threshold 2.0 = "at this rate the monthly budget is gone in half a
# month" — early, but the gateway's shed-don't-degrade design means a
# sustained burn is a real capacity signal, not noise.
DEFAULT_SLO_TARGET = 0.99
DEFAULT_BURN_THRESHOLD = 2.0
DEFAULT_BURN_WINDOW_S = 60.0
DEFAULT_REPL_LAG_RECORDS = 1000


def parse_peer(spec: str) -> Tuple[str, str]:
    """``spec`` -> (kind, address): ``tcp`` for ``host:port`` /
    ``tcp://host:port`` (queue server 'N' RPC), ``http`` for
    ``http://host:port`` (CLI metrics endpoint)."""
    spec = spec.strip()
    if not spec:
        raise ValueError("empty peer spec")
    if spec.startswith("http://") or spec.startswith("https://"):
        return "http", spec.rstrip("/")
    if spec.startswith("tcp://"):
        spec = spec[len("tcp://"):]
    host, sep, port = spec.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(
            f"peer spec {spec!r} is not host:port / tcp://host:port / "
            f"http://host:port"
        )
    return "tcp", f"{host}:{port}"


class _Peer:
    """One federated peer: its pull transport + series store + state."""

    def __init__(self, spec: str, capacity: int):
        self.kind, self.address = parse_peer(spec)
        self.label = self.address if self.kind == "tcp" else spec.rstrip("/")
        self.store = TimeSeriesStore(capacity)
        self.state = PEER_DOWN  # until the first successful pull
        self.host = ""
        self.pid = 0
        self.worker = None  # queue-server worker id (ISSUE 17), or None
        self.profile = None  # last profile summary (ISSUE 16), or None
        self.last_pull_wall = 0.0
        self.last_error = ""
        self.pulls_ok = 0
        self.pulls_failed = 0
        self._client = None  # persistent TCP control connection

    # -- pull transports ---------------------------------------------------
    def _pull_tcp(self, timeout_s: float) -> dict:
        from psana_ray_tpu.transport.tcp import TcpQueueClient

        if self._client is None:
            host, _, port = self.address.rpartition(":")
            # fail-fast dial: the collector must mark a dead peer DOWN
            # within one sweep, not ride the reconnect envelope
            self._client = TcpQueueClient(
                host, int(port), timeout_s=timeout_s, reconnect_tries=0
            )
        return self._client.cluster_rpc({"op": "metrics"})

    def _pull_http(self, timeout_s: float) -> dict:
        try:
            with urllib.request.urlopen(
                f"{self.address}/federate", timeout=timeout_s
            ) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
        # old peer: no /federate route — merge its /healthz snapshot,
        # host-tagged only by address (caller marks the peer degraded)
        with urllib.request.urlopen(
            f"{self.address}/healthz", timeout=timeout_s
        ) as resp:
            return {"ok": True, "_healthz_fallback": True,
                    "metrics": json.loads(resp.read().decode())}

    def drop_client(self) -> None:
        client, self._client = self._client, None
        if client is not None:
            try:
                client.disconnect()
            except Exception:  # noqa: BLE001 — already failing
                pass

    def pull(self, timeout_s: float) -> dict:
        if self.kind == "tcp":
            return self._pull_tcp(timeout_s)
        return self._pull_http(timeout_s)


class PeerState:
    """Read-model row for one peer (what the console renders)."""

    __slots__ = (
        "label", "kind", "state", "host", "pid", "worker", "age_s", "error",
        "profile",
    )

    def __init__(self, peer: _Peer, now: float):
        self.label = peer.label
        self.kind = peer.kind
        self.state = peer.state
        self.host = peer.host
        self.pid = peer.pid
        self.worker = peer.worker
        self.profile = peer.profile
        self.age_s = (now - peer.last_pull_wall) if peer.last_pull_wall else -1.0
        self.error = peer.last_error


class ClusterCollector:
    """Poll the peer list; merge into host-tagged series; alert on SLO
    burn. ``poll_once`` is separated from the thread loop so tests (and
    ``obs.top --once``) drive sweeps explicitly."""

    def __init__(
        self,
        peers: List[str],
        interval_s: float = 2.0,
        capacity: int = DEFAULT_CAPACITY,
        pull_timeout_s: float = 5.0,
        slo_target: float = DEFAULT_SLO_TARGET,
        burn_threshold: float = DEFAULT_BURN_THRESHOLD,
        burn_window_s: float = DEFAULT_BURN_WINDOW_S,
        repl_lag_records: int = DEFAULT_REPL_LAG_RECORDS,
        register: bool = True,
    ):
        if not peers:
            raise ValueError("collector needs at least one peer")
        self.interval_s = float(interval_s)
        self.pull_timeout_s = float(pull_timeout_s)
        self.slo_target = float(slo_target)
        self.burn_threshold = float(burn_threshold)
        self.burn_window_s = float(burn_window_s)
        self.repl_lag_records = int(repl_lag_records)
        self._lock = threading.Lock()
        self._peers: Dict[str, _Peer] = {}  # guarded-by: _lock
        for spec in peers:
            p = _Peer(spec, capacity)
            self._peers[p.label] = p
        self._sweeps = 0  # guarded-by: _lock
        self._alerts_fired = 0  # guarded-by: _lock
        self._active_alerts: Dict[Tuple[str, str], float] = {}  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if register:
            try:
                from psana_ray_tpu.obs.registry import MetricsRegistry

                MetricsRegistry.default().register("collector", self)
            except Exception:  # noqa: BLE001 — obs optional
                pass

    # -- one sweep ---------------------------------------------------------
    def poll_once(self, now: Optional[float] = None) -> Dict[str, str]:
        """Pull every peer once; returns ``{peer_label: state}``. Peer
        transitions (up -> down, up -> degraded) leave breadcrumbs —
        degrade loudly, never die: one dead peer must not blind the
        pane."""
        now = time.time() if now is None else now
        with self._lock:
            peers = list(self._peers.values())
        states: Dict[str, str] = {}
        for peer in peers:
            prev = peer.state
            try:
                payload = peer.pull(self.pull_timeout_s)
            except Exception as e:  # noqa: BLE001 — a dead peer is DATA
                peer.drop_client()
                peer.state = PEER_DOWN
                peer.last_error = repr(e)
                peer.pulls_failed += 1
            else:
                if payload.get("ok"):
                    metrics = payload.get("metrics")
                    peer.store.record(
                        metrics if isinstance(metrics, dict) else {}, now=now
                    )
                    peer.host = payload.get("host", peer.host) or peer.host
                    peer.pid = int(payload.get("pid", peer.pid) or 0)
                    # worker tag (ISSUE 17): this peer's pinned TCP
                    # connection always answers from the same forked
                    # worker, so the tag is stable per peer
                    w = payload.get("worker")
                    peer.worker = int(w) if w is not None else None
                    prof = payload.get("profile")
                    peer.profile = prof if isinstance(prof, dict) else None
                    peer.last_pull_wall = now
                    peer.last_error = ""
                    peer.pulls_ok += 1
                    peer.state = (
                        PEER_DEGRADED
                        if payload.get("_healthz_fallback")
                        else PEER_UP
                    )
                else:
                    # an old queue server: 'N' answered, but not the
                    # metrics op — reachable yet pre-federation
                    peer.state = PEER_DEGRADED
                    peer.last_error = str(payload.get("error", "refused"))
                    peer.pulls_failed += 1
            if peer.state != prev and peer.state != PEER_UP:
                FLIGHT.record(
                    "collector_peer_" + peer.state,
                    peer=peer.label, error=peer.last_error,
                )
            states[peer.label] = peer.state
        with self._lock:
            self._sweeps += 1
        self._evaluate_alerts(now, peers)
        return states

    # -- SLO burn-rate alerts ---------------------------------------------
    def _burn_rate(self, store: TimeSeriesStore) -> Optional[float]:
        """Error-budget burn over the short window from the gateway's
        goodput/completed counters (None without gateway activity)."""
        good = store.delta("gateway.goodput_total", self.burn_window_s)
        done = store.delta("gateway.completed_total", self.burn_window_s)
        if good is None or done is None or done <= 0:
            return None
        attainment = good / done
        budget = max(1e-6, 1.0 - self.slo_target)
        return (1.0 - attainment) / budget

    def _evaluate_alerts(self, now: float, peers: List[_Peer]) -> None:
        for peer in peers:
            store = peer.store
            burn = self._burn_rate(store)
            self._set_alert(
                peer.label, ALERT_SLO_BURN,
                burn is not None and burn >= self.burn_threshold,
                now, value=round(burn, 2) if burn is not None else None,
            )
            lag = store.last("replication.lag_records")
            self._set_alert(
                peer.label, ALERT_REPLICATION_LAG,
                lag is not None and lag >= self.repl_lag_records,
                now, value=lag,
            )
            stalled = store.last("stalls.degraded")
            self._set_alert(
                peer.label, ALERT_STALL, bool(stalled), now, value=stalled
            )

    def _set_alert(
        self, peer: str, kind: str, firing: bool, now: float, value=None
    ) -> None:
        key = (peer, kind)
        with self._lock:
            active = key in self._active_alerts
            if firing and not active:
                self._active_alerts[key] = now
                self._alerts_fired += 1
            elif not firing and active:
                del self._active_alerts[key]
            else:
                return
        if firing:  # edge: one breadcrumb per episode, like the stall detector
            FLIGHT.record("slo_alert", alert=kind, peer=peer, value=value)
        else:
            FLIGHT.record("slo_alert_cleared", alert=kind, peer=peer)

    # -- reads (console / controller / tests) ------------------------------
    def peers(self) -> List[PeerState]:
        now = time.time()
        with self._lock:
            return [PeerState(p, now) for p in self._peers.values()]

    def store(self, label: str) -> Optional[TimeSeriesStore]:
        with self._lock:
            p = self._peers.get(label)
            return p.store if p is not None else None

    def stores(self) -> Dict[str, TimeSeriesStore]:
        with self._lock:
            return {label: p.store for label, p in self._peers.items()}

    def active_alerts(self) -> List[dict]:
        now = time.time()
        with self._lock:
            return [
                {"peer": peer, "alert": kind, "for_s": round(now - since, 1)}
                for (peer, kind), since in sorted(self._active_alerts.items())
            ]

    # -- background loop ---------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the pane must outlive a bad sweep
                pass

    def start(self) -> "ClusterCollector":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="cluster-collector"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._lock:
            peers = list(self._peers.values())
        for p in peers:
            p.drop_client()

    def __enter__(self) -> "ClusterCollector":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- registry source ---------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            peers = list(self._peers.values())
            sweeps = self._sweeps
            fired = self._alerts_fired
            active = len(self._active_alerts)
        up = sum(1 for p in peers if p.state == PEER_UP)
        degraded = sum(1 for p in peers if p.state == PEER_DEGRADED)
        down = sum(1 for p in peers if p.state == PEER_DOWN)
        return {
            "peers": len(peers),
            "peers_up": up,
            "peers_degraded": degraded,
            "peers_down": down,
            "sweeps_total": sweeps,
            "alerts_fired_total": fired,
            "alerts_active": active,
            "pulls_ok_total": sum(p.pulls_ok for p in peers),
            "pulls_failed_total": sum(p.pulls_failed for p in peers),
        }
