"""Cluster-wide observability: metrics export, stage timing, stall detection.

The three legs (ISSUE 1 / SURVEY.md §5 — the reference has no
observability story at all):

- **Export** — :class:`MetricsRegistry` aggregates every process-local
  metrics object and serves Prometheus text format over a stdlib HTTP
  thread (:class:`MetricsServer`, ``--metrics_port`` on every CLI);
- **Stage timing** — :mod:`psana_ray_tpu.obs.stages` names the pipeline
  boundaries; monotonic hop stamps threaded through the record envelope
  decompose end-to-end latency into per-stage histograms;
- **Health** — :class:`StallDetector` turns queue counters into
  structured backpressure / stall / liveness warnings, and the queue
  server answers a stats RPC (``transport.tcp`` opcode ``T``).

Plus the per-frame layer (ISSUE 4):

- **Tracing** — :mod:`psana_ray_tpu.obs.tracing`: sampled per-frame
  distributed traces across producer/queue-server/consumer, merged into
  a Perfetto-loadable timeline by ``python -m psana_ray_tpu.obs.
  trace_merge``;
- **Flight recorder** — :mod:`psana_ray_tpu.obs.flight`: bounded event
  ring + dump-on-stall/exception/SIGUSR2 postmortem black box.

And the telemetry plane (ISSUE 13):

- **History** — :mod:`psana_ray_tpu.obs.timeseries`: a bounded,
  zero-alloc-on-sample ring of periodic registry snapshots per process
  (rates/percentiles computed at read time; flight dumps append the
  tail);
- **Federation** — :mod:`psana_ray_tpu.obs.collector`: one collector
  pulls every queue server ('N' metrics RPC) and CLI (``/federate``)
  into a host-tagged series store, with SLO burn-rate alerts;
- **Console** — ``python -m psana_ray_tpu.obs.top``: the live fleet
  pane over the federated history (``--once`` for scripts/tests);
- **Exemplars** — latency histograms retain a sampled trace id per
  bucket; ``trace_merge --exemplar <id>`` resolves a bad bucket to the
  frame's merged cross-host timeline.

And the continuous profiling plane (ISSUE 16):

- **Flame sampling** — :mod:`psana_ray_tpu.obs.profiling`: an always-on
  97 Hz stack sampler folding every thread into a bounded zero-alloc
  trie, with on-CPU/waiting discrimination and per-stage attribution
  via the obs/stages vocabulary;
- **Cost model** — the ``prof`` registry source: per-process cpu_frac,
  per-stage cpu_ms, and cpu_ns_per_frame / py_bytes_per_frame against
  the wire counters;
- **Merge** — ``python -m psana_ray_tpu.obs.prof_merge``: cluster-wide
  flamegraphs (collapsed/speedscope) and cpu_frac counter tracks
  overlaid on the trace_merge Perfetto timeline.

Everything here is pure stdlib and importable without JAX.
"""

from psana_ray_tpu.obs.exporter import (  # noqa: F401
    MetricsServer,
    add_metrics_args,
    start_metrics_server,
)
from psana_ray_tpu.obs.registry import MetricsRegistry, snapshot_source  # noqa: F401
from psana_ray_tpu.obs.stages import (  # noqa: F401
    STAGE_BATCH,
    STAGE_DEQUEUE,
    STAGE_DEVICE_PUT,
    STAGE_DISPATCH,
    STAGE_E2E,
    STAGE_ENQUEUE,
    STAGE_QUEUE_DWELL,
    STAGES,
    StageTimes,
    observe_batch_stages,
    observe_record_stages,
)
from psana_ray_tpu.obs.stall import (  # noqa: F401
    EVENT_BACKPRESSURE,
    EVENT_CONSUMER_STALL,
    EVENT_PRODUCER_IDLE,
    StallDetector,
    StallEvent,
)
from psana_ray_tpu.obs.flight import FLIGHT, FlightRecorder  # noqa: F401
from psana_ray_tpu.obs.timeseries import (  # noqa: F401
    HistorySampler,
    SeriesRing,
    TimeSeriesStore,
    add_history_args,
    configure_history_from_args,
    default_history,
)
from psana_ray_tpu.obs.collector import ClusterCollector  # noqa: F401
from psana_ray_tpu.obs.profiling import (  # noqa: F401
    FlameSampler,
    ProfTelemetry,
    StackTrie,
    add_profile_args,
    configure_profiling_from_args,
    default_profiler,
    profile_summary,
    profile_top,
    start_default_profiler,
    stop_default_profiler,
)
from psana_ray_tpu.obs.tracing import (  # noqa: F401
    TRACER,
    TraceContext,
    Tracer,
    add_trace_args,
    configure_from_args as configure_tracing_from_args,
    emit_batch_spans,
    exchange_anchors,
    obs_status_suffix,
)
