"""Cluster-wide observability: metrics export, stage timing, stall detection.

The three legs (ISSUE 1 / SURVEY.md §5 — the reference has no
observability story at all):

- **Export** — :class:`MetricsRegistry` aggregates every process-local
  metrics object and serves Prometheus text format over a stdlib HTTP
  thread (:class:`MetricsServer`, ``--metrics_port`` on every CLI);
- **Stage timing** — :mod:`psana_ray_tpu.obs.stages` names the pipeline
  boundaries; monotonic hop stamps threaded through the record envelope
  decompose end-to-end latency into per-stage histograms;
- **Health** — :class:`StallDetector` turns queue counters into
  structured backpressure / stall / liveness warnings, and the queue
  server answers a stats RPC (``transport.tcp`` opcode ``T``).

Everything here is pure stdlib and importable without JAX.
"""

from psana_ray_tpu.obs.exporter import (  # noqa: F401
    MetricsServer,
    add_metrics_args,
    start_metrics_server,
)
from psana_ray_tpu.obs.registry import MetricsRegistry, snapshot_source  # noqa: F401
from psana_ray_tpu.obs.stages import (  # noqa: F401
    STAGE_BATCH,
    STAGE_DEQUEUE,
    STAGE_DEVICE_PUT,
    STAGE_DISPATCH,
    STAGE_E2E,
    STAGE_ENQUEUE,
    STAGE_QUEUE_DWELL,
    STAGES,
    StageTimes,
    observe_batch_stages,
    observe_record_stages,
)
from psana_ray_tpu.obs.stall import (  # noqa: F401
    EVENT_BACKPRESSURE,
    EVENT_CONSUMER_STALL,
    EVENT_PRODUCER_IDLE,
    StallDetector,
    StallEvent,
)
