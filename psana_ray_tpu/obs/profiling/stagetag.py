"""Thread-local pipeline-stage tags for the continuous profiler.

The flame sampler (:mod:`psana_ray_tpu.obs.profiling.sampler`) bills
every stack sample to the CANONICAL stage vocabulary the latency
histograms already speak (:data:`psana_ray_tpu.obs.stages.STAGES`):
each worker thread publishes "which stage am I executing right now" as
one small-int tag in a plain dict keyed by thread ident, written at the
EXISTING instrumentation points (the producer put path, the consumer
drain loop, ``annotate_stage`` device regions, the event-loop dispatch
pass). The sampler reads the dict from its own thread — a
``threading.local`` would hide the value from the reader, so the tag
table is deliberately a shared dict: CPython dict stores are atomic
under the GIL, and overwriting an existing key allocates nothing.

Tags are SMALL INTS (0..N_TAGS-1, all in CPython's small-int cache) so
setting one on the per-record hot path is a single dict store with zero
allocation. Tag 0 is "untagged": threads that never declared a stage
(interpreter main thread, import machinery, third-party pools) bill
there, and the ISSUE 16 attribution acceptance measures how little of
the busy pipeline that is.

This module imports NOTHING project-side (only ``threading``) so the
transport and infeed layers can tag unconditionally without import
cycles; a test pins ``TAG_NAMES[1:]`` to ``obs.stages.STAGES`` so the
vocabularies cannot drift apart.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = [
    "TAG_UNTAGGED",
    "TAG_ENQUEUE",
    "TAG_QUEUE_DWELL",
    "TAG_DEQUEUE",
    "TAG_BATCH",
    "TAG_DEVICE_PUT",
    "TAG_DISPATCH",
    "TAG_NAMES",
    "TAG_OF_STAGE",
    "N_TAGS",
    "set_stage",
    "swap_stage",
    "current_tag",
    "clear_thread",
    "stage_region",
]

# Tag ids: 0 = no declared stage; 1.. mirror obs.stages.STAGES order
# (pinned by tests/test_profiling.py so the vocabularies cannot drift).
TAG_UNTAGGED = 0
TAG_ENQUEUE = 1
TAG_QUEUE_DWELL = 2
TAG_DEQUEUE = 3
TAG_BATCH = 4
TAG_DEVICE_PUT = 5
TAG_DISPATCH = 6

TAG_NAMES = (
    "untagged",
    "enqueue",
    "queue_dwell",
    "dequeue",
    "batch",
    "device_put",
    "dispatch",
)
N_TAGS = len(TAG_NAMES)

#: stage name -> tag id (the ``annotate_stage`` bridge; unknown names
#: map to untagged rather than raising — a new stage name must never
#: break the data path it instruments).
TAG_OF_STAGE = {name: i for i, name in enumerate(TAG_NAMES)}

# thread ident -> tag id. Written by the tagged thread, read by the
# sampler thread; single dict store / lookup per operation, GIL-atomic.
_TAGS: Dict[int, int] = {}


def set_stage(tag: int) -> None:
    """Declare the calling thread's current stage (hot path: one dict
    store of a cached small int, no allocation on an existing key)."""
    _TAGS[threading.get_ident()] = tag


def swap_stage(tag: int) -> int:
    """Set the calling thread's tag and return the PREVIOUS one (0 when
    none) — the save/restore half used by scoped instrumentation so
    nested stages unwind correctly."""
    ident = threading.get_ident()
    prev = _TAGS.get(ident, TAG_UNTAGGED)
    _TAGS[ident] = tag
    return prev


def current_tag(ident: Optional[int] = None) -> int:
    """The tag a thread last declared (its own by default)."""
    if ident is None:
        ident = threading.get_ident()
    return _TAGS.get(ident, TAG_UNTAGGED)


def clear_thread(ident: Optional[int] = None) -> None:
    """Drop a thread's entry (sampler GC for dead threads; tests)."""
    _TAGS.pop(threading.get_ident() if ident is None else ident, None)


class stage_region:
    """Context manager: tag the calling thread with a stage FOR THE
    SCOPE, optionally wrapping an inner context manager (the device
    profiler's ``TraceAnnotation`` in ``utils.trace.annotate_stage``) so
    one ``with`` statement feeds both the device timeline and the
    continuous profiler. Restores the previous tag on exit — nested
    regions (dispatch > device_put) unwind to the enclosing stage."""

    __slots__ = ("_tag", "_inner", "_prev")

    def __init__(self, stage: str, inner=None):
        self._tag = TAG_OF_STAGE.get(stage, TAG_UNTAGGED)
        self._inner = inner
        self._prev = TAG_UNTAGGED

    def __enter__(self):
        self._prev = swap_stage(self._tag)
        if self._inner is not None:
            self._inner.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            if self._inner is not None:
                return self._inner.__exit__(exc_type, exc, tb)
            return False
        finally:
            set_stage(self._prev)
