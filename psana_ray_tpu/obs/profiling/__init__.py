"""Continuous profiling plane (ISSUE 16).

Always-on, low-overhead CPU attribution for every pipeline process:

- :mod:`~psana_ray_tpu.obs.profiling.sampler` — 97 Hz flame sampler
  folding every thread's stack into a bounded, allocation-free trie,
  with per-thread on-CPU/waiting discrimination;
- :mod:`~psana_ray_tpu.obs.profiling.stagetag` — thread-local stage
  tags set at the existing obs/stages instrumentation points, so
  samples bill to the enqueue/dequeue/batch/device_put vocabulary;
- :mod:`~psana_ray_tpu.obs.profiling.costmodel` — the ``prof``
  telemetry source: cpu_frac, per-stage cpu_ms, cpu_ns_per_frame and
  py_bytes_per_frame against the wire counters;
- :mod:`~psana_ray_tpu.obs.profiling.export` — collapsed-stack /
  speedscope / spool dumps, merged cluster-wide by
  ``python -m psana_ray_tpu.obs.prof_merge``.

This package mirrors the process-global idiom of
``obs.timeseries``: one default sampler per process
(:func:`start_default_profiler` / :func:`default_profiler`), CLI flags
via :func:`add_profile_args` (``--profile_hz 0`` = off), and
best-effort read hooks (:func:`profile_top`, :func:`profile_summary`)
that return ``None`` instead of raising when profiling is off — flight
dumps and federation must never fail because the profiler is absent.
"""

from __future__ import annotations

import argparse
import atexit
import threading
from typing import Optional

from psana_ray_tpu.obs.profiling.stagetag import (  # noqa: F401
    N_TAGS,
    TAG_BATCH,
    TAG_DEQUEUE,
    TAG_DEVICE_PUT,
    TAG_DISPATCH,
    TAG_ENQUEUE,
    TAG_NAMES,
    TAG_OF_STAGE,
    TAG_QUEUE_DWELL,
    TAG_UNTAGGED,
    current_tag,
    set_stage,
    stage_region,
    swap_stage,
)
from psana_ray_tpu.obs.profiling.costmodel import ProfTelemetry  # noqa: F401
from psana_ray_tpu.obs.profiling.sampler import (  # noqa: F401
    DEFAULT_HZ,
    FlameSampler,
    StackTrie,
)
from psana_ray_tpu.obs.profiling.export import (  # noqa: F401
    collapsed_lines,
    frame_label,
    load_spool,
    parse_collapsed,
    speedscope_doc,
    spool_doc,
    write_spool,
)

__all__ = [
    "DEFAULT_HZ",
    "FlameSampler",
    "StackTrie",
    "ProfTelemetry",
    "stage_region",
    "set_stage",
    "swap_stage",
    "current_tag",
    "TAG_NAMES",
    "collapsed_lines",
    "parse_collapsed",
    "speedscope_doc",
    "spool_doc",
    "write_spool",
    "load_spool",
    "frame_label",
    "default_profiler",
    "start_default_profiler",
    "stop_default_profiler",
    "profile_top",
    "profile_summary",
    "add_profile_args",
    "configure_profiling_from_args",
]


# -- process-global wiring ---------------------------------------------------
_default_lock = threading.Lock()
_default_sampler: Optional[FlameSampler] = None
_atexit_armed = False


def default_profiler() -> Optional[FlameSampler]:
    """The process's flame sampler, or None when profiling is off (the
    flight recorder and federation ask on every dump — an absent
    profiler must cost nothing and fail nothing)."""
    with _default_lock:
        return _default_sampler


def start_default_profiler(
    hz: float = DEFAULT_HZ,
    spool_dir: Optional[str] = None,
    process: str = "",
    registry=None,
) -> FlameSampler:
    """Start (or return) THE process-global sampler, register the
    ``prof`` source, and arm an atexit spool dump when ``spool_dir`` is
    set. Idempotent: the first caller's hz/spool_dir win."""
    global _default_sampler, _atexit_armed
    with _default_lock:
        if _default_sampler is None:
            _default_sampler = FlameSampler(
                hz=hz, process=process, spool_dir=spool_dir, registry=registry
            ).start()
            if not _atexit_armed:
                _atexit_armed = True
                atexit.register(stop_default_profiler)
        return _default_sampler


def stop_default_profiler() -> None:
    """Stop + forget the process-global sampler, writing its spool when
    one was requested (also the atexit hook; tests call it directly)."""
    global _default_sampler
    with _default_lock:
        sampler, _default_sampler = _default_sampler, None
    if sampler is not None:
        sampler.stop()


# -- best-effort read hooks (flight dumps, federation) -----------------------
def profile_top(n: int = 16) -> Optional[dict]:
    """Top-``n`` hot frames + per-stage cpu_ms from the live default
    sampler; ``None`` when profiling is off (flight dumps embed the
    result verbatim)."""
    s = default_profiler()
    if s is None:
        return None
    trie = s.trie
    return {
        "hz": s.hz,
        "samples": trie.samples_total,
        "on_cpu": trie.on_cpu_total,
        "waiting": trie.waiting_total,
        "hot": trie.hot_frames(n),
        "stage_cpu_ms": s.stage_cpu_ms(),
    }


def profile_summary(top_n: int = 5) -> Optional[dict]:
    """The compact per-process summary that rides
    ``federation_payload`` (OUTSIDE the numeric ``metrics`` tree —
    frame names are strings and the metric grammar drops strings):
    CPU%, per-frame cost, and the hottest frames with self-sample
    percentages. ``None`` when profiling is off."""
    s = default_profiler()
    if s is None:
        return None
    trie = s.trie
    tel = s.telemetry
    on = trie.on_cpu_total
    hot = []
    for h in trie.hot_frames(top_n):
        hot.append(
            {
                "frame": h["frame"],
                "self": h["self"],
                "pct": (100.0 * h["self"] / on) if on else 0.0,
            }
        )
    return {
        "hz": s.hz,
        "samples": trie.samples_total,
        "on_cpu": on,
        "cpu_frac": tel.cpu_frac,
        "cpu_ns_per_frame": tel.cpu_ns_per_frame,
        "py_bytes_per_frame": tel.py_bytes_per_frame,
        "hot": hot,
        "stage_cpu_ms": s.stage_cpu_ms(),
    }


# -- CLI wiring --------------------------------------------------------------
def add_profile_args(parser) -> None:
    """The shared ``--profile_hz`` / ``--profile_dir`` pair every
    long-running CLI exposes (one definition, like
    ``add_history_args``)."""
    parser.add_argument(
        "--profile_hz", type=float, default=DEFAULT_HZ,
        help="continuous-profiler sample rate in Hz (flame sampler + "
        "per-frame cost model; feeds flight dumps, federation, and "
        "`python -m psana_ray_tpu.obs.prof_merge`); 0 = off",
    )
    try:
        parser.add_argument(
            "--profile_dir", default=None,
            help="write a per-process profile spool "
            "(<process>-<pid>.prof.json) here on exit, mergeable with "
            "`python -m psana_ray_tpu.obs.prof_merge` (default: no spool)",
        )
    except argparse.ArgumentError:
        # the consumer CLI already owns --profile_dir (jax device-trace
        # logdir); the one directory serves both outputs — device traces
        # land in timestamped subdirs, the CPU spool beside them
        pass


def configure_profiling_from_args(args, process: str = "") -> Optional[FlameSampler]:
    """CLI entry: start the process-global profiler from the
    ``add_profile_args`` flags (None when ``--profile_hz 0``)."""
    hz = getattr(args, "profile_hz", 0.0) or 0.0
    if hz <= 0:
        return None
    return start_default_profiler(
        hz=hz,
        spool_dir=getattr(args, "profile_dir", None),
        process=process,
    )
