"""Profile export formats: collapsed stacks, speedscope, spool JSON.

Three consumers, three formats, one source of truth (the sampler's
:class:`~psana_ray_tpu.obs.profiling.sampler.StackTrie`):

- **collapsed** — Brendan Gregg's ``stage;frame;frame count`` lines,
  pipeable straight into ``flamegraph.pl`` or ``inferno``;
- **speedscope** — the https://speedscope.app sampled-profile JSON, for
  interactive drill-down without any local tooling;
- **spool** — the repo's own merge format: trie rows plus the clock
  anchors (wall, mono pairs — the same alignment contract
  ``obs.trace_merge`` uses) and the 1 Hz cpu_frac timeline, written per
  process as ``<dir>/<process>-<pid>.prof.json`` and merged across a
  cluster by ``python -m psana_ray_tpu.obs.prof_merge``.

Stage names ride as the FIRST frame of every collapsed/speedscope
stack, so stage attribution survives round-trips through tools that
know nothing about this repo's vocabulary.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "frame_label",
    "collapsed_lines",
    "parse_collapsed",
    "speedscope_doc",
    "spool_doc",
    "write_spool",
    "load_spool",
]


def frame_label(code) -> str:
    """``file.py:qualname:lineno`` — the display key for one frame."""
    name = getattr(code, "co_qualname", None) or code.co_name
    return "%s:%s:%d" % (os.path.basename(code.co_filename), name, code.co_firstlineno)


def collapsed_lines(trie, waiting: bool = False) -> List[str]:
    """Collapsed-stack lines (on-CPU counts by default; ``waiting=True``
    exports the off-CPU flame instead)."""
    key = "off" if waiting else "on"
    out: List[str] = []
    for row in trie.rows():
        count = row[key]
        if count <= 0:
            continue
        parts = [row["stage"]]
        parts.extend(row["frames"])
        out.append("%s %d" % (";".join(parts), count))
    return out


def parse_collapsed(lines) -> List[Tuple[List[str], int]]:
    """Inverse of :func:`collapsed_lines` (round-trip tests, ingest)."""
    out: List[Tuple[List[str], int]] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        stack_s, _, count_s = line.rpartition(" ")
        out.append((stack_s.split(";"), int(count_s)))
    return out


def speedscope_doc(trie, name: str = "psana-ray-tpu", waiting: bool = False) -> dict:
    """A speedscope "sampled" profile: one sample per distinct
    (stage, stack) path, weighted by its count."""
    key = "off" if waiting else "on"
    frames: List[dict] = []
    index: Dict[str, int] = {}
    samples: List[List[int]] = []
    weights: List[int] = []

    def fid(label: str) -> int:
        i = index.get(label)
        if i is None:
            i = len(frames)
            index[label] = i
            frames.append({"name": label})
        return i

    total = 0
    for row in trie.rows():
        count = row[key]
        if count <= 0:
            continue
        stack = [fid("stage: %s" % row["stage"])]
        stack.extend(fid(lbl) for lbl in row["frames"])
        samples.append(stack)
        weights.append(count)
        total += count
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [
            {
                "type": "sampled",
                "name": name,
                "unit": "none",
                "startValue": 0,
                "endValue": total,
                "samples": samples,
                "weights": weights,
            }
        ],
        "exporter": "psana_ray_tpu.obs.profiling",
        "name": name,
    }


def spool_doc(sampler) -> dict:
    """The mergeable per-process profile document."""
    trie = sampler.trie
    anchors = list(sampler.anchors)
    # a fresh anchor at dump time bounds clock drift over long runs
    anchors.append({"wall": time.time(), "mono": time.monotonic()})
    return {
        "kind": "psana_ray_tpu.prof_spool",
        "version": 1,
        "meta": {
            "process": sampler.process,
            "pid": os.getpid(),
            "hz": sampler.hz,
            "start_wall": sampler.start_wall,
            "start_mono": sampler.start_mono,
        },
        "anchors": anchors,
        "totals": {
            "samples": trie.samples_total,
            "on_cpu": trie.on_cpu_total,
            "waiting": trie.waiting_total,
            "nodes": trie.n_nodes,
            "overflow": trie.overflow_total,
        },
        "stage_totals": trie.stage_totals(),
        "stage_cpu_ms": sampler.stage_cpu_ms(),
        "cpu_series": [[t, v] for t, v in sampler.telemetry.cpu_timeline()],
        "stacks": trie.rows(),
    }


def write_spool(sampler, directory: Optional[str] = None, path: Optional[str] = None) -> str:
    """Serialise a sampler's spool to ``path`` or
    ``<directory>/<process>-<pid>.prof.json``; returns the path."""
    if path is None:
        directory = directory or sampler.spool_dir or "."
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "%s-%d.prof.json" % (sampler.process, os.getpid()))
    doc = spool_doc(sampler)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def load_spool(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("kind") != "psana_ray_tpu.prof_spool":
        raise ValueError("%s is not a psana_ray_tpu profile spool" % path)
    return doc
