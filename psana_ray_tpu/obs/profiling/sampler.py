"""Continuous flame sampler: bounded stack trie + 97 Hz daemon thread.

The host datapath's ceiling is Python CPU (PERF_NOTES: ~340 fps
passthrough vs 28.2k fps/chip device-side), but until this PR nothing
measured WHERE that CPU goes. This module is the always-on half of the
answer: a daemon thread wakes ~97 times a second (off-aligned from the
100 Hz USER_HZ tick and from 1 Hz telemetry scrapes, so it never beats
against either), snapshots every thread's Python stack via
``sys._current_frames()``, and folds each stack into a bounded trie —
preallocated ``array`` columns for parent/key/counts, one interned
code-object key per frame — so the steady state allocates NOTHING and
the whole profile lives in a few hundred KB regardless of runtime.

Two discriminators keep the flame honest:

- **on-CPU vs waiting** — per-thread CPU time read from
  ``/proc/self/task/<tid>/stat`` (utime+stime, one ``os.pread`` of a
  cached fd per thread per sample; the clock equivalent of
  ``CLOCK_THREAD_CPUTIME_ID`` without a per-call syscall wrapper
  allocation). A thread whose CPU ticks did not advance since the last
  sample was waiting (GIL, select, queue get) and bills to the ``off``
  column — so blocked threads don't pollute the on-CPU flame. Where
  procfs is unavailable the sampler degrades to counting every sample
  as on-CPU rather than failing.
- **stage tags** — each sample bills to the
  :mod:`~psana_ray_tpu.obs.profiling.stagetag` tag its thread last
  declared, so the profile decomposes into the same
  enqueue/dequeue/batch/device_put vocabulary the latency histograms
  speak.

Sampling-loop functions are marked ``# lint: sample-path`` and kept
allocation-free by construction (the telemetry-discipline checker
enforces it); first-sight growth (new code object, new trie path, new
thread) happens in unmarked helpers, mirroring ``SeriesRing`` /
``TimeSeriesStore.record``. ``tests/test_profiling.py`` pins the
steady state with ``sys.getallocatedblocks``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from array import array
from typing import Dict, List, Optional

from psana_ray_tpu.obs.profiling.stagetag import (
    N_TAGS,
    TAG_NAMES,
    _TAGS,
    clear_thread,
)
from psana_ray_tpu.obs.profiling.costmodel import ProfTelemetry

__all__ = ["StackTrie", "FlameSampler", "DEFAULT_HZ", "DEFAULT_MAX_NODES", "DEFAULT_MAX_DEPTH"]

#: Default sample rate. 97 is prime and off-aligned from the kernel's
#: 100 Hz accounting tick and the 1 Hz history sampler, so the profiler
#: neither aliases against scheduler quanta nor synchronises with other
#: periodic work (the classic "everything looks idle at the tick" trap).
DEFAULT_HZ = 97.0
DEFAULT_MAX_NODES = 8192
DEFAULT_MAX_DEPTH = 64


class StackTrie:
    """Bounded call-stack trie with preallocated count columns.

    Nodes are rows in parallel ``array`` columns (parent link, interned
    key, on-CPU count, waiting count); children are per-node dicts
    keyed by ``id(code)`` — the interned key — which stay hit-only once
    every hot path has been seen, so :meth:`sample` is allocation-free
    at steady state. The trie is rooted at one synthetic node per stage
    tag (negative keys), so (stage, stack) is a single path and export
    needs no join. When ``max_nodes`` is exhausted new paths bill to
    their deepest existing prefix and ``overflow_total`` counts what
    was truncated — a full trie degrades the profile, never the
    process.

    Single-writer by design: only the sampler thread calls
    :meth:`sample`; readers (exports, snapshots) tolerate a count
    landing one sample late rather than taking a lock on the hot path.
    """

    __slots__ = (
        "_cap",
        "_max_depth",
        "_parent",
        "_key",
        "_on",
        "_off",
        "_kids",
        "_code",
        "_stack",
        "_stage_root",
        "_stage_on",
        "_stage_off",
        "_n",
        "samples_total",
        "on_cpu_total",
        "waiting_total",
        "overflow_total",
    )

    def __init__(self, max_nodes: int = DEFAULT_MAX_NODES, max_depth: int = DEFAULT_MAX_DEPTH):
        cap = max(int(max_nodes), N_TAGS + 1)
        self._cap = cap
        self._max_depth = max(int(max_depth), 4)
        self._parent = array("l", [-1]) * cap
        self._key = array("q", [0]) * cap
        self._on = array("q", [0]) * cap
        self._off = array("q", [0]) * cap
        self._kids: List[Dict[int, int]] = []
        self._code: Dict[int, object] = {}  # id(code) -> code (keeps keys unique)
        self._stack = array("q", [0]) * self._max_depth
        self._stage_on = array("q", [0]) * N_TAGS
        self._stage_off = array("q", [0]) * N_TAGS
        self._n = 0
        self.samples_total = 0
        self.on_cpu_total = 0
        self.waiting_total = 0
        self.overflow_total = 0
        # one root per stage tag, key = -(tag + 1) (negative sentinel:
        # can never collide with an id())
        self._stage_root = array("l", [0]) * N_TAGS
        for t in range(N_TAGS):
            self._stage_root[t] = self._grow(-1, -(t + 1))

    @property
    def n_nodes(self) -> int:
        return self._n

    def _grow(self, parent: int, key: int) -> int:
        """First-sight node allocation (unmarked: runs once per new
        (stage, stack-prefix), never at steady state)."""
        n = self._n
        if n >= self._cap:
            return -1
        self._parent[n] = parent
        self._key[n] = key
        self._kids.append({})
        if parent >= 0:
            self._kids[parent][key] = n
        self._n = n + 1
        return n

    def sample(self, frame, on_cpu, tag):  # lint: sample-path
        """Fold one thread's stack into the trie (sampler thread only)."""
        stack = self._stack
        code_of = self._code
        lim = self._max_depth
        depth = 0
        f = frame
        while f is not None and depth < lim:
            c = f.f_code
            k = id(c)
            if k not in code_of:
                code_of[k] = c  # first sight of this code object
            stack[depth] = k
            depth += 1
            f = f.f_back
        node = self._stage_root[tag]
        kids = self._kids
        i = depth - 1  # stack is leaf-first; fold root-first
        while i >= 0:
            k = stack[i]
            nxt = kids[node].get(k, -1)
            if nxt < 0:
                nxt = self._grow(node, k)
                if nxt < 0:
                    self.overflow_total += 1
                    break  # bill to the deepest prefix that fit
            node = nxt
            i -= 1
        if on_cpu:
            self._on[node] += 1
            self._stage_on[tag] += 1
            self.on_cpu_total += 1
        else:
            self._off[node] += 1
            self._stage_off[tag] += 1
            self.waiting_total += 1
        self.samples_total += 1

    # ---- read side (cold: exports, dumps, tests) ----

    def _label(self, key: int) -> str:
        c = self._code.get(key)
        if c is None:
            return "?"
        name = getattr(c, "co_qualname", None) or c.co_name
        return "%s:%s:%d" % (os.path.basename(c.co_filename), name, c.co_firstlineno)

    def rows(self) -> List[dict]:
        """Every counted (stage, stack) path as
        ``{"stage", "frames", "on", "off"}`` — frames root-first."""
        out: List[dict] = []
        for node in range(self._n):
            on = self._on[node]
            off = self._off[node]
            if on == 0 and off == 0:
                continue
            frames: List[str] = []
            stage = TAG_NAMES[0]
            i = node
            while i >= 0:
                k = self._key[i]
                if k < 0:
                    stage = TAG_NAMES[-k - 1]
                else:
                    frames.append(self._label(k))
                i = self._parent[i]
            frames.reverse()
            out.append({"stage": stage, "frames": frames, "on": int(on), "off": int(off)})
        return out

    def hot_frames(self, n: int = 16) -> List[dict]:
        """Top-``n`` frames by SELF on-CPU samples (counts bill to the
        sampled leaf, so a node's count is its self time)."""
        agg: Dict[str, int] = {}
        for node in range(self._n):
            on = self._on[node]
            k = self._key[node]
            if on and k >= 0:
                lbl = self._label(k)
                agg[lbl] = agg.get(lbl, 0) + int(on)
        top = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))[:n]
        return [{"frame": lbl, "self": cnt} for lbl, cnt in top]

    def stage_totals(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for t in range(N_TAGS):
            on = int(self._stage_on[t])
            off = int(self._stage_off[t])
            if on or off:
                out[TAG_NAMES[t]] = {"on": on, "off": off}
        return out


class FlameSampler:
    """The continuous-profiler daemon thread.

    ``start()`` spawns one daemon thread that paces itself with a
    drift-corrected ``Event.wait`` (never ``time.sleep`` — the
    blocking-hot-path checker guards this file), samples every live
    thread into a :class:`StackTrie`, and about once a second does the
    cold housekeeping: cost-model tick (:class:`ProfTelemetry`), dead
    thread GC, procfs fd hygiene. ``stop()`` joins the thread, closes
    fds, and (when ``spool_dir`` is set) writes the spool JSON that
    ``python -m psana_ray_tpu.obs.prof_merge`` consumes.

    ``register=True`` publishes the cost model as the ``prof`` source
    on the obs MetricsRegistry so cpu_frac / cpu_ns_per_frame ride the
    existing history rings, Prometheus endpoint, and federation.
    """

    DEFAULT_HZ = DEFAULT_HZ

    def __init__(
        self,
        hz: float = DEFAULT_HZ,
        max_nodes: int = DEFAULT_MAX_NODES,
        max_depth: int = DEFAULT_MAX_DEPTH,
        process: str = "",
        spool_dir: Optional[str] = None,
        registry=None,
        register: bool = True,
        frames_fn=None,
        bytes_fn=None,
    ):
        self.hz = float(hz)
        if self.hz <= 0:
            raise ValueError("FlameSampler hz must be > 0 (use 0 at the CLI to disable)")
        self.period_s = 1.0 / self.hz
        self.process = process or os.path.basename(sys.argv[0] or "py")
        self.spool_dir = spool_dir
        self.trie = StackTrie(max_nodes=max_nodes, max_depth=max_depth)
        self.telemetry = ProfTelemetry(sampler=self, frames_fn=frames_fn, bytes_fn=bytes_fn)
        self._registry = registry
        self._register = register
        self._registered = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._own_ident = -1
        # ident -> [fd, last_cpu_ticks]; a 2-slot list so per-sample
        # updates mutate in place (no tuple churn)
        self._threads: Dict[int, list] = {}
        self.start_wall = 0.0
        self.start_mono = 0.0
        self.anchors: List[dict] = []

    # ---- lifecycle ----

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "FlameSampler":
        if self._thread is not None:
            return self
        self.start_wall = time.time()
        self.start_mono = time.monotonic()
        self.anchors.append({"wall": self.start_wall, "mono": self.start_mono})
        self._stop.clear()
        if self._register and not self._registered:
            try:
                if self._registry is None:
                    from psana_ray_tpu.obs.registry import MetricsRegistry

                    self._registry = MetricsRegistry.default()
                self._registry.register("prof", self.telemetry)
                self._registered = True
            except Exception:  # obs optional: profiler must work without it
                pass
        t = threading.Thread(target=self._run, name="prof-sampler", daemon=True)
        self._thread = t
        t.start()
        return self

    def stop(self, write_spool: bool = True) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=2.0)
        self._thread = None
        self.telemetry.tick_cost_model()
        if self._registered and self._registry is not None:
            try:
                self._registry.unregister("prof")
            except Exception:
                pass
            self._registered = False
        for info in self._threads.values():
            if info[0] >= 0:
                try:
                    os.close(info[0])
                except OSError:
                    pass
        self._threads.clear()
        if write_spool and self.spool_dir:
            try:
                from psana_ray_tpu.obs.profiling.export import write_spool

                write_spool(self, directory=self.spool_dir)
            except Exception:
                pass

    def rearm_after_fork(self, process: Optional[str] = None) -> "FlameSampler":
        """Make a sampler inherited across ``os.fork`` valid in the CHILD.

        Fork clones neither the sampler thread nor the procfs task
        directory: ``self._thread`` points at a thread that does not
        exist here, and every cached ``/proc/self/task/<tid>/stat`` fd
        in ``self._threads`` describes the PARENT's threads (procfs
        fds stay readable post-fork — they would silently misattribute
        CPU). Reset both and restart. ``queue_server --workers`` forks
        BEFORE any sampler starts, so its workers never need this; it
        exists for embedders that fork with a live profiler, and
        ``process`` lets the child rename its spool (e.g. a worker id)
        so prof_merge shows it as its own process row."""
        self._thread = None  # the parent's thread; not ours to join
        self._stop.clear()
        for info in self._threads.values():
            if info[0] >= 0:
                try:
                    os.close(info[0])
                except OSError:
                    pass
        self._threads.clear()
        self._registered = False  # the child's registry is a fresh copy
        if process:
            self.process = process
        return self.start()

    # ---- sampling loop (hot: lint-guarded) ----

    def _run(self):  # lint: sample-path
        self._own_ident = threading.get_ident()
        period = self.period_s
        nxt = time.monotonic() + period
        last_house = 0.0
        while True:
            now = time.monotonic()
            delay = nxt - now
            if delay < 0.0:
                nxt = now + period  # fell behind (suspend, GIL storm): re-anchor
                delay = 0.0
            if self._stop.wait(delay):
                break
            self._sample_once()
            nxt += period
            now = time.monotonic()
            if now - last_house >= 1.0:
                last_house = now
                self._housekeep(now)

    def _sample_once(self):  # lint: sample-path
        frames = sys._current_frames()
        trie = self.trie
        own = self._own_ident
        tags = _TAGS
        for ident in frames:
            if ident == own:
                continue
            tag = tags.get(ident, 0)
            if tag < 0 or tag >= N_TAGS:
                tag = 0
            trie.sample(frames[ident], self._thread_on_cpu(ident), tag)
        # break the dict <-> own-frame reference cycle: the snapshot
        # holds THIS frame, whose locals hold the snapshot — without
        # this decref every tick leaves one cycle for the generational
        # GC (pinned by the zero-alloc test, which runs no GC)
        frames = None

    def _thread_on_cpu(self, ident):  # lint: sample-path
        """Did this thread's CPU clock advance since its last sample?
        One pread of a cached ``/proc/self/task/<tid>/stat`` fd; procfs
        regenerates the whole file at offset 0 so no seek/reopen."""
        info = self._threads.get(ident)
        if info is None:
            info = self._register_thread(ident)
        fd = info[0]
        if fd < 0:
            return True  # no procfs: count as on-CPU rather than guess
        try:
            data = os.pread(fd, 512, 0)
        except OSError:
            info[0] = -1  # thread exited between snapshot and read
            return True
        j = data.rfind(b")") + 2  # comm field may contain spaces; skip past it
        parts = data[j:].split()
        ticks = int(parts[11]) + int(parts[12])  # utime + stime
        prev = info[1]
        info[1] = ticks
        return ticks > prev

    # ---- cold helpers (first-sight / ~1 Hz) ----

    def _register_thread(self, ident) -> list:
        nid = -1
        for t in threading.enumerate():
            if t.ident == ident:
                nid = getattr(t, "native_id", None) or -1
                break
        fd = -1
        if nid > 0:
            try:
                fd = os.open("/proc/self/task/%d/stat" % nid, os.O_RDONLY)
            except OSError:
                fd = -1
        info = [fd, 0]
        self._threads[ident] = info
        return info

    def _housekeep(self, now: float) -> None:
        try:
            self.telemetry.tick_cost_model(now)
        except Exception:
            pass
        self._gc_threads()

    def _gc_threads(self) -> None:
        live = sys._current_frames()
        dead = [i for i in self._threads if i not in live]
        live = None  # same frame-cycle decref as _sample_once
        for ident in dead:
            info = self._threads.pop(ident, None)
            if info is not None and info[0] >= 0:
                try:
                    os.close(info[0])
                except OSError:
                    pass
            clear_thread(ident)

    # ---- read side ----

    def stage_cpu_ms(self) -> Dict[str, float]:
        """Per-stage on-CPU milliseconds (sample count x period)."""
        period_ms = 1000.0 / self.hz
        out: Dict[str, float] = {}
        totals = self.trie.stage_totals()
        for name, t in totals.items():
            out[name] = t["on"] * period_ms
        return out
