"""Per-frame cost model + CPU saturation source (the ``prof`` source).

ROADMAP item 2 ("break the single-core Python ceiling") will be judged
by a number nothing measured before this PR: how much host CPU and how
many Python-touched bytes each frame costs. This module derives both by
differencing two counters the repo already pays for — process CPU time
(``os.times``) and the wire copy counters (``utils.bufpool.WIRE``) —
about once a second on the sampler's housekeeping tick:

- ``cpu_frac``     — process CPU seconds per wall second (saturation
  signal for ROADMAP item 4's elasticity controller; also appended to
  a local SeriesRing so spools carry the full utilisation timeline);
- ``cpu_ns_per_frame``   — CPU nanoseconds burned per wire frame;
- ``py_bytes_per_frame`` — bytes memcpy'd through Python per frame
  (the "per-frame Python bytes touched ~0" acceptance number).

Registered as the ``prof`` source on the MetricsRegistry, every value
here is numeric, so it flows unmodified through ``flatten_numeric``
into Prometheus, the PR 13 history rings, federation metrics, and the
bench baseline gate. The non-numeric profile summary (hot frame NAMES)
deliberately lives outside this source — see
``registry.federation_payload``'s ``profile`` key — because the metric
grammar drops strings.

Deltas are computed against injected ``frames_fn`` / ``bytes_fn`` when
the caller has a better frame counter than the wire totals (bench.py
injects its own frame count so the model scores exactly the measured
window).
"""

from __future__ import annotations

import os
import threading
import time

from psana_ray_tpu.obs.timeseries import SeriesRing

__all__ = ["ProfTelemetry", "CPU_SERIES_CAPACITY"]

CPU_SERIES_CAPACITY = 600  # ~10 min of 1 Hz ticks, same budget as history rings


class ProfTelemetry:
    """Cost-model state; obs source protocol via :meth:`snapshot`.

    Written from the sampler thread's ~1 Hz housekeeping tick
    (:meth:`tick_cost_model`), read from scrape/federation threads —
    all mutable state is guarded by ``_lock``.
    """

    def __init__(self, sampler=None, frames_fn=None, bytes_fn=None):
        self._sampler = sampler
        self._frames_fn = frames_fn
        self._bytes_fn = bytes_fn
        self._lock = threading.Lock()
        self.cpu_frac = 0.0  # guarded-by: _lock
        self.cpu_ns_per_frame = 0.0  # guarded-by: _lock
        self.py_bytes_per_frame = 0.0  # guarded-by: _lock
        self.frames_seen = 0  # guarded-by: _lock
        self.ticks_total = 0  # guarded-by: _lock
        self._last_mono = 0.0  # guarded-by: _lock
        self._last_cpu = 0.0  # guarded-by: _lock
        self._last_frames = 0  # guarded-by: _lock
        self._last_bytes = 0  # guarded-by: _lock
        self.cpu_series = SeriesRing(CPU_SERIES_CAPACITY)  # guarded-by: _lock

    def _frame_counters(self):
        """(frames_total, bytes_total) from the injected counters or the
        process-wide wire counters."""
        if self._frames_fn is not None:
            frames = int(self._frames_fn())
            nbytes = int(self._bytes_fn()) if self._bytes_fn is not None else 0
            return frames, nbytes
        try:
            from psana_ray_tpu.utils.bufpool import WIRE

            s = WIRE.stats()
            return int(s["copies_total"]), int(s["bytes_copied_total"])
        except Exception:
            return 0, 0

    def tick_cost_model(self, now=None) -> None:
        """One cost-model step: difference CPU/frames/bytes since the
        previous tick. Called ~1 Hz off the sampler's housekeeping (or
        directly by tests); cold path, allocation is fine here."""
        if now is None:
            now = time.monotonic()
        t = os.times()
        cpu = t.user + t.system
        frames, nbytes = self._frame_counters()
        with self._lock:
            dt = now - self._last_mono
            if self._last_mono > 0.0 and dt > 0.0:
                d_cpu = max(0.0, cpu - self._last_cpu)
                self.cpu_frac = d_cpu / dt
                d_frames = frames - self._last_frames
                if d_frames > 0:
                    self.cpu_ns_per_frame = d_cpu * 1e9 / d_frames
                    self.py_bytes_per_frame = (nbytes - self._last_bytes) / float(d_frames)
            self._last_mono = now
            self._last_cpu = cpu
            self._last_frames = frames
            self._last_bytes = nbytes
            self.frames_seen = frames
            self.ticks_total += 1
            self.cpu_series.append(now, self.cpu_frac)

    def cpu_timeline(self):
        """``[(mono, cpu_frac), ...]`` ticks for spool export."""
        with self._lock:
            return self.cpu_series.samples()

    # ---- obs registry source protocol ----

    def snapshot(self) -> dict:
        s = self._sampler
        with self._lock:
            out = {
                "enabled": 1 if (s is not None and s.running) else 0,
                "cpu_frac": self.cpu_frac,
                "cpu_ns_per_frame": self.cpu_ns_per_frame,
                "py_bytes_per_frame": self.py_bytes_per_frame,
                "frames_seen": self.frames_seen,
                "ticks_total": self.ticks_total,
            }
        if s is not None:
            trie = s.trie
            out["hz"] = s.hz
            out["samples_total"] = trie.samples_total
            out["on_cpu_total"] = trie.on_cpu_total
            out["waiting_total"] = trie.waiting_total
            out["nodes"] = trie.n_nodes
            out["overflow_total"] = trie.overflow_total
            out["stage_cpu_ms"] = s.stage_cpu_ms()
        return out

    def stats(self) -> dict:
        return self.snapshot()
