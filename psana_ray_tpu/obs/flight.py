"""Crash flight recorder: a bounded in-memory ring of structured events,
dumped to disk with a metrics snapshot and all thread stacks when the
pipeline wedges.

The black box for postmortems: counters tell you THAT a run degenerated;
the flight recorder tells you the last N things that happened before it
did (queue ops, reconnects, EOS markers, stall events, errors) plus what
every thread was doing at the moment of the dump. Recording is always on
and cheap (one deque append under a lock, and only at RARE control-plane
events — never per frame); dumping requires :meth:`FlightRecorder.
install` with a directory.

Dump triggers (ISSUE 4):

- a :class:`~psana_ray_tpu.obs.stall.StallDetector` event (wire
  ``on_event=FLIGHT.on_stall`` — the queue server CLI does);
- an unhandled exception (``install`` chains ``sys.excepthook``);
- ``SIGUSR2`` (``kill -USR2 <pid>`` on any wedged process).

Pure stdlib, importable without JAX or numpy.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import socket
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

__all__ = ["FlightRecorder", "FLIGHT"]

# Rate limit between automatic dumps (stall storms fire once per episode
# already, but several queues can degenerate at once): one dump per
# window keeps the postmortem readable and the disk bounded.
DUMP_MIN_INTERVAL_S = 5.0

# Samples per key of time-series history appended to a dump (ISSUE 13):
# at the default 1 s sampling this is the last ~minute of every series
# BEFORE the trigger — the "how did we get here", where the metrics
# snapshot is only the "where we ended up".
TAIL_SAMPLES = 64


def _thread_stacks() -> Dict[str, list]:
    """Every live thread's current stack, keyed ``name-ident`` — the
    "what was everyone doing" half of the dump."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, 'unknown')}-{ident}"
        out[key] = [ln.rstrip("\n") for ln in traceback.format_stack(frame)]
    return out


class FlightRecorder:
    """Bounded ring of structured events + the dump machinery."""

    def __init__(self, maxlen: int = 1024):
        # REENTRANT: the SIGUSR2 handler runs in the MAIN thread between
        # bytecodes and calls record()/dump() — if the signal lands while
        # that same thread already holds this lock (mid-record/snapshot),
        # a plain Lock would deadlock the process the operator was trying
        # to diagnose. Handler re-entry under an RLock only ever appends
        # to the ring mid-operation, which is harmless.
        self._lock = threading.RLock()
        self._events: deque = deque(maxlen=maxlen)
        self._counts: Dict[str, int] = {}
        self._total = 0
        self._dumps = 0
        self._last_dump = 0.0
        self._dir: Optional[str] = None
        self._process = ""
        self._host = socket.gethostname()
        self._prev_sighandler = None
        self._prev_excepthook = None
        self._prev_threading_excepthook = None
        self._installed_signum: Optional[int] = None

    # -- recording (always on, rare events only) --------------------------
    def record(self, kind: str, /, **detail) -> None:
        """Append one structured event; bounded ring, never blocks, never
        raises into the caller (the black box must not take down the
        plane). The reserved keys (kind/wall/mono) win over same-named
        detail fields."""
        try:
            evt = dict(detail)
            evt["kind"] = kind
            evt["wall"] = time.time()
            evt["mono"] = time.monotonic()
            with self._lock:
                self._events.append(evt)
                self._total += 1
                self._counts[kind] = self._counts.get(kind, 0) + 1
        except Exception:  # noqa: BLE001
            logger.debug("flight record failed", exc_info=True)

    @property
    def event_count(self) -> int:
        with self._lock:
            return self._total

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def count_of(self, *kinds: str) -> int:
        """Lifetime count across the named event kinds (counts survive
        ring eviction — heartbeat summaries must not undercount)."""
        with self._lock:
            return sum(self._counts.get(k, 0) for k in kinds)

    # -- dump machinery ---------------------------------------------------
    def install(
        self,
        dump_dir: str,
        process: str = "",
        signum: Optional[int] = None,
        excepthook: bool = True,
    ) -> "FlightRecorder":
        """Arm dumping into ``dump_dir``: SIGUSR2 (or ``signum``) dumps on
        demand, and unhandled exceptions dump before the interpreter dies
        (the previous hook still runs). Signal installation is skipped off
        the main thread (Python restriction) — the excepthook and
        programmatic triggers still work there."""
        os.makedirs(dump_dir, exist_ok=True)
        with self._lock:
            # armed-state stores under the lock: dump()/snapshot() read
            # them there, and arming must never race a dump into a
            # half-set (dir, process) pair
            self._dir = dump_dir
            self._process = process or self._process
            proc = self._process
        if signum is None:
            signum = getattr(signal, "SIGUSR2", None)
        if signum is not None and threading.current_thread() is threading.main_thread():
            try:
                self._prev_sighandler = signal.signal(signum, self._on_signal)
                self._installed_signum = signum
            except (ValueError, OSError):  # non-main thread / unsupported
                self._installed_signum = None
        if excepthook:
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._on_exception
            # sys.excepthook never fires for non-main threads (Python
            # 3.8+ routes those to threading.excepthook) — a crashing
            # worker (serve thread, prefetcher, pump) is exactly the
            # multithreaded wedge the black box exists for
            self._prev_threading_excepthook = threading.excepthook
            threading.excepthook = self._on_thread_exception
        self.record("flight_installed", dir=dump_dir, process=proc)
        return self

    def uninstall(self) -> None:
        """Restore the previous signal handler / excepthook (tests)."""
        if self._installed_signum is not None and self._prev_sighandler is not None:
            try:
                signal.signal(self._installed_signum, self._prev_sighandler)
            except (ValueError, OSError):
                pass
        self._installed_signum = None
        self._prev_sighandler = None
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._prev_threading_excepthook is not None:
            threading.excepthook = self._prev_threading_excepthook
            self._prev_threading_excepthook = None
        with self._lock:
            self._dir = None

    def _on_signal(self, signum, frame):
        self.record("sigusr2", signum=int(signum))
        # dump from a SEPARATE thread, never the signal frame: the dump
        # takes a metrics-registry snapshot, which acquires other
        # sources' plain (non-reentrant) locks — the interrupted main
        # thread may be HOLDING one of them mid-observation (Tracer.span,
        # Meter.add, ...), and acquiring it from the handler would
        # deadlock the very process the operator is diagnosing. A helper
        # thread just blocks until the main thread resumes and releases.
        threading.Thread(
            target=self.dump, args=("signal",), kwargs={"force": True},
            daemon=True, name="flight-dump",
        ).start()
        prev = self._prev_sighandler
        if callable(prev):
            prev(signum, frame)

    def _on_thread_exception(self, hook_args):
        """threading.excepthook chain: a worker thread died uncaught."""
        self.record(
            "unhandled_thread_exception",
            thread=getattr(hook_args.thread, "name", "?"),
            exc_type=getattr(hook_args.exc_type, "__name__", str(hook_args.exc_type)),
            message=str(hook_args.exc_value),
        )
        self.dump(
            "thread_exception",
            trigger={
                "thread": getattr(hook_args.thread, "name", "?"),
                "exc_type": getattr(
                    hook_args.exc_type, "__name__", str(hook_args.exc_type)
                ),
                "message": str(hook_args.exc_value),
                "traceback": traceback.format_exception(
                    hook_args.exc_type, hook_args.exc_value, hook_args.exc_traceback
                ),
            },
            force=True,
        )
        prev = self._prev_threading_excepthook or threading.__excepthook__
        prev(hook_args)

    def _on_exception(self, exc_type, exc, tb):
        self.record(
            "unhandled_exception",
            exc_type=getattr(exc_type, "__name__", str(exc_type)),
            message=str(exc),
        )
        self.dump(
            "exception",
            trigger={
                "exc_type": getattr(exc_type, "__name__", str(exc_type)),
                "message": str(exc),
                "traceback": traceback.format_exception(exc_type, exc, tb),
            },
            force=True,
        )
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def on_stall(self, event) -> None:
        """`StallDetector(on_event=...)` hook: record the stall AND dump —
        a wedged pipeline is exactly what the black box exists for."""
        detail = (
            dataclasses.asdict(event) if dataclasses.is_dataclass(event) else {"event": repr(event)}
        )
        self.record("stall", stall_kind=detail.get("kind"), **{
            k: v for k, v in detail.items() if k != "kind"
        })
        self.dump("stall", trigger=detail)

    def dump(
        self,
        reason: str,
        trigger: Optional[dict] = None,
        path: Optional[str] = None,
        force: bool = False,
    ) -> Optional[str]:
        """Write the black box to disk: the event ring, a metrics-registry
        snapshot, and every thread's stack. Returns the path, or None when
        no directory is armed / the rate limit suppressed it. Never raises
        (logged instead): the dump rides failure paths."""
        try:
            with self._lock:
                if self._dir is None and path is None:
                    return None
                now = time.monotonic()
                if not force and now - self._last_dump < DUMP_MIN_INTERVAL_S:
                    return None
                self._last_dump = now
                self._dumps += 1
                seq = self._dumps
                events = list(self._events)
                counts = dict(self._counts)
                # armed-state snapshot: the file write below runs OUTSIDE
                # the lock (record() callers must not block on disk), so
                # take a coherent (dir, process) pair here
                dump_dir, proc = self._dir, self._process
            try:
                from psana_ray_tpu.obs.registry import MetricsRegistry

                metrics = MetricsRegistry.default().snapshot()
            except Exception as e:  # noqa: BLE001 — snapshot is best-effort
                metrics = {"error": repr(e)}
            # the local time-series tail (ISSUE 13): the minutes BEFORE
            # the trigger, when a history sampler is running — absent
            # history costs nothing and fails nothing
            tail = None
            try:
                from psana_ray_tpu.obs.timeseries import default_history

                hist = default_history()
                if hist is not None:
                    tail = hist.tail(TAIL_SAMPLES)
            except Exception as e:  # noqa: BLE001 — best-effort like metrics
                tail = {"error": repr(e)}
            # WHAT the process was burning CPU on when it stalled
            # (ISSUE 16): top hot frames + per-stage cpu_ms from the
            # live flame sampler — null when profiling is off
            prof_top = None
            try:
                from psana_ray_tpu.obs.profiling import profile_top

                prof_top = profile_top(16)
            except Exception as e:  # noqa: BLE001 — best-effort like metrics
                prof_top = {"error": repr(e)}
            doc = {
                "reason": reason,
                "trigger": trigger,
                "host": self._host,
                "pid": os.getpid(),
                "process": proc,
                "wall": time.time(),
                "mono": time.monotonic(),
                "event_counts": counts,
                "events": events,
                "metrics": metrics,
                "timeseries_tail": tail,
                "profile_top": prof_top,
                "threads": _thread_stacks(),
            }
            if path is None:
                stamp = time.strftime("%Y%m%d-%H%M%S")
                path = os.path.join(
                    dump_dir,
                    f"flight-{proc or 'proc'}-{os.getpid()}-{stamp}-{seq}.json",
                )
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1)
            logger.warning("flight recorder dump (%s) -> %s", reason, path)
            return path
        except Exception:  # noqa: BLE001 — the black box must not crash the plane
            logger.exception("flight recorder dump failed")
            return None

    # -- registry source ---------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            out: Dict[str, Any] = {
                "events_total": self._total,
                "dumps_total": self._dumps,
                "armed": self._dir is not None,
            }
            for kind, n in self._counts.items():
                out[f"events_{kind}_total"] = n
        return out


#: The process-global recorder; call sites record into it unconditionally
#: (rare control-plane events only), CLIs arm dumping via ``install``.
FLIGHT = FlightRecorder()
