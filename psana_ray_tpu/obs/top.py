"""``python -m psana_ray_tpu.obs.top`` — the live federated console.

Thin entry point; the implementation (collector wiring + ANSI
rendering) lives in :mod:`psana_ray_tpu.obs.console`.
"""

from psana_ray_tpu.obs.console import main

if __name__ == "__main__":
    raise SystemExit(main())
