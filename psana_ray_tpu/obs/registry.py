"""Process-wide metrics registry: aggregate + render as Prometheus text.

One :class:`MetricsRegistry` per process collects every metrics-bearing
object (``PipelineMetrics`` bundles, ``Meter``/``LatencyStats``
singletons, queue ``stats()`` callables, stall detectors) under a source
name; :meth:`snapshot` returns the whole tree as a JSON-safe dict (tests,
bench artifacts) and :meth:`render_prometheus` flattens the same tree
into Prometheus exposition text-format 0.0.4 for the HTTP exporter
(:mod:`psana_ray_tpu.obs.exporter`).

Naming: nested dict paths join with ``_`` under the ``psana_ray`` prefix
and the top-level source name becomes the ``source`` label, e.g.::

    psana_ray_frames_total{source="producer"} 4096
    psana_ray_stages_queue_dwell_p99_ms{source="infeed.epix"} 1.84

Names ending in ``_total`` are typed ``counter``; everything else is a
``gauge``. Pure stdlib, no prometheus_client dependency.
"""

from __future__ import annotations

import math
import os
import re
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from psana_ray_tpu.utils.metrics import LatencyStats, Meter, PipelineMetrics, StageTimes

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

Source = Union[PipelineMetrics, Meter, LatencyStats, StageTimes, dict, Callable[[], dict]]


def _sanitize(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def flatten_numeric(
    path: Tuple[str, ...], value: Any, out: List[Tuple[str, float]]
) -> None:
    """Flatten a snapshot tree's numeric leaves into ``(dotted.path,
    float)`` pairs — ONE flattening grammar shared by the Prometheus
    renderer and the time-series history ring
    (:mod:`psana_ray_tpu.obs.timeseries`), so the history key for a
    metric is its /metrics name with ``.`` for the sanitized ``_``
    joins. Bools become 0/1; non-finite and non-numeric leaves are
    skipped. The ``exemplars`` subtree of a latency snapshot is skipped
    WHOLE: an exemplar is a retained (trace id, value) LINK for the
    drill-down tooling, not a series — flattening its numeric half
    would mint a bogus mostly-static gauge per bucket on /metrics and
    a history ring per bucket in every sampling process."""
    if isinstance(value, dict):
        for k, v in value.items():
            if k == "exemplars":
                continue
            flatten_numeric(path + (str(k),), v, out)
        return
    if isinstance(value, bool):
        out.append((".".join(path), 1.0 if value else 0.0))
        return
    if isinstance(value, (int, float)):
        v = float(value)
        if math.isfinite(v):
            out.append((".".join(path), v))


def snapshot_source(src: Source) -> dict:
    """One source -> JSON-safe dict. Objects with ``snapshot()`` win
    (PipelineMetrics, Meter, LatencyStats, StageTimes, StallDetector);
    bare dicts pass through; callables (queue ``stats`` methods, lambdas)
    are invoked; anything with ``stats()`` (transport queues) is asked."""
    snap = getattr(src, "snapshot", None)
    if callable(snap):
        return snap() or {}
    if isinstance(src, dict):
        return dict(src)
    if callable(src):
        return src() or {}
    stats = getattr(src, "stats", None)
    if callable(stats):
        return stats() or {}
    raise TypeError(f"not a metrics source: {type(src)!r}")


class MetricsRegistry:
    """Named metrics sources + the two export surfaces.

    Distinct from the transport-rendezvous
    :class:`psana_ray_tpu.transport.registry.Registry` — this one holds
    observability objects, not queues. ``default()`` is the process-global
    instance every CLI registers into; tests build their own."""

    _global: Optional["MetricsRegistry"] = None
    _global_lock = threading.Lock()

    def __init__(self, prefix: str = "psana_ray"):
        self.prefix = _sanitize(prefix)
        self._lock = threading.Lock()
        self._sources: Dict[str, Source] = {}

    @classmethod
    def default(cls) -> "MetricsRegistry":
        with cls._global_lock:
            if cls._global is None:
                cls._global = MetricsRegistry()
            return cls._global

    @classmethod
    def reset_default(cls):
        with cls._global_lock:
            cls._global = None

    def register(self, name: str, source: Source) -> Source:
        """Add (or replace — last registration wins, so restarted
        pipelines under a stable name just take over the series) a source."""
        with self._lock:
            self._sources[name] = source
        return source

    def unregister(self, name: str):
        with self._lock:
            self._sources.pop(name, None)

    def sources(self) -> List[str]:
        with self._lock:
            return sorted(self._sources)

    def snapshot(self) -> Dict[str, dict]:
        """The whole tree as a JSON-safe dict: ``{source_name: {...}}``.
        A source that raises contributes an ``error`` entry instead of
        poisoning the scrape (one dead queue must not blind the cluster)."""
        with self._lock:
            items = list(self._sources.items())
        out: Dict[str, dict] = {}
        for name, src in items:
            try:
                out[name] = snapshot_source(src)
            except Exception as e:  # noqa: BLE001 — scrape must survive
                out[name] = {"error": repr(e)}
        return out

    # -- Prometheus text format ------------------------------------------
    def render_prometheus(self) -> str:
        """Exposition text-format 0.0.4: numeric leaves of the snapshot
        tree, grouped per metric family with HELP/TYPE headers, the source
        name as a label. Non-finite values and non-numeric leaves are
        skipped (a scrape is never malformed)."""
        families: Dict[str, List[Tuple[str, float]]] = {}
        for source, tree in self.snapshot().items():
            leaves: List[Tuple[str, float]] = []
            flatten_numeric((), tree, leaves)
            for path, value in leaves:
                metric = f"{self.prefix}_{_sanitize(path)}"
                families.setdefault(metric, []).append((source, value))
        lines: List[str] = []
        for metric in sorted(families):
            mtype = "counter" if metric.endswith("_total") else "gauge"
            lines.append(f"# HELP {metric} psana-ray-tpu pipeline metric")
            lines.append(f"# TYPE {metric} {mtype}")
            for source, value in sorted(families[metric]):
                label = _escape_label(source)
                lines.append(f'{metric}{{source="{label}"}} {_format_value(value)}')
        return "\n".join(lines) + "\n" if lines else ""


def _format_value(v: float) -> str:
    if v == int(v) and abs(v) < 2**53:
        return str(int(v))
    return repr(v)


def federation_payload(registry: Optional[MetricsRegistry] = None) -> dict:
    """One host-tagged registry snapshot — the federation unit of ISSUE
    13, served identically by the queue server's 'N' ``{"op":
    "metrics"}`` RPC and the HTTP exporter's ``/federate`` route, so the
    collector merges queue servers and producer/consumer CLIs into the
    same host-tagged series store."""
    reg = registry if registry is not None else MetricsRegistry.default()
    payload = {
        "ok": True,
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "wall": time.time(),
        "mono": time.monotonic(),
        "metrics": reg.snapshot(),
    }
    # multi-worker data plane (ISSUE 17): a forked queue-server worker
    # tags its payload so the collector/console can label per-worker
    # rows (a pulled TCP connection pins to ONE worker for its life,
    # so each peer's series is per-worker consistent)
    try:
        from psana_ray_tpu.transport.workers import current_worker_id

        wid = current_worker_id()
        if wid is not None:
            payload["worker"] = wid
    except Exception:
        pass
    # continuous-profiler summary (ISSUE 16) rides OUTSIDE "metrics":
    # hot-frame NAMES are strings and flatten_numeric would drop them.
    # Absent/broken profiler must cost nothing — peers render "-".
    try:
        from psana_ray_tpu.obs.profiling import profile_summary

        payload["profile"] = profile_summary()
    except Exception:
        payload["profile"] = None
    return payload
