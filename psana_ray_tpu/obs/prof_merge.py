"""Merge per-process profile spools into one cluster CPU profile.

``python -m psana_ray_tpu.obs.prof_merge <spool-dir-or-files...>
[--out merged_prof.json] [--collapsed out.folded] [--speedscope out.ss.json]
[--trace <trace spools...>]`` reads the ``*.prof.json`` spools written
by :class:`psana_ray_tpu.obs.profiling.sampler.FlameSampler` (one per
process: producer, queue server, consumer, ...) and produces:

- a merged summary doc: per-process cost-model numbers, cluster-wide
  hot frames (self on-CPU samples, process-annotated), and summed
  per-stage cpu_ms — "where does the CLUSTER burn CPU, in the stage
  vocabulary";
- optionally one combined collapsed-stack file and one speedscope doc
  (stacks prefixed ``process;stage;...`` so flamegraphs split per
  process first);
- optionally a Perfetto overlay: with ``--trace`` pointing at the
  PR 4 ``*.trace.jsonl`` spools, the merged trace doc gains one
  ``cpu_frac`` counter track per profiled process, aligned onto the
  same unified timeline via the identical (wall, mono) clock-anchor
  contract ``trace_merge`` uses — CPU saturation directly under the
  frame spans that caused it.

Alignment: each spool carries (wall, mono) anchor pairs;
``offset = median(wall - mono)`` maps that process's monotonic ticks
onto the shared wallclock axis, exactly as ``trace_merge.clock_offset``
does (same-host wallclocks are literally the same clock; cross-host
skew is bounded by the trace spools' peer anchors when overlaying).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

from psana_ray_tpu.obs.trace_merge import _median
from psana_ray_tpu.obs import trace_merge

__all__ = ["load_spool", "clock_offset", "merge", "main"]


def load_spool(path: str) -> dict:
    """One ``*.prof.json`` spool (delegates format checking to the
    profiling exporter)."""
    from psana_ray_tpu.obs.profiling.export import load_spool as _load

    doc = _load(path)
    doc["path"] = path
    return doc


def clock_offset(spool: dict) -> float:
    """monotonic -> wall offset for this process: median over the
    spool's anchor pairs, meta start pair as fallback — the same
    estimator ``trace_merge.clock_offset`` applies to trace spools."""
    pairs = [(a["wall"], a["mono"]) for a in spool.get("anchors", [])]
    meta = spool.get("meta", {})
    if not pairs and "start_wall" in meta:
        pairs = [(meta["start_wall"], meta["start_mono"])]
    if not pairs:
        return 0.0
    return _median([w - m for w, m in pairs])


def _expand(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*.prof.json"))))
        else:
            out.append(p)
    return out


def merge(paths: List[str], trace_inputs: Optional[List[str]] = None,
          top_n: int = 32) -> dict:
    """Merge profile spools (files or directories) into the cluster
    profile doc; with ``trace_inputs``, start from
    ``trace_merge.merge`` and overlay cpu_frac counter tracks."""
    files = _expand(paths)
    if not files:
        raise FileNotFoundError(f"no profile spools found under {paths!r}")
    spools = [load_spool(p) for p in files]

    processes: List[dict] = []
    hot_agg: Dict[str, int] = {}
    stage_ms: Dict[str, float] = {}
    events: List[dict] = []

    if trace_inputs:
        doc = trace_merge.merge(trace_inputs)
        events = doc["traceEvents"]
    else:
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"tool": "psana_ray_tpu.obs.prof_merge"},
        }

    # counter tracks get their own pid block far above trace_merge's
    # 1..N process tracks so the ids can never collide
    for i, spool in enumerate(spools):
        meta = spool.get("meta", {})
        offset = clock_offset(spool)
        name = "%s:%s" % (meta.get("process", "proc"), meta.get("pid", "?"))
        totals = spool.get("totals", {})
        processes.append(
            {
                "process": name,
                "spool": spool["path"],
                "hz": meta.get("hz", 0.0),
                "mono_to_wall_offset_s": offset,
                "samples": totals.get("samples", 0),
                "on_cpu": totals.get("on_cpu", 0),
                "waiting": totals.get("waiting", 0),
                "overflow": totals.get("overflow", 0),
                "stage_cpu_ms": spool.get("stage_cpu_ms", {}),
            }
        )
        for stage, ms in spool.get("stage_cpu_ms", {}).items():
            stage_ms[stage] = stage_ms.get(stage, 0.0) + float(ms)
        for row in spool.get("stacks", []):
            on = row.get("on", 0)
            frames = row.get("frames", [])
            if on and frames:
                # counts bill to the sampled leaf -> leaf self time
                hot_agg[frames[-1]] = hot_agg.get(frames[-1], 0) + on
        pid = 1000 + i
        events.append(
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": f"prof {name}"}}
        )
        for t, v in spool.get("cpu_series", []):
            events.append(
                {
                    "ph": "C", "name": "cpu_frac", "pid": pid, "tid": 0,
                    "ts": (t + offset) * 1e6, "args": {"cpu_frac": v},
                }
            )

    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
    hot = [
        {"frame": lbl, "self": cnt}
        for lbl, cnt in sorted(hot_agg.items(), key=lambda kv: (-kv[1], kv[0]))[:top_n]
    ]
    doc["profile"] = {
        "processes": processes,
        "hot": hot,
        "stage_cpu_ms": stage_ms,
        "on_cpu_total": sum(p["on_cpu"] for p in processes),
        "samples_total": sum(p["samples"] for p in processes),
    }
    return doc


def merged_collapsed(paths: List[str]) -> List[str]:
    """One collapsed-stack file for the whole cluster: each process's
    stacks prefixed with its name so flamegraphs split per process."""
    out: List[str] = []
    for path in _expand(paths):
        spool = load_spool(path)
        meta = spool.get("meta", {})
        name = "%s:%s" % (meta.get("process", "proc"), meta.get("pid", "?"))
        for row in spool.get("stacks", []):
            on = row.get("on", 0)
            if on <= 0:
                continue
            parts = [name, row.get("stage", "untagged")]
            parts.extend(row.get("frames", []))
            out.append("%s %d" % (";".join(parts), on))
    return out


def merged_speedscope(paths: List[str]) -> dict:
    """A cluster speedscope doc (sampled, process-prefixed stacks)."""
    frames: List[dict] = []
    index: Dict[str, int] = {}
    samples: List[List[int]] = []
    weights: List[int] = []

    def fid(label: str) -> int:
        i = index.get(label)
        if i is None:
            i = len(frames)
            index[label] = i
            frames.append({"name": label})
        return i

    total = 0
    for line in merged_collapsed(paths):
        stack_s, _, count_s = line.rpartition(" ")
        count = int(count_s)
        samples.append([fid(lbl) for lbl in stack_s.split(";")])
        weights.append(count)
        total += count
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [
            {
                "type": "sampled",
                "name": "psana-ray-tpu cluster",
                "unit": "none",
                "startValue": 0,
                "endValue": total,
                "samples": samples,
                "weights": weights,
            }
        ],
        "exporter": "psana_ray_tpu.obs.prof_merge",
        "name": "psana-ray-tpu cluster",
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m psana_ray_tpu.obs.prof_merge",
        description="merge per-process profile spools (*.prof.json) into a "
        "cluster CPU profile; optionally overlay cpu_frac counter tracks "
        "onto the trace_merge Perfetto doc",
    )
    p.add_argument(
        "inputs", nargs="+",
        help="profile spools (*.prof.json) or directories containing them",
    )
    p.add_argument("--out", default="merged_prof.json", help="output path")
    p.add_argument(
        "--collapsed", default=None, metavar="PATH",
        help="also write cluster collapsed stacks (flamegraph.pl input)",
    )
    p.add_argument(
        "--speedscope", default=None, metavar="PATH",
        help="also write a cluster speedscope JSON (speedscope.app)",
    )
    p.add_argument(
        "--trace", nargs="+", default=None, metavar="TRACE",
        help="trace spools (*.trace.jsonl) or directories: merge them via "
        "trace_merge and embed cpu_frac counter tracks alongside the frame "
        "spans on the unified timeline",
    )
    a = p.parse_args(argv)
    try:
        doc = merge(a.inputs, trace_inputs=a.trace)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    with open(a.out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    prof = doc["profile"]
    print(
        f"merged {len(prof['processes'])} process profile(s), "
        f"{prof['samples_total']} sample(s) "
        f"({prof['on_cpu_total']} on-CPU) -> {a.out}"
    )
    for pr in prof["processes"]:
        print(
            f"  {pr['process']}: {pr['samples']} samples @ {pr['hz']:g} Hz, "
            f"offset {pr['mono_to_wall_offset_s']:.3f}s, "
            f"{pr['overflow']} overflow"
        )
    for h in prof["hot"][:10]:
        print(f"  hot: {h['self']:>8} {h['frame']}")
    if a.collapsed:
        lines = merged_collapsed(a.inputs)
        with open(a.collapsed, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        print(f"collapsed stacks -> {a.collapsed} ({len(lines)} stacks)")
    if a.speedscope:
        with open(a.speedscope, "w", encoding="utf-8") as f:
            json.dump(merged_speedscope(a.inputs), f)
        print(f"speedscope profile -> {a.speedscope}")
    if a.trace:
        print("cpu_frac counter tracks embedded alongside trace spans "
              "(open --out in https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
