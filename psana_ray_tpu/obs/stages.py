"""Canonical pipeline stage names + per-record latency decomposition.

Every frame crosses the same boundaries on its way from detector source to
device step; this module names them ONCE so the record envelope
(:func:`psana_ray_tpu.records.mark_hop`), the latency histograms
(:class:`psana_ray_tpu.utils.metrics.StageTimes`), the Prometheus export,
and the device-timeline annotations (:func:`psana_ray_tpu.utils.trace.
annotate_stage`) all agree.

Hop boundaries (monotonic timestamps stamped on the record)::

    src ──enqueue──▶ enq ──queue_dwell──▶ deq ──dequeue──▶ push
        ──batch──▶ batch ──device_put──▶ device_put ──dispatch──▶ (step done)

Stage semantics:

- ``enqueue``      source read done → accepted by the transport
  (includes producer-side backpressure wait);
- ``queue_dwell``  accepted → popped by a consumer (queue residency);
- ``dequeue``      popped → copied into the batch buffer (decode + memcpy);
- ``batch``        in the batch buffer → batch emitted (waiting for the
  batch to fill; first records of a batch wait longest);
- ``device_put``   batch emitted → staged on device (host→device copy,
  or global sharded assembly on multi-host);
- ``dispatch``     staged → step returned (prefetch-buffer dwell + device
  step; with ``block_until_ready`` a true device latency).

Because stages are CONSECUTIVE differences of one record's timeline, the
per-stage means over a set of records sum EXACTLY to the mean of the
``e2e`` pseudo-stage (src → step done) over the same records — that is
what lets BENCH's 3400× device-vs-e2e gap decompose into named stages
instead of a single opaque number. A missing boundary (e.g. records that
crossed a process hop, where monotonic stamps don't travel) never breaks
the telescoping: the next present boundary's stage absorbs the gap.
"""

from __future__ import annotations

import time
from typing import Optional

from psana_ray_tpu.obs.tracing import TRACE_KEY
from psana_ray_tpu.utils.metrics import StageTimes  # noqa: F401  (re-export)

# Hop (boundary) names, in pipeline order.
HOP_SRC = "src"
HOP_ENQ = "enq"
HOP_DEQ = "deq"
HOP_PUSH = "push"
HOP_BATCH = "batch"
HOP_DEVICE_PUT = "device_put"
# the final boundary (step done) is passed explicitly, never stamped

HOPS = (HOP_SRC, HOP_ENQ, HOP_DEQ, HOP_PUSH, HOP_BATCH, HOP_DEVICE_PUT)

# Stage names: STAGES[i] spans HOPS[i] -> HOPS[i+1]; the last stage spans
# the last hop -> step completion.
STAGE_ENQUEUE = "enqueue"
STAGE_QUEUE_DWELL = "queue_dwell"
STAGE_DEQUEUE = "dequeue"
STAGE_BATCH = "batch"
STAGE_DEVICE_PUT = "device_put"
STAGE_DISPATCH = "dispatch"
STAGE_E2E = "e2e"  # pseudo-stage: src -> step done (the decomposed total)

STAGES = (
    STAGE_ENQUEUE,
    STAGE_QUEUE_DWELL,
    STAGE_DEQUEUE,
    STAGE_BATCH,
    STAGE_DEVICE_PUT,
    STAGE_DISPATCH,
)


def observe_record_stages(
    stages: StageTimes, hops: dict, t_end: float
) -> None:
    """Fold one record's hop stamps + the step-completion time into the
    per-stage histograms. Missing boundaries are skipped; the stage ending
    at the next present boundary absorbs the gap, so the observed stages
    always telescope to (last boundary - first boundary).

    A traced record (its hops dict carries the sampled trace id under
    ``obs.tracing.TRACE_KEY``) stamps that id as the stage histograms'
    exemplar — the retained "which frame is in the bad bucket" link that
    ``trace_merge --exemplar`` resolves (ISSUE 13)."""
    exemplar = hops.get(TRACE_KEY)  # the sampled trace id, when traced
    prev: Optional[float] = None
    for i, hop in enumerate(HOPS):
        t = hops.get(hop)
        if t is None:
            continue
        if prev is not None:
            # STAGES[i-1] is the stage ENDING at this boundary; when an
            # earlier boundary was missing it absorbs the gap (telescoping)
            stages.observe(STAGES[i - 1], t - prev, exemplar=exemplar)
        prev = t
    if prev is not None:
        stages.observe(STAGE_DISPATCH, t_end - prev, exemplar=exemplar)
        t0 = hops.get(HOP_SRC)
        if t0 is not None:
            stages.observe(STAGE_E2E, t_end - t0, exemplar=exemplar)


def observe_batch_stages(stages: StageTimes, batch, t_end: Optional[float] = None) -> None:
    """Per-record stage decomposition for a whole batch (its ``hops``
    list carries one stamp dict per timed real record). Near-zero cost on
    untimed streams: ``batch.hops`` is None unless a producer stamped the
    records."""
    hops_list = getattr(batch, "hops", None)
    if not hops_list:
        return
    t_end = time.monotonic() if t_end is None else t_end
    for hops in hops_list:
        observe_record_stages(stages, hops, t_end)
