"""HTTP metrics endpoint: Prometheus text format over stdlib http.server.

Every long-running CLI (producer, consumer, sfx, queue server) takes a
``--metrics_port`` flag; non-zero starts one :class:`MetricsServer` on a
daemon thread serving:

- ``GET /metrics``  — Prometheus exposition text-format 0.0.4 (scrape me);
- ``GET /healthz``  — the same registry as a JSON snapshot (humans, tests,
  and the bench artifact use this shape);
- ``GET /federate`` — the snapshot wrapped host-tagged (host/pid/wall/
  mono), byte-compatible with the queue server's 'N' ``{"op":
  "metrics"}`` RPC answer — what the ISSUE 13 cluster collector pulls
  from producer/consumer processes (it falls back to ``/healthz`` on
  peers predating the route).

``--metrics_port 0`` (the default) starts nothing — the disabled path
costs literally zero (no socket, no thread). Tests construct
:class:`MetricsServer` with ``port=0`` directly, which binds an ephemeral
port (the CLI semantics of "0 = off" live in
:func:`start_metrics_server`, not here).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from psana_ray_tpu.obs.registry import MetricsRegistry

logger = logging.getLogger(__name__)

CONTENT_TYPE_PROM = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Background-thread HTTP server over one :class:`MetricsRegistry`."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        host: str = "0.0.0.0",
        port: int = 0,
    ):
        self.registry = registry if registry is not None else MetricsRegistry.default()
        reg = self.registry

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, ctype: str, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                try:
                    if path in ("/metrics", "/"):
                        body = reg.render_prometheus().encode()
                        self._send(200, CONTENT_TYPE_PROM, body)
                    elif path in ("/healthz", "/snapshot"):
                        body = json.dumps(reg.snapshot()).encode()
                        self._send(200, "application/json", body)
                    elif path == "/federate":
                        from psana_ray_tpu.obs.registry import federation_payload

                        body = json.dumps(federation_payload(reg)).encode()
                        self._send(200, "application/json", body)
                    else:
                        self._send(404, "text/plain", b"not found\n")
                except BrokenPipeError:
                    pass  # scraper hung up mid-response
                except Exception as e:  # noqa: BLE001 — never kill the server
                    try:
                        self._send(500, "text/plain", repr(e).encode())
                    except OSError:
                        pass

            def log_message(self, fmt, *args):  # quiet: scrapes are periodic
                logger.debug("metrics http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            daemon=True,
            name="metrics-http",
        )

    def start(self) -> "MetricsServer":
        self._thread.start()
        logger.info("metrics endpoint up on %s:%d (/metrics, /healthz)", self.host, self.port)
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc):
        self.close()


def add_metrics_args(parser) -> None:
    """The shared ``--metrics_host``/``--metrics_port`` pair every
    long-running CLI exposes (one definition: help text, defaults, and
    any future auth/validation stay in sync across the fleet)."""
    parser.add_argument(
        "--metrics_host", default="0.0.0.0",
        help="interface for --metrics_port (default all interfaces: a "
        "central Prometheus scrapes across hosts; bind 127.0.0.1 on "
        "untrusted networks — the endpoint is unauthenticated)",
    )
    parser.add_argument(
        "--metrics_port", type=int, default=0,
        help="serve Prometheus metrics (frames/bytes/batches counters, "
        "latency quantiles, per-stage timings, queue health) on this "
        "port; 0 = disabled (zero cost)",
    )


def start_metrics_server(
    port: int,
    registry: Optional[MetricsRegistry] = None,
    host: str = "0.0.0.0",
) -> Optional[MetricsServer]:
    """CLI entry: start the endpoint on ``port``; ``port <= 0`` is OFF
    (returns None, zero cost — the ``--metrics_port`` contract). Failure
    to bind logs and returns None rather than killing the pipeline: data
    flow outranks its own observability."""
    if port is None or port <= 0:
        return None
    try:
        return MetricsServer(registry=registry, host=host, port=port).start()
    except OSError as e:
        logger.warning("metrics endpoint on port %d unavailable: %s", port, e)
        return None
