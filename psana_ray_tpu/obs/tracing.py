"""Sampled per-frame distributed tracing across the pipeline's processes.
# lint: hot-path

PR 1 gave the pipeline aggregate stage histograms; this module answers the
question those cannot: *where did THIS frame spend its time* across the
producer -> queue server -> consumer -> device boundary (the per-request
trace production streaming systems pair with their counters — tf.data's
pipeline instrumentation and DALI's per-iteration view, PAPERS.md).

Three pieces:

- :class:`TraceContext` — a compact wire-format context (trace id, sample
  flag, origin host/pid) that rides the :class:`~psana_ray_tpu.records.
  FrameRecord` envelope. Sampled frames encode as schema v3 with the
  25-byte context appended after the shape; UNSAMPLED frames encode as
  plain v2, byte-identical to the pre-tracing wire format — the
  unsampled hot path pays zero allocations and zero wire bytes
  (the same gating discipline as PR 1's ``stage_timing``).
- :class:`Tracer` — the per-process span sink. Each process appends
  spans (producer: produce/enqueue; queue server: queue_dwell/relay;
  consumer: dequeue/batch/device_put/dispatch — reusing the
  :mod:`psana_ray_tpu.obs.stages` boundaries) to a bounded per-process
  JSONL spool, together with (wallclock, monotonic) clock anchors and
  peer-anchor exchanges (tcp opcode ``A``) that let the merge tool put
  three processes on one timeline.
- ``python -m psana_ray_tpu.obs.trace_merge`` reads the spools and emits
  Chrome trace-event JSON loadable in Perfetto / TensorBoard, one track
  per process, frame spans linked by trace id. The device-side
  ``stage.*`` annotations (:func:`psana_ray_tpu.utils.trace.
  annotate_stage`) use the same stage vocabulary, so a jax.profiler
  capture of the same run lines up against the host spans.

Everything here is pure stdlib (no numpy, no jax) so every process —
including the queue server — can afford the import. Span recording for
sampled frames is one lock + one small dict append; the spool is flushed
in the background of normal operation (every ``FLUSH_EVERY`` spans and at
process exit), never per span.
"""

from __future__ import annotations

import atexit
import dataclasses
import itertools
import json
import os
import socket
import struct
import threading
import time
from typing import Any, Dict, Optional

__all__ = [
    "TraceContext",
    "Tracer",
    "TRACER",
    "TRACE_KEY",
    "SPAN_PRODUCE",
    "SPAN_RELAY",
    "add_trace_args",
    "configure_from_args",
    "emit_batch_spans",
    "exchange_anchors",
    "obs_status_suffix",
]

# Reserved key in a record's ``hops`` dict carrying the trace id through
# the in-process batching path (the hops dict already rides the envelope;
# stage observation iterates only the HOP_* names, so the key is inert
# there).
TRACE_KEY = "trace_id"

# Span names beyond the canonical stage names (obs.stages):
SPAN_PRODUCE = "produce"  # instant: source read done (frame is born)
SPAN_RELAY = "relay"  # queue server: response serialization + send

_FLAG_SAMPLED = 0x01

# trace_id:u64, origin_pid:u32, flags:u8, origin_host:12s (utf-8, padded)
_CTX_WIRE = struct.Struct("<QIB12s")


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Compact per-frame trace context; rides the record envelope.

    ``trace_id`` is unique per sampled frame across the deployment
    (origin pid + counter mixed in); ``origin_host``/``origin_pid``
    identify the producing process for the merged timeline."""

    trace_id: int
    sampled: bool = True
    origin_host: str = ""
    origin_pid: int = 0

    WIRE_SIZE = _CTX_WIRE.size  # 25 bytes on sampled frames only

    def pack(self) -> Any:
        flags = _FLAG_SAMPLED if self.sampled else 0
        host = self.origin_host.encode("utf-8", "replace")[:12]
        return _CTX_WIRE.pack(
            self.trace_id & 0xFFFFFFFFFFFFFFFF, self.origin_pid & 0xFFFFFFFF,
            flags, host,
        )

    @staticmethod
    def unpack_from(buf, offset: int) -> "TraceContext":
        trace_id, pid, flags, host = _CTX_WIRE.unpack_from(buf, offset)
        return TraceContext(
            trace_id=trace_id,
            sampled=bool(flags & _FLAG_SAMPLED),
            origin_host=host.rstrip(b"\0").decode("utf-8", "replace"),
            origin_pid=pid,
        )


# Spool record tags (one JSON object per line):
#   m = meta (process identity, sample config)   a = clock anchor
#   p = peer anchor (tcp opcode 'A' exchange)    s = span   i = instant
FLUSH_EVERY = 128


class Tracer:
    """Per-process span sink with a bounded JSONL spool.

    Disabled (the default) every surface is a no-op behind ONE attribute
    check; ``maybe_trace`` on an enabled tracer allocates NOTHING for
    unsampled frames (counter arithmetic only — pinned by test and the
    hot-alloc checker's span fixtures)."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._every = 0  # sample 1 frame in N; 0 = off
        # frame ticker: itertools.count.__next__ is atomic in CPython, so
        # concurrent producer shard threads get UNIQUE frame numbers (and
        # therefore unique trace ids) without a hot-path lock; _count is
        # a best-effort gauge of the latest value for snapshot()
        self._ticker = itertools.count(1)
        self._count = 0
        self._id_base = 0
        self._host = socket.gethostname()
        self._pid = os.getpid()
        self._process = ""
        self._path: Optional[str] = None
        self._f = None
        self._buf: list = []
        self._spans = 0
        self._drops = 0
        self._max_spans = 0
        self._by_name: Dict[str, int] = {}
        self._atexit_registered = False

    # -- configuration ----------------------------------------------------
    def configure(
        self,
        spool_dir: str,
        sample_every: int = 100,
        process: str = "proc",
        max_spans: int = 200_000,
    ) -> "Tracer":
        """Enable tracing: sample 1 frame in ``sample_every`` (1 = every
        frame) and spool spans to ``spool_dir``. Reconfiguring closes the
        previous spool first. ``max_spans`` bounds the spool — beyond it
        spans are dropped and counted (``spans_dropped``), never blocking
        the pipeline."""
        if sample_every <= 0:
            raise ValueError("sample_every must be >= 1 (frames per sample)")
        with self._lock:
            self._close_locked()
            os.makedirs(spool_dir, exist_ok=True)
            self._process = process
            self._pid = os.getpid()
            self._every = int(sample_every)
            self._ticker = itertools.count(1)
            self._count = 0
            self._spans = 0
            self._drops = 0
            self._by_name = {}
            self._max_spans = max_spans
            # unique-across-processes id space: pid in the top bits, a
            # wall-clock sub-second salt so quick restarts don't collide
            salt = int(time.time() * 1e6) & 0xFFFFF
            self._id_base = ((self._pid & 0xFFFFFFFF) << 28) ^ (salt << 8)
            self._path = os.path.join(
                spool_dir, f"{process}-{self._host}-{self._pid}.trace.jsonl"
            )
            self._f = open(self._path, "w", encoding="utf-8")
            self._buf = [
                self._line(
                    t="m", process=process, host=self._host, pid=self._pid,
                    every=self._every, start_wall=time.time(),
                    start_mono=time.monotonic(),
                )
            ]
            self._anchor_locked()
            self._flush_locked()
            self.enabled = True
            if not self._atexit_registered:
                self._atexit_registered = True
                atexit.register(self.close)
        return self

    @property
    def spool_path(self) -> Optional[str]:
        return self._path

    @property
    def sample_every(self) -> int:
        return self._every

    # -- hot path ---------------------------------------------------------
    def maybe_trace(self) -> Optional[TraceContext]:
        """Per-frame sampling gate (producer side). Disabled: one
        attribute check. Enabled but unsampled: counter arithmetic only —
        no allocation, no lock. Sampled: a fresh :class:`TraceContext`.

        Thread-safe without locking: the ticker hands concurrent shard
        threads unique frame numbers (atomic ``__next__``), and the
        sample config is read ONCE so a concurrent ``close()`` can never
        produce a divide-by-zero mid-frame — worst case a frame straddling
        the close is sampled into a spool that is already flushing."""
        if not self.enabled:
            return None
        every = self._every
        if every <= 0:  # racing a close(): tracing is over, not an error
            return None
        n = next(self._ticker)
        self._count = n  # best-effort gauge (snapshot/status only)
        if n % every:
            return None
        return TraceContext(
            trace_id=(self._id_base + n) & 0xFFFFFFFFFFFFFFFF,
            sampled=True,
            origin_host=self._host,
            origin_pid=self._pid,
        )

    # -- span sinks (sampled frames only) ---------------------------------
    def span(self, trace_id: int, name: str, t0: float, t1: float) -> None:
        """One completed span ``[t0, t1]`` in THIS process's monotonic
        domain (the merge tool aligns domains via the spooled anchors)."""
        if not self.enabled:
            return
        self._emit(name, self._line(t="s", id=trace_id, n=name, a=t0, b=t1))

    def instant(self, trace_id: int, name: str, t: float) -> None:
        """A zero-duration marker (e.g. ``produce`` at source-read done)."""
        if not self.enabled:
            return
        self._emit(name, self._line(t="i", id=trace_id, n=name, a=t))

    def _emit(self, name: str, line: str) -> None:
        """THE bounded-spool sink: cap accounting, per-name counts, and
        the every-``FLUSH_EVERY`` anchor+flush policy live here once."""
        with self._lock:
            if self._spans >= self._max_spans:
                self._drops += 1
                return
            self._spans += 1
            self._by_name[name] = self._by_name.get(name, 0) + 1
            self._buf.append(line)
            if len(self._buf) >= FLUSH_EVERY:
                self._anchor_locked()
                self._flush_locked()

    # -- clock alignment --------------------------------------------------
    def write_anchor(self) -> None:
        """Record a (wallclock, monotonic) pair — the merge tool estimates
        this process's monotonic->wall offset from the median of these."""
        if not self.enabled:
            return
        with self._lock:
            self._anchor_locked()

    def record_peer_anchor(self, exchange: dict) -> None:
        """Record one ping/anchor exchange with the queue server (tcp
        opcode ``A``: local send/recv wall+mono around the server's
        wall+mono reply) — lets the merge tool align this process to the
        server's clock across hosts, bounded by the measured RTT."""
        if not self.enabled:
            return
        with self._lock:
            self._buf.append(self._line(t="p", **exchange))

    def _anchor_locked(self) -> None:
        # guarded-by-caller: _lock
        self._buf.append(self._line(t="a", wall=time.time(), mono=time.monotonic()))

    @staticmethod
    def _line(**kw) -> str:
        return json.dumps(kw, separators=(",", ":"))

    # -- lifecycle --------------------------------------------------------
    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        # guarded-by-caller: _lock
        if self._f is None or not self._buf:
            self._buf = self._buf if self._f is not None else []
            return
        self._f.write("\n".join(self._buf) + "\n")
        self._f.flush()
        self._buf = []

    def close(self) -> None:
        """Flush + close the spool and disable. Safe to call repeatedly
        (registered atexit)."""
        with self._lock:
            self._close_locked()

    def _close_locked(self) -> None:
        # guarded-by-caller: _lock
        if self._f is not None:
            self._anchor_locked()
            self._flush_locked()
            try:
                self._f.close()
            except OSError:
                pass
        self._f = None
        self.enabled = False
        self._every = 0

    # -- observability of the observer ------------------------------------
    def snapshot(self) -> dict:
        """Registry source: is tracing on, at what rate, how many spans."""
        with self._lock:
            out: Dict[str, Any] = {
                "enabled": self.enabled,
                "sample_every": self._every,
                "frames_seen_total": self._count,
                "spans_total": self._spans,
                "spans_dropped_total": self._drops,
            }
            if self._by_name:
                out["spans_by_name"] = dict(self._by_name)
        return out

    def status_suffix(self, flight=None) -> str:
        """Heartbeat-line suffix: sample rate, spans emitted, flight-
        recorder event count — empty when tracing is off (the line stays
        exactly as it was before this feature)."""
        if not self.enabled:
            return ""
        with self._lock:
            every, spans, drops = self._every, self._spans, self._drops
        suffix = f" trace[1/{every} spans={spans}"
        if drops:
            suffix += f" drops={drops}"
        suffix += "]"
        if flight is not None:
            suffix += f" flight={flight.event_count}"
        return suffix


#: The process-global tracer every CLI configures (tests build their own).
TRACER = Tracer()


def emit_batch_spans(batch, t_end: float, tracer: Optional[Tracer] = None) -> None:
    """Consumer-side spans for one batch: each traced record's hop stamps
    (``TRACE_KEY`` marks the traced ones) become per-stage spans ending at
    ``t_end`` (step completion) — the same telescoping walk as
    :func:`psana_ray_tpu.obs.stages.observe_record_stages`, so span
    boundaries and histogram boundaries agree by construction. Near-zero
    cost on untraced streams (``batch.hops`` is None)."""
    tr = TRACER if tracer is None else tracer
    if not tr.enabled:
        return
    hops_list = getattr(batch, "hops", None)
    if not hops_list:
        return
    from psana_ray_tpu.obs.stages import HOPS, STAGE_DISPATCH, STAGE_ENQUEUE, STAGES

    for hops in hops_list:
        tid = hops.get(TRACE_KEY)
        if tid is None:
            continue
        prev = None
        for i, hop in enumerate(HOPS):
            t = hops.get(hop)
            if t is None:
                continue
            # skip the enqueue leg: the PRODUCER's _Sender.flush already
            # emitted it (in-process transports share the hops dict, so
            # replaying src->enq here would double the span)
            if prev is not None and STAGES[i - 1] != STAGE_ENQUEUE:
                tr.span(tid, STAGES[i - 1], prev, t)
            prev = t
        if prev is not None:
            tr.span(tid, STAGE_DISPATCH, prev, t_end)


def exchange_anchors(queue, n: int = 3, tracer: Optional[Tracer] = None) -> int:
    """Run ``n`` ping/anchor exchanges against a queue handle that speaks
    the anchor RPC (``TcpQueueClient.anchor``) and spool them. Returns how
    many succeeded; 0 for transports without the RPC (in-process / shm —
    same-host wall clocks already agree)."""
    tr = TRACER if tracer is None else tracer
    anchor = getattr(queue, "anchor", None)
    if not tr.enabled or anchor is None:
        return 0
    done = 0
    for _ in range(n):
        try:
            tr.record_peer_anchor(anchor())
            done += 1
        except Exception:  # noqa: BLE001 — alignment is best-effort
            break
    return done


# -- CLI wiring ------------------------------------------------------------
def add_trace_args(parser) -> None:
    """The shared ``--trace_dir`` / ``--trace_sample`` / ``--flight_dir``
    trio every long-running CLI exposes (one definition, like
    ``add_metrics_args``)."""
    parser.add_argument(
        "--trace_dir", default=None,
        help="enable sampled per-frame distributed tracing: spool spans "
        "to this directory (one JSONL file per process); merge with "
        "`python -m psana_ray_tpu.obs.trace_merge <dir>` and open the "
        "result in Perfetto. Default off (zero cost)",
    )
    parser.add_argument(
        "--trace_sample", type=int, default=100,
        help="sample 1 frame in N for tracing (1 = every frame); only "
        "active with --trace_dir. Unsampled frames pay zero allocations",
    )
    parser.add_argument(
        "--flight_dir", default=None,
        help="crash flight recorder: dump the event ring + metrics "
        "snapshot + thread stacks here on stall/unhandled exception/"
        "SIGUSR2 (default: --trace_dir when set, else off)",
    )


def configure_from_args(args, process: str, queue=None) -> Optional[Tracer]:
    """CLI entry: configure the global tracer + flight recorder from the
    ``add_trace_args`` flags. Registers both as metrics-registry sources
    (``trace`` / ``flight``) so /metrics shows tracing is on. ``queue``
    (optional, a TCP client or monitor handle) seeds the clock alignment
    with peer-anchor exchanges. Returns the tracer, or None when tracing
    stays off."""
    trace_dir = getattr(args, "trace_dir", None)
    flight_dir = getattr(args, "flight_dir", None) or trace_dir
    out = None
    if trace_dir:
        TRACER.configure(
            trace_dir, sample_every=max(1, args.trace_sample), process=process
        )
        out = TRACER
    from psana_ray_tpu.obs.flight import FLIGHT

    if flight_dir:
        FLIGHT.install(flight_dir, process=process)
    if trace_dir or flight_dir:
        from psana_ray_tpu.obs.registry import MetricsRegistry

        reg = MetricsRegistry.default()
        if trace_dir:
            reg.register("trace", TRACER)
        reg.register("flight", FLIGHT)
    if out is not None and queue is not None:
        exchange_anchors(queue)
    return out


def obs_status_suffix() -> str:
    """One-call heartbeat suffix over the global tracer + flight recorder
    (the consumer/sfx ``--status_interval`` lines append this). Durable-
    storage breadcrumbs (ISSUE 8: segment rollover, spill entry/exit,
    recovery scans, torn-tail repairs, replay opens/gaps) get their own
    bracket whenever any fired in this process — empty otherwise, so
    memory-only runs keep their exact pre-durability heartbeat lines."""
    from psana_ray_tpu.obs.flight import FLIGHT

    out = TRACER.status_suffix(FLIGHT)
    rolls = FLIGHT.count_of("segment_rollover")
    spills = FLIGHT.count_of("spill_enter")
    recoveries = FLIGHT.count_of("recovery_scan", "durable_reexpose")
    torn = FLIGHT.count_of("torn_tail_repair")
    replays = FLIGHT.count_of("replay_open", "replay_gap")
    if rolls or spills or recoveries or torn or replays:
        out += (
            f" durable[roll={rolls} spill={spills} recover={recoveries}"
            f" torn={torn} replay={replays}]"
        )
    return out
