"""Stall / backpressure detection over transport queues.

The reference's failure mode for a slow or dead consumer is SILENT: the
queue fills, producers spin in backoff, and nothing anywhere says why
(SURVEY.md §5 — "debugging a slow consumer means print statements"). The
:class:`StallDetector` polls queue ``stats()`` and emits STRUCTURED warn
events when the pipeline degenerates:

- ``backpressure``    the queue has sat at maxsize for longer than
  ``full_threshold_s`` — consumers are not keeping up (or died);
- ``consumer_stall``  depth > 0 but the get counter has not moved for
  ``idle_threshold_s`` — data is waiting and nobody reads;
- ``producer_idle``   depth == 0 and the put counter has not moved for
  ``idle_threshold_s`` — consumers are starved and nobody feeds them
  (producer liveness; a clean EOS also looks like this, which is why
  these are warnings with context, not fatal errors).

Each event is logged once per episode (the flag re-arms when the
condition clears), handed to ``on_event``, kept in a bounded ``events``
deque, and counted — the detector is itself a registry source, so
``psana_ray_stalls_*_total`` series appear on the metrics endpoint.

Since ISSUE 12 the detector also ACTS, not just warns: while any
episode is active a ``degraded`` gauge is up, and a serving gateway
bound via :meth:`StallDetector.bind_gateway` is ESCALATED (its shed
threshold rises — admission runs against the shrunken degraded budget)
for the duration; when the last episode clears, ``on_clear`` fires and
the gateway is restored. The escalate/restore cycle is pinned by
tests/test_serving.py.

``poll_once(now=...)`` is separated from the thread loop so tests drive
time explicitly instead of sleeping.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from psana_ray_tpu.utils.metrics import probe_queue_stats as _queue_stats

logger = logging.getLogger(__name__)

EVENT_BACKPRESSURE = "backpressure"
EVENT_CONSUMER_STALL = "consumer_stall"
EVENT_PRODUCER_IDLE = "producer_idle"


@dataclasses.dataclass(frozen=True)
class StallEvent:
    kind: str
    queue: str
    duration_s: float
    depth: int
    maxsize: int
    detail: str = ""

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))


class _QueueState:
    __slots__ = (
        "last_puts", "last_gets", "last_t",
        "full_since", "full_warned",
        "idle_since", "idle_warned",
        "starved_since", "starved_warned",
        "put_rate", "get_rate",
    )

    def __init__(self):
        self.last_puts: Optional[int] = None
        self.last_gets: Optional[int] = None
        self.last_t: Optional[float] = None
        self.full_since: Optional[float] = None
        self.full_warned = False
        self.idle_since: Optional[float] = None
        self.idle_warned = False
        self.starved_since: Optional[float] = None
        self.starved_warned = False
        self.put_rate = 0.0
        self.get_rate = 0.0


class StallDetector:
    """Poll watched queues; warn loudly when the stream degenerates."""

    def __init__(
        self,
        poll_interval_s: float = 1.0,
        full_threshold_s: float = 5.0,
        idle_threshold_s: float = 10.0,
        on_event: Optional[Callable[[StallEvent], None]] = None,
        on_clear: Optional[Callable[[], None]] = None,
        max_events: int = 256,
    ):
        self.poll_interval_s = poll_interval_s
        self.full_threshold_s = full_threshold_s
        self.idle_threshold_s = idle_threshold_s
        self.on_event = on_event
        # fired once when the LAST active episode clears (the moment the
        # degraded gauge drops) — the restore half of escalate/restore
        self.on_clear = on_clear
        self.events: deque = deque(maxlen=max_events)
        self._counts: Dict[str, int] = {
            EVENT_BACKPRESSURE: 0,
            EVENT_CONSUMER_STALL: 0,
            EVENT_PRODUCER_IDLE: 0,
        }
        self._lock = threading.Lock()
        self._watched: Dict[str, Any] = {}
        self._provider: Optional[Callable[[], Dict[str, Any]]] = None
        self._states: Dict[str, _QueueState] = {}
        self._degraded = False  # any episode active  # guarded-by: _lock
        self._gateways: list = []  # escalate/restore targets  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wiring -----------------------------------------------------------
    def watch(self, name: str, queue) -> "StallDetector":
        """Watch one queue (anything with ``stats()`` or ``size()``)."""
        with self._lock:
            self._watched[name] = queue
        return self

    def watch_provider(self, provider: Callable[[], Dict[str, Any]]) -> "StallDetector":
        """Watch a DYNAMIC queue population: ``provider()`` returns
        ``{name: queue}`` each poll (the queue server's named queues
        appear as clients OPEN them)."""
        self._provider = provider
        return self

    def bind_gateway(self, gateway) -> "StallDetector":
        """Escalate a :class:`~psana_ray_tpu.serving.gateway.
        ServingGateway` while any stall episode is active: its shed
        threshold rises on the first firing and restores when the last
        episode clears — the detector shouting into action instead of
        the void (ISSUE 12)."""
        with self._lock:
            self._gateways.append(gateway)
            degraded = self._degraded
        if degraded:  # bound mid-episode: catch up immediately
            gateway.escalate("stall-detector (bound mid-episode)")
        return self

    def start(self) -> "StallDetector":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True, name="stall-detector")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "StallDetector":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _run(self):
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the watchdog must outlive faults
                logger.exception("stall detector poll failed")

    # -- detection --------------------------------------------------------
    def _queues(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._watched)
        if self._provider is not None:
            try:
                out.update(self._provider() or {})
            except Exception:  # noqa: BLE001
                logger.exception("stall detector queue provider failed")
        return out

    def poll_once(self, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        seen = set()
        for name, queue in self._queues().items():
            seen.add(name)
            try:
                stats = _queue_stats(queue)
            except Exception:
                # dead transport: closure is its own signal — and the
                # episode can never be observed clearing, so DROP the
                # state (a dead queue must not latch the degraded gauge
                # and hold bound gateways escalated forever)
                with self._lock:
                    self._states.pop(name, None)
                continue
            self._check_queue(name, stats, now)
        with self._lock:  # queues that left the watch population too
            for name in [n for n in self._states if n not in seen]:
                self._states.pop(name)
        self._check_cleared()

    @property
    def degraded(self) -> bool:
        """True while any stall episode is active (the gauge the bound
        gateways' shed thresholds follow)."""
        with self._lock:
            return self._degraded

    def _check_cleared(self):
        """Drop the degraded gauge (and restore bound gateways) once no
        watched queue has an active episode left."""
        with self._lock:
            if not self._degraded:
                return
            active = any(
                st.full_warned or st.idle_warned or st.starved_warned
                for st in self._states.values()
            )
            if active:
                return
            self._degraded = False
            gateways = list(self._gateways)
        logger.info("STALL cleared: all episodes resolved")
        for gw in gateways:
            try:
                gw.restore()
            except Exception:  # noqa: BLE001 — the watchdog outlives faults
                logger.exception("stall gateway restore failed")
        if self.on_clear is not None:
            try:
                self.on_clear()
            except Exception:  # noqa: BLE001
                logger.exception("stall on_clear callback failed")

    def _check_queue(self, name: str, stats: dict, now: float):
        with self._lock:  # scrapes iterate _states from the HTTP thread
            st = self._states.setdefault(name, _QueueState())
        depth = int(stats.get("depth", 0))
        maxsize = int(stats.get("maxsize", 0) or 0)
        puts = stats.get("puts")
        gets = stats.get("gets")

        if st.last_t is not None and now > st.last_t:
            dt = now - st.last_t
            if puts is not None and st.last_puts is not None:
                st.put_rate = (puts - st.last_puts) / dt
            if gets is not None and st.last_gets is not None:
                st.get_rate = (gets - st.last_gets) / dt

        # backpressure: pegged at maxsize
        if maxsize and depth >= maxsize:
            st.full_since = now if st.full_since is None else st.full_since
            if not st.full_warned and now - st.full_since >= self.full_threshold_s:
                st.full_warned = True
                self._emit(StallEvent(
                    EVENT_BACKPRESSURE, name, now - st.full_since, depth, maxsize,
                    "queue pegged at maxsize; consumers not keeping up",
                ))
        else:
            st.full_since, st.full_warned = None, False

        # consumer stall: data waiting, gets frozen. Requires a real get
        # counter — a depth-only source (stats() fallback to size()) keeps
        # a standing depth under healthy steady-state consumption, and
        # warning on it would cry wolf every idle_threshold_s
        gets_frozen = gets is not None and gets == st.last_gets
        if depth > 0 and gets_frozen:
            st.idle_since = now if st.idle_since is None else st.idle_since
            if not st.idle_warned and now - st.idle_since >= self.idle_threshold_s:
                st.idle_warned = True
                self._emit(StallEvent(
                    EVENT_CONSUMER_STALL, name, now - st.idle_since, depth, maxsize,
                    "items queued but no consumer progress",
                ))
        else:
            st.idle_since, st.idle_warned = None, False

        # producer liveness: consumers starved, puts frozen
        puts_frozen = puts is not None and puts == st.last_puts
        if depth == 0 and puts_frozen:
            st.starved_since = now if st.starved_since is None else st.starved_since
            if not st.starved_warned and now - st.starved_since >= self.idle_threshold_s:
                st.starved_warned = True
                self._emit(StallEvent(
                    EVENT_PRODUCER_IDLE, name, now - st.starved_since, depth, maxsize,
                    "queue empty and no producer progress (stalled, or done without EOS)",
                ))
        else:
            st.starved_since, st.starved_warned = None, False

        st.last_puts, st.last_gets, st.last_t = puts, gets, now

    def _emit(self, event: StallEvent):
        with self._lock:
            self._counts[event.kind] = self._counts.get(event.kind, 0) + 1
            self._degraded = True
            gateways = list(self._gateways)
        self.events.append(event)
        logger.warning("STALL %s", event.to_json())
        for gw in gateways:  # firing acts, not just warns (ISSUE 12)
            try:
                gw.escalate(f"{event.kind}:{event.queue}")
            except Exception:  # noqa: BLE001 — the watchdog outlives faults
                logger.exception("stall gateway escalate failed")
        if self.on_event is not None:
            try:
                self.on_event(event)
            except Exception:  # noqa: BLE001
                logger.exception("stall on_event callback failed")

    # -- registry source ---------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            states = list(self._states.items())
            degraded = self._degraded
        out: dict = {f"{k}_total": v for k, v in counts.items()}
        out["degraded"] = 1 if degraded else 0
        for name, st in states:
            out[name] = {
                "put_rate": round(st.put_rate, 3),
                "get_rate": round(st.get_rate, 3),
            }
        return out
