"""Standalone queue server — the ``ray start --head`` of this framework.

The reference's runbook starts a Ray head node whose GCS hosts detached
queue actors by (namespace, name) (``README.md:13-18``,
``shared_queue.py:33-38``); producers and consumers on other nodes join it
by address. Here the equivalent service is one process hosting *many named
queues* over TCP (:mod:`transport.tcp` OPEN opcode): clients reach it with
``--address tcp://host:port`` and their configured (namespace, queue_name)
get-or-creates the queue server-side — one server serves every detector's
stream. Named queues are detached: they outlive the clients that created
them, until this process stops.

``--workers N`` (ISSUE 17) breaks the single-core ceiling: N forked
evloop processes share the ONE listening port via ``SO_REUSEPORT``, each
named queue rendezvous-pinned to exactly one worker, connections shipped
between workers over ``SCM_RIGHTS`` when the kernel's connection
sharding disagrees with the queue pinning. The client contract is
unchanged — one address, same ordering, same redelivery.

Optionally backed by a shared-memory ring (``--shm``) so local processes on
the serving host can bypass TCP entirely while remote ones fan in/out over
the network.

Teardown parity (``ray stop``, reference ``README.md:37-40``): SIGINT/SIGTERM
closes the queue, unblocking all clients with a dead-transport error.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
import time

logger = logging.getLogger(__name__)


def main(argv=None):
    p = argparse.ArgumentParser(prog="psana-ray-tpu-queue")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=6379, help="reference head-node port")
    p.add_argument("--queue_size", type=int, default=100)
    p.add_argument(
        "--workers",
        type=int,
        default=1,
        help=(
            "fork this many evloop server processes sharing ONE port via "
            "SO_REUSEPORT (ISSUE 17): each named queue lives on exactly "
            "one worker (rendezvous-pinned, respawn-stable), connections "
            "migrate between workers over SCM_RIGHTS when the kernel's "
            "accept sharding disagrees with the pinning, and a crashed "
            "worker is respawned with its queues recovered from the "
            "durable log. Clients see one address and the unchanged "
            "contract. Incompatible with --shm and --replicate_peers"
        ),
    )
    p.add_argument(
        "--shm",
        default=None,
        metavar="NAME",
        help=(
            "back queues with shm rings: the default queue uses ring NAME "
            "(local procs attach via shm://NAME); named queues use ring "
            "<namespace>__<queue_name> (local procs attach via shm:// "
            "with matching config)"
        ),
    )
    p.add_argument(
        "--durable_dir",
        default=None,
        metavar="DIR",
        help=(
            "back every queue with a recycled mmap'd segment log under "
            "DIR (ISSUE 8): queued frames survive kill -9/restart (boot "
            "re-exposes everything above the committed offset, repairing "
            "a torn tail by CRC truncation), depth beyond RAM spills to "
            "the log, consumers can --replay the retained range, and the "
            "consumer-group coordinator state is persisted too. "
            "Incompatible with --shm"
        ),
    )
    from psana_ray_tpu.config import DurabilityConfig

    # ONE source of truth for the durability knobs: the dataclass the
    # library surface documents is also where the CLI defaults live
    dur_defaults = DurabilityConfig()
    p.add_argument(
        "--segment_bytes", type=int, default=dur_defaults.segment_bytes,
        help="pre-allocated size of one segment file (recycled, never "
        "reallocated; must fit the largest record)",
    )
    p.add_argument(
        "--retain_segments", type=int, default=dur_defaults.retain_segments,
        help="fully-consumed segments kept for --replay before being "
        "recycled; unconsumed records are NEVER recycled regardless",
    )
    p.add_argument(
        "--fsync", choices=("none", "batch", "always"),
        default=dur_defaults.fsync,
        help="segment-log fsync policy: 'none' survives process death "
        "(page cache) but a machine crash may lose the tail; 'batch' "
        "fsyncs every --fsync_batch_n appends + on roll/commit; "
        "'always' fsyncs per append (measured overhead in PERF_NOTES)",
    )
    p.add_argument(
        "--fsync_batch_n", type=int, default=dur_defaults.fsync_batch_n,
        help="appends per fsync under --fsync batch",
    )
    p.add_argument(
        "--ram_items", type=int, default=dur_defaults.ram_items,
        help="RAM-resident records per durable queue before spilling "
        "delivery to log reads (0 = the queue's --queue_size)",
    )
    p.add_argument(
        "--replicate_peers",
        default=None,
        metavar="HOST:PORT,...",
        help=(
            "chain-replicate durable partition logs across this static "
            "server list (ISSUE 11): each durable queue this server "
            "owns ships its segment log to the next server in the "
            "partition's rendezvous ranking, producer acks wait for "
            "the follower (replicated ack floor), and the consumer-"
            "group coordinator snapshot replicates under a leader "
            "lease. Every server of the cluster should be started "
            "with the SAME list. Requires --durable_dir and "
            "--advertise"
        ),
    )
    p.add_argument(
        "--advertise",
        default=None,
        metavar="HOST:PORT",
        help=(
            "this server's own address AS IT APPEARS in "
            "--replicate_peers (placement is computed from the peer "
            "list, so the spelling must match exactly)"
        ),
    )
    p.add_argument(
        "--replica_codec",
        default=None,
        help=(
            "wire codec for the replication links ('auto', a codec "
            "name, or unset for raw) — the segment log ships "
            "compressed exactly like any other negotiated link"
        ),
    )
    p.add_argument(
        "--port_file", default=None,
        help="write the bound port to this file once listening (harness "
        "support: lets a supervisor/test start with --port 0 and learn "
        "the port without parsing logs)",
    )
    p.add_argument(
        "--max_conns",
        type=int,
        default=0,
        help=(
            "admission control: refuse connections past this many with a "
            "clean protocol error instead of accepting unboundedly (an "
            "accept storm must not OOM the relay); 0 = unlimited"
        ),
    )
    p.add_argument(
        "--drain_s",
        type=float,
        default=10.0,
        help=(
            "graceful-shutdown window: on SIGINT/SIGTERM the server stops "
            "accepting PUTs but keeps serving GETs until every queue is "
            "empty or this many seconds pass, THEN closes (0 = abrupt)"
        ),
    )
    from psana_ray_tpu.autotune import add_autotune_args
    from psana_ray_tpu.obs import (
        add_history_args,
        add_metrics_args,
        add_profile_args,
        add_trace_args,
    )

    add_metrics_args(p)
    add_trace_args(p)
    add_history_args(p)
    add_profile_args(p)
    add_autotune_args(p)
    p.add_argument(
        "--stall_poll_s", type=float, default=1.0,
        help="queue-health poll interval for the stall detector "
        "(backpressure / consumer-stall / producer-idle warnings); "
        "0 = detector off",
    )
    p.add_argument(
        "--stall_full_s", type=float, default=5.0,
        help="warn 'backpressure' after a queue sits at maxsize this long",
    )
    p.add_argument(
        "--stall_idle_s", type=float, default=10.0,
        help="warn 'consumer_stall'/'producer_idle' after put/get "
        "counters freeze this long",
    )
    p.add_argument("--log_level", default="INFO")
    a = p.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, a.log_level.upper(), logging.INFO),
        format="%(asctime)s - %(levelname)s - %(message)s",
    )

    if a.durable_dir and a.shm:
        p.error("--durable_dir and --shm are mutually exclusive (the "
                "segment log backs in-process queues; shm rings have "
                "their own lifetime)")
    if a.replicate_peers and not (a.durable_dir and a.advertise):
        p.error("--replicate_peers requires --durable_dir (the segment "
                "log is what replicates) and --advertise (this server's "
                "own address in the peer list)")
    if a.replicate_peers:
        _peers = [s.strip() for s in a.replicate_peers.split(",") if s.strip()]
        if a.advertise not in _peers:
            # a spelling mismatch would silently disable all shipping
            # (placement can't find this server in the chain)
            p.error(f"--advertise {a.advertise!r} does not appear in "
                    f"--replicate_peers {_peers} — the spellings must "
                    f"match exactly or no queue will ever replicate")
    if a.workers > 1:
        import socket as _socket

        if not hasattr(_socket, "SO_REUSEPORT"):
            p.error("--workers needs SO_REUSEPORT, which this platform "
                    "does not expose — run a single worker")
        if a.shm:
            p.error("--workers is incompatible with --shm (shm rings "
                    "already give local processes multi-process access; "
                    "pick one data plane)")
        if a.replicate_peers:
            p.error("--workers is incompatible with --replicate_peers "
                    "(replica links bind queues directly to one serving "
                    "process; run replicated servers single-worker)")
        return _run_workers(a, dur_defaults)
    return _serve(a, dur_defaults)


def _run_workers(a, dur_defaults) -> int:
    """The parent of a ``--workers N`` fleet: resolve the shared port,
    fork N workers (each builds its full server in :func:`_serve`),
    respawn the dead, forward shutdown. The parent itself serves
    nothing — it is pure supervision, and it forks BEFORE starting any
    thread so no lock is ever cloned mid-hold."""
    import os
    import tempfile

    from psana_ray_tpu.transport.splice import probe_report
    from psana_ray_tpu.transport.workers import (
        WorkerContext,
        WorkerSupervisor,
        resolve_port,
    )

    port = resolve_port(a.host, a.port)
    sock_dir = tempfile.mkdtemp(prefix="psana-workers-")

    def _worker_entry(worker_id):
        ctx = WorkerContext(worker_id, a.workers, sock_dir)
        _serve(a, dur_defaults, worker_ctx=ctx, port=port)

    sup = WorkerSupervisor(a.workers, _worker_entry).start()
    if a.port_file:
        with open(a.port_file + ".tmp", "w") as f:
            f.write(str(port))
        os.replace(a.port_file + ".tmp", a.port_file)  # atomic: no torn read
    logger.info(
        "queue server: %d workers sharing %s:%d via SO_REUSEPORT "
        "(rendezvous-pinned queues, SCM_RIGHTS migration, respawn on "
        "death; kernel pass-through probe: %s) — clients use "
        "--address tcp://<host>:%d exactly as with one worker",
        a.workers, a.host, port, probe_report(), port,
    )

    done = threading.Event()

    def _stop(sig, frame):
        logger.info("signal %s — shutting down worker fleet", sig)
        done.set()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    done.wait()
    # each worker runs its own graceful drain inside its SIGTERM handler
    sup.stop(timeout_s=a.drain_s + 10.0)
    return 0


def _serve(a, dur_defaults, worker_ctx=None, port=None) -> int:
    """One full queue-server process: backing, TCP server, obs plane,
    autotune, signal-driven drain. With ``worker_ctx`` this is one
    worker of a ``--workers`` fleet: it reuseport-binds the shared
    port, owns only its rendezvous partitions, and tags its telemetry
    with the worker id."""
    from psana_ray_tpu.obs import MetricsRegistry, StallDetector, start_metrics_server
    from psana_ray_tpu.transport.ring import RingBuffer
    from psana_ray_tpu.transport.tcp import TcpQueueServer

    wid = worker_ctx.worker_id if worker_ctx is not None else None
    owns_default = worker_ctx is None or wid == worker_ctx.default_owner
    queue_factory = None
    group_store_path = None
    replication = None
    # late-bound autotune registry hook: named queues open AFTER the
    # daemon starts, and each durable one registers its own dials
    tune_box = {"daemon": None}
    if a.durable_dir:
        import os

        from psana_ray_tpu.autotune.knobs import fsync_batch_knob, ram_items_knob
        from psana_ray_tpu.storage import DurableRingBuffer, SegmentLog

        os.makedirs(a.durable_dir, exist_ok=True)
        # per-worker coordinator state: a queue's consumer groups are
        # only ever touched by its owning worker (ops route there), so
        # per-worker files never race; keep --workers N stable across
        # restarts or group progress stays in the old owner's file
        group_store_path = os.path.join(
            a.durable_dir,
            "groups.json" if wid is None else f"groups-w{wid}.json",
        )

        def _durable_backing(ns, name, maxsize):
            # one log directory per named queue; the boot-time recovery
            # scan runs inside SegmentLog.__init__
            qdir = os.path.join(a.durable_dir, f"{ns}__{name}")
            log = SegmentLog(
                qdir,
                segment_bytes=a.segment_bytes,
                retain_segments=a.retain_segments,
                fsync=a.fsync,
                fsync_batch_n=a.fsync_batch_n,
                name=f"{ns}/{name}",
            )
            q = DurableRingBuffer(
                log, maxsize=maxsize, name=f"{ns}__{name}",
                ram_items=a.ram_items or None,
                # spill reads resolve lazily so the evloop can splice
                # the on-disk payload straight to the socket (ISSUE 17)
                lazy_spill=True,
            )
            depth = q.size()
            if depth:
                logger.info(
                    "durable queue (%s, %s): recovered %d unconsumed "
                    "record(s) from %s (committed offset %d%s)",
                    ns, name, depth, qdir, log.committed(""),
                    ", TORN TAIL repaired" if log.torn_tail_repaired else "",
                )
            daemon = tune_box["daemon"]
            if daemon is not None and (ns, name) != ("default", "default"):
                # per-named-queue dials (ISSUE 17): each durable log
                # tunes fsync batching and spill threshold to ITS
                # producer, suffixed so names never collide
                reg = daemon.controller.registry
                try:
                    reg.register(
                        fsync_batch_knob(log, name=f"fsync_batch_n:{ns}/{name}"),
                        "--fsync_batch_n set explicitly"
                        if a.fsync_batch_n != dur_defaults.fsync_batch_n
                        else None,
                    )
                    reg.register(
                        ram_items_knob(q, name=f"ram_items:{ns}/{name}"),
                        "--ram_items set explicitly"
                        if a.ram_items != dur_defaults.ram_items
                        else None,
                    )
                except ValueError:
                    pass  # same name re-opened in-process: dials exist
            return q

        queue_factory = _durable_backing
        if owns_default:
            backing = _durable_backing("default", "default", a.queue_size)
        else:
            # this worker never serves the default queue (ops on it
            # migrate to its owner); a plain ring satisfies the server
            # ctor without touching the owner's log directory
            backing = RingBuffer(a.queue_size)
        logger.info(
            "backing queues: segment logs under %s (segment_bytes=%d, "
            "retain=%d, fsync=%s)",
            a.durable_dir, a.segment_bytes, a.retain_segments, a.fsync,
        )
        if a.replicate_peers:
            from psana_ray_tpu.cluster.replication import ReplicationManager

            peers = [s.strip() for s in a.replicate_peers.split(",") if s.strip()]
            replication = ReplicationManager(
                a.durable_dir, peers, a.advertise,
                codec=a.replica_codec,
                segment_bytes=a.segment_bytes,
                retain_segments=a.retain_segments,
                fsync=a.fsync,
                fsync_batch_n=a.fsync_batch_n,
            )
            logger.info(
                "replication: chain over %s (advertise=%s, codec=%s) — "
                "owned durable queues ship to their rendezvous "
                "runner-up; producer acks ride the replicated floor",
                peers, a.advertise, a.replica_codec or "raw",
            )
    elif a.shm:
        from psana_ray_tpu.transport.shm_ring import ShmRingBuffer

        def _shm_backing(name, maxsize):
            try:
                return ShmRingBuffer.create(name, maxsize=maxsize)
            except RuntimeError:
                return ShmRingBuffer.attach(name, retries=1, interval_s=0.1)

        backing = _shm_backing(a.shm, a.queue_size)
        # named queues (OPEN opcode) get shm backings too, named with the
        # SAME <namespace>__<queue_name> derivation as transport/
        # addressing.shm_ring_name — so a local consumer using
        # `--address shm://` with matching config reads the very ring that
        # remote producers feed over TCP (no second copy, no TCP hop)
        def queue_factory(ns, name, maxsize):
            shm_name = f"{ns}__{name}"
            logger.info("named queue (%s, %s) -> shm ring %r", ns, name, shm_name)
            return _shm_backing(shm_name, maxsize)

        logger.info("backing queues: shm rings (default ring %r)", a.shm)
    else:
        backing = RingBuffer(a.queue_size)

    server = TcpQueueServer(
        backing, host=a.host, port=port if port is not None else a.port,
        maxsize=a.queue_size,
        queue_factory=queue_factory, max_conns=a.max_conns,
        group_store_path=group_store_path, replication=replication,
        reuseport=worker_ctx is not None, worker_ctx=worker_ctx,
    ).serve_background()
    if a.port_file and worker_ctx is None:  # fleet parent already wrote it
        with open(a.port_file + ".tmp", "w") as f:
            f.write(str(server.port))
        import os as _os

        _os.replace(a.port_file + ".tmp", a.port_file)  # atomic: no torn read
    if worker_ctx is not None:
        from psana_ray_tpu.transport.splice import probe_report

        logger.info(
            "worker %d/%d listening on %s:%d (splice: %s)",
            wid, worker_ctx.n_workers, a.host, server.port, probe_report(),
        )
    else:
        logger.info(
            "queue server listening on %s:%d (size=%d%s) — clients use "
            "--address tcp://<host>:%d, or start N of these and point "
            "clients at --cluster host:port,host:port (sharded queue "
            "service; the legacy thread-per-connection --server_mode was "
            "removed, the epoll event loop is THE server)",
            a.host, server.port, a.queue_size,
            f", max_conns={a.max_conns}" if a.max_conns else "",
            server.port,
        )

    # Observability: every queue (default + OPENed named ones) as a
    # registry source, the Prometheus endpoint over it, and the stall
    # detector watching the same dynamic population. All three are
    # zero-cost when their flags are off. The relay's recv-buffer pool
    # self-registers as the `bufpool` source (leases/hits/misses) with
    # payload-copy counters under `wire` — the zero-copy datapath's
    # steady state is visible on the same endpoint.
    MetricsRegistry.default().register("queue_server", server.stats_all)
    # a worker fleet staggers the scrape endpoints: worker i serves
    # --metrics_port + i (one process cannot answer for its siblings;
    # the federation collector aggregates per-worker series instead)
    metrics_port = a.metrics_port
    if metrics_port and wid is not None:
        metrics_port += wid
    metrics_server = start_metrics_server(metrics_port, host=a.metrics_host)
    # Time-series history (ISSUE 13): the bounded per-key snapshot ring
    # behind flight-dump tails and the federation collector's 'N'
    # metrics RPC (this server answers it regardless; the sampler adds
    # the local HISTORY dimension). One daemon thread, preallocated
    # rings, --history_interval 0 turns it off.
    from psana_ray_tpu.obs import configure_history_from_args, configure_profiling_from_args

    history = configure_history_from_args(a)
    # continuous profiler (ISSUE 16): bills the event loop's dispatch
    # pass to the "dispatch" stage; --profile_hz 0 = off. Workers spool
    # under distinct process names so prof_merge shows per-worker rows.
    profiler = configure_profiling_from_args(
        a, "queue_server" if wid is None else f"queue_server-w{wid}"
    )
    # Tracing (relay spans: queue_dwell/relay per sampled frame) and the
    # flight recorder (dump-on-stall/SIGUSR2/exception — the black box for
    # wedged runs) arm from the shared --trace_dir/--flight_dir flags.
    from psana_ray_tpu.obs import FLIGHT, configure_tracing_from_args

    configure_tracing_from_args(
        a, "queue_server" if wid is None else f"queue_server-w{wid}"
    )
    stall = None
    if a.stall_poll_s > 0:
        stall = StallDetector(
            poll_interval_s=a.stall_poll_s,
            full_threshold_s=a.stall_full_s,
            idle_threshold_s=a.stall_idle_s,
            # every stall event lands in the flight ring; when a dump dir
            # is armed the firing ALSO writes the postmortem black box
            # (events + metrics snapshot + all thread stacks)
            on_event=FLIGHT.on_stall,
        ).watch_provider(server.queues_by_name)
        MetricsRegistry.default().register("stalls", stall)
        stall.start()

    # autotune (ISSUE 15): server-side knobs — fsync batching and the
    # RAM spill threshold on the default durable queue (plus one dial
    # pair PER NAMED durable queue as they open), the relay recv-pool
    # retention floor, and the recommendation-only data-plane width —
    # judged by the measured relay rate (gets/s on the default queue).
    # Explicitly-set flags pin their knobs: the operator's value is a
    # decision, not a default (a flag passed AT its default value reads
    # as unset — documented).
    autotune = None
    if a.autotune != "off":
        from psana_ray_tpu.autotune import Objective, configure_autotune_from_args
        from psana_ray_tpu.autotune.knobs import (
            bufpool_retention_knob,
            fsync_batch_knob,
            ram_items_knob,
            workers_knob,
        )
        from psana_ray_tpu.utils.bufpool import BufferPool

        knobs = [
            bufpool_retention_knob(BufferPool.default()),
            # declines on a single-core box; recommendation-only
            workers_knob(current=a.workers),
        ]
        pinned = {}
        if a.workers > 1:
            pinned["workers"] = "--workers set explicitly"
        if a.durable_dir and getattr(backing, "log", None) is not None:
            knobs.append(fsync_batch_knob(backing.log))
            knobs.append(ram_items_knob(backing))
            if a.fsync_batch_n != dur_defaults.fsync_batch_n:
                pinned["fsync_batch_n"] = "--fsync_batch_n set explicitly"
            if a.ram_items != dur_defaults.ram_items:
                pinned["ram_items"] = "--ram_items set explicitly"
        autotune = configure_autotune_from_args(
            a, knobs, Objective("queue_server.default.gets"), pinned=pinned
        )
        tune_box["daemon"] = autotune

    done = threading.Event()
    force = threading.Event()

    def _stop(sig, frame):
        if done.is_set():
            # second signal: the operator wants OUT now (double-Ctrl-C
            # convention) — abort the drain window
            logger.info("second signal %s — forcing immediate shutdown", sig)
            force.set()
            return
        logger.info("signal %s — shutting down queue server", sig)
        done.set()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    done.wait()
    if a.drain_s > 0 and not force.is_set():
        # graceful drain: producers are refused (clean dead-queue exits),
        # consumers keep reading until the queues empty or the window ends
        server.begin_drain()
        start = time.monotonic()
        while time.monotonic() - start < a.drain_s and not force.is_set():
            if server.depth() == 0:
                logger.info("drained — all queues empty")
                break
            force.wait(0.2)
        else:
            logger.warning(
                "drain window ended with %d item(s) still queued", server.depth()
            )
    if autotune is not None:
        autotune.stop()
    if stall is not None:
        stall.stop()
    if history is not None:
        history.stop()
    if metrics_server is not None:
        metrics_server.close()
    server.close_all()  # unblock ALL clients with TransportClosed (dead-queue parity)
    server.shutdown()
    for q in server.all_queues():
        log = getattr(q, "log", None)
        if log is not None:  # durable backings: flush + unmap segments
            log.close()
    if worker_ctx is not None:
        worker_ctx.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
