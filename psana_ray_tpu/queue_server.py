"""Standalone queue server — the ``ray start --head`` of this framework.

The reference's runbook starts a Ray head node whose GCS hosts the detached
queue actor (``README.md:13-18``, ``shared_queue.py:35``); producers and
consumers on other nodes join it by address. Here the equivalent service is
one process serving a bounded queue over TCP (:mod:`transport.tcp`), which
remote producers/consumers reach with ``--address tcp://host:port``.

Optionally backed by a shared-memory ring (``--shm``) so local processes on
the serving host can bypass TCP entirely while remote ones fan in/out over
the network.

Teardown parity (``ray stop``, reference ``README.md:37-40``): SIGINT/SIGTERM
closes the queue, unblocking all clients with a dead-transport error.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading

logger = logging.getLogger(__name__)


def main(argv=None):
    p = argparse.ArgumentParser(prog="psana-ray-tpu-queue")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=6379, help="reference head-node port")
    p.add_argument("--queue_size", type=int, default=100)
    p.add_argument(
        "--shm",
        default=None,
        metavar="NAME",
        help="back the server with shm ring NAME (local procs attach via shm://NAME)",
    )
    p.add_argument("--log_level", default="INFO")
    a = p.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, a.log_level.upper(), logging.INFO),
        format="%(asctime)s - %(levelname)s - %(message)s",
    )

    from psana_ray_tpu.transport.ring import RingBuffer
    from psana_ray_tpu.transport.tcp import TcpQueueServer

    if a.shm:
        from psana_ray_tpu.transport.shm_ring import ShmRingBuffer

        try:
            backing = ShmRingBuffer.create(a.shm, maxsize=a.queue_size)
        except RuntimeError:
            backing = ShmRingBuffer.attach(a.shm, retries=1, interval_s=0.1)
        logger.info("backing queue: shm ring %r", a.shm)
    else:
        backing = RingBuffer(a.queue_size)

    server = TcpQueueServer(backing, host=a.host, port=a.port).serve_background()
    logger.info(
        "queue server listening on %s:%d (size=%d) — clients use --address tcp://<host>:%d",
        a.host, server.port, a.queue_size, server.port,
    )

    done = threading.Event()

    def _stop(sig, frame):
        logger.info("signal %s — shutting down queue server", sig)
        done.set()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    done.wait()
    try:
        backing.close()  # unblock clients with TransportClosed (dead-queue parity)
    except Exception:
        pass
    server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
