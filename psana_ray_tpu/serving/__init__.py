"""SLO-aware serving gateway (ISSUE 12): the layer between the queue
transport and the device consumers.

Three cooperating mechanisms, all driven by MEASUREMENT (the tf.data
"measure-then-control" philosophy, PAPERS.md):

- :class:`SloPolicy` — the measured latency/throughput frontier
  (bench's ``device_latency_operating_point``: B1 0.89 ms ... B8
  4.33 ms) as a control law: pick the batch size per dispatch from the
  current backlog so an idle system serves B1 latency and a loaded one
  serves B8 throughput, always keeping predicted queue-wait + device
  time inside the p99 SLO budget;
- :class:`ServingGateway` — admission control with deadline shedding
  (shed at the front door BEFORE spending batcher/device time, re-check
  at dequeue — an aged-out frame is dropped loudly, never processed
  late) plus weighted deficit round-robin dispatch across per-tenant
  queues;
- :class:`GatewayTelemetry` — the obs source (``gateway``): per-tenant
  admitted/shed/goodput/p99 and SLO attainment, the degraded gauge the
  StallDetector escalation flips.
"""

from psana_ray_tpu.serving.gateway import ServingGateway, make_batch_dispatch
from psana_ray_tpu.serving.policy import DEFAULT_OPERATING_POINTS, SloPolicy
from psana_ray_tpu.serving.telemetry import (
    GatewayTelemetry,
    PATH_ADMISSION,
    PATH_DEADLINE,
    PATH_STALL,
    SHED_PATHS,
)

__all__ = [
    "DEFAULT_OPERATING_POINTS",
    "GatewayTelemetry",
    "PATH_ADMISSION",
    "PATH_DEADLINE",
    "PATH_STALL",
    "SHED_PATHS",
    "ServingGateway",
    "SloPolicy",
    "make_batch_dispatch",
]
