"""Gateway accounting (obs source ``gateway``): per-tenant SLO attainment.

One counter FAMILY for every shed path — admission (predicted sojourn
over budget at the front door), ``deadline`` (aged out in the gateway
queue, caught at dequeue), ``stall`` (would have fit the normal budget
but the stall-detector escalation shrank it) — so "how much did we
shed, and why" is one query, and the conservation identity

    offered == completed + shed(admission) + shed(deadline)
             + shed(stall) + backlog

holds at every instant (pinned by tests/test_serving.py's sweep test:
after a drain, ``offered == completed + shed_total`` — shed is loud and
counted, admitted frames are never lost).

Per tenant: offered/admitted/shed/completed counts, goodput (completed
WITHIN the SLO), and a latency reservoir whose p99 is the number the
SLO is written against. ``slo_attainment`` is goodput/completed.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from psana_ray_tpu.utils.metrics import LatencyStats

PATH_ADMISSION = "admission"
PATH_DEADLINE = "deadline"
PATH_STALL = "stall"
SHED_PATHS = (PATH_ADMISSION, PATH_DEADLINE, PATH_STALL)


class _TenantStats:
    __slots__ = ("offered", "admitted", "shed", "completed", "goodput", "lat")

    def __init__(self):
        self.offered = 0
        self.admitted = 0
        self.shed = 0
        self.completed = 0
        self.goodput = 0
        self.lat = LatencyStats(reservoir_size=2048)


class GatewayTelemetry:
    """Counters + gauges for one :class:`~psana_ray_tpu.serving.gateway.
    ServingGateway`. Registered in the default MetricsRegistry on
    ``attach`` (last registration under a name wins, so a restarted
    gateway takes over its series)."""

    def __init__(self, name: str = "gateway", register: bool = True):
        self._name = name
        self._register = register
        self._lock = threading.Lock()
        self._registered = False  # guarded-by: _lock
        self.offered_total = 0  # guarded-by: _lock
        self.admitted_total = 0  # guarded-by: _lock
        self.shed_total = 0  # guarded-by: _lock
        self._shed_by_path: Dict[str, int] = {
            p: 0 for p in SHED_PATHS
        }  # guarded-by: _lock
        self.completed_total = 0  # guarded-by: _lock
        self.goodput_total = 0  # guarded-by: _lock
        self.dispatched_batches = 0  # guarded-by: _lock
        self.dispatched_frames = 0  # guarded-by: _lock
        self.batch_last = 0  # guarded-by: _lock
        self.escalations = 0  # guarded-by: _lock
        self.restores = 0  # guarded-by: _lock
        self._tenants: Dict[str, _TenantStats] = {}  # guarded-by: _lock
        # the gateway, for the degraded/backlog gauges
        self._gw = None  # guarded-by: _lock

    def attach(self, gateway) -> None:
        with self._lock:
            self._gw = gateway
        if not self._register:
            return
        with self._lock:
            if self._registered:
                return
            self._registered = True
        try:
            from psana_ray_tpu.obs import MetricsRegistry

            MetricsRegistry.default().register(self._name, self)
        except Exception:  # obs optional: serving must work without it
            pass

    def _tenant(self, tenant: str) -> _TenantStats:
        # guarded-by-caller: _lock
        ts = self._tenants.get(tenant)
        if ts is None:
            ts = self._tenants[tenant] = _TenantStats()
        return ts

    # -- the counter family ------------------------------------------------
    def admitted(self, tenant: str, n: int = 1) -> None:
        with self._lock:
            self.offered_total += n
            self.admitted_total += n
            ts = self._tenant(tenant)
            ts.offered += n
            ts.admitted += n

    def shed(self, path: str, tenant: str, n: int = 1,
             at_door: bool = False) -> None:
        """One shed event on ``path`` (admission/deadline/stall).
        ``at_door=True`` (admission-time paths) also counts the frames
        as offered — dequeue-path sheds were already offered+admitted
        when they came through the door."""
        if path not in SHED_PATHS:
            raise ValueError(f"unknown shed path {path!r} (want {SHED_PATHS})")
        with self._lock:
            self.shed_total += n
            self._shed_by_path[path] += n
            ts = self._tenant(tenant)
            ts.shed += n
            if at_door:
                self.offered_total += n
                ts.offered += n

    def completed(
        self,
        tenant: str,
        latency_s: float,
        in_slo: bool,
        exemplar: Optional[int] = None,
    ) -> None:
        """``exemplar`` (a sampled frame's trace id, ISSUE 13) is
        retained per latency bucket by the tenant's reservoir — the
        link ``trace_merge --exemplar`` resolves from a bad p99 bucket
        to that frame's cross-host timeline."""
        with self._lock:
            self.completed_total += 1
            ts = self._tenant(tenant)
            ts.completed += 1
            if in_slo:
                self.goodput_total += 1
                ts.goodput += 1
        ts.lat.observe(latency_s, exemplar=exemplar)  # internally locked

    def dispatched(self, batch: int, n_frames: int) -> None:
        with self._lock:
            self.dispatched_batches += 1
            self.dispatched_frames += n_frames
            self.batch_last = batch

    def escalated(self) -> None:
        with self._lock:
            self.escalations += 1

    def restored(self) -> None:
        with self._lock:
            self.restores += 1

    # -- reads -------------------------------------------------------------
    def shed_by_path(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._shed_by_path)

    def tenant_goodput(self) -> Dict[str, int]:
        with self._lock:
            return {t: ts.goodput for t, ts in self._tenants.items()}

    def stats(self) -> dict:
        with self._lock:
            gw = self._gw
            out = {
                "offered_total": self.offered_total,
                "admitted_total": self.admitted_total,
                "shed_total": self.shed_total,
                "completed_total": self.completed_total,
                "goodput_total": self.goodput_total,
                "dispatched_batches": self.dispatched_batches,
                "dispatched_frames": self.dispatched_frames,
                "batch_last": self.batch_last,
                "escalations": self.escalations,
                "restores": self.restores,
                "slo_attainment": round(
                    self.goodput_total / self.completed_total, 4
                ) if self.completed_total else 1.0,
            }
            for p, n in self._shed_by_path.items():
                out[f"shed_{p}_total"] = n
            tenants = list(self._tenants.items())
        rates: Dict[str, float] = {}
        if gw is not None:
            out["degraded"] = 1 if gw.degraded else 0
            out["backlog"] = gw.backlog()
            try:
                # the per-tenant offered-rate series (ISSUE 13): what
                # the admission predictor consumes, exported so the
                # history ring records demand next to goodput
                rates = gw.offered_fps_by_tenant()
            except Exception:  # noqa: BLE001 — a mid-teardown gateway
                rates = {}
        for t, ts in tenants:
            lat = ts.lat.snapshot()
            out[t] = {
                "offered": ts.offered,
                "offered_fps": rates.get(t, 0.0),
                "admitted": ts.admitted,
                "shed": ts.shed,
                "completed": ts.completed,
                "goodput": ts.goodput,
                "slo_attainment": round(
                    ts.goodput / ts.completed, 4
                ) if ts.completed else 1.0,
                "p99_ms": lat.get("p99_ms", 0.0),
            }
            ex = ts.lat.exemplars()
            if ex:
                out[t]["exemplars"] = ex
        return out

    # obs registry source protocol
    def snapshot(self) -> dict:
        return self.stats()
