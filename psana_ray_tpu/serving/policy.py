"""SLO policy: the measured latency/throughput frontier as a control law.

The bench measures the device's operating points — batch size vs device
latency (``device_latency_operating_point``: B1 0.89 ms ... B8 4.33 ms
on the fused calib path, BENCH_r05) — but until ISSUE 12 the consumer
drained fixed-size batches regardless of load. :class:`SloPolicy` turns
that table into the two decisions the gateway makes per dispatch:

- **which batch size**: the largest operating point the current backlog
  can fill (idle -> B1, no batching tax; loaded -> B8, max throughput),
  never one whose device time alone busts the SLO;
- **whether to admit**: predicted fair-share queue wait + device time
  against the SLO budget (shrunk while the stall detector says the
  system is degraded — graceful degradation instead of collapse).

The table is seeded from the bench numbers and REFINED online: every
dispatch's measured wall time feeds an EWMA per batch size, so the
policy tracks the machine it is actually running on (tf.data's
measure-then-control, PAPERS.md), not the one the bench ran on.

Threading: the EWMA table has a single writer (the gateway dispatch
loop); readers see whole float values (GIL-atomic dict reads), so the
policy carries no lock of its own — the gateway's lock orders the
decisions that matter.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

# (batch, device_ms) measured on the fused-calib device path (bench
# device-latency section, BENCH_r05). Intermediate points interpolated
# on the measured B1/B8 anchors; the online EWMA refines all of them.
DEFAULT_OPERATING_POINTS: Tuple[Tuple[int, float], ...] = (
    (1, 0.89),
    (2, 1.43),
    (4, 2.45),
    (8, 4.33),
)


class SloPolicy:
    """Batch-size choice + admission arithmetic under a p99 latency SLO.

    ``slo_ms`` is the end-to-end (admission -> dispatch-complete) p99
    target for ADMITTED work. ``shed_margin`` is the fraction of that
    budget admission may fill (headroom for prediction error);
    ``degraded_margin`` replaces it while the gateway is escalated by
    the stall detector — a smaller budget sheds more at the door, which
    is the point: shed loudly instead of serving everyone late.
    """

    def __init__(
        self,
        slo_ms: float = 25.0,
        operating_points: Optional[Sequence[Tuple[int, float]]] = None,
        shed_margin: float = 0.9,
        degraded_margin: float = 0.5,
        ewma: float = 0.2,
    ):
        pts = sorted(operating_points or DEFAULT_OPERATING_POINTS)
        if not pts:
            raise ValueError("need at least one (batch, device_ms) point")
        self._service_ms: Dict[int, float] = {}
        last_b = 0
        for b, ms in pts:
            b = int(b)
            if b <= last_b:
                raise ValueError(f"batch sizes must be ascending, got {pts}")
            if ms <= 0:
                raise ValueError(f"device_ms must be positive, got {ms}")
            self._service_ms[b] = float(ms)
            last_b = b
        self._batches = sorted(self._service_ms)
        if slo_ms <= 0:
            raise ValueError("slo_ms must be positive")
        if not 0 < degraded_margin <= shed_margin <= 1.0:
            raise ValueError(
                "want 0 < degraded_margin <= shed_margin <= 1.0, got "
                f"{degraded_margin}/{shed_margin}"
            )
        self.slo_ms = float(slo_ms)
        self.shed_margin = float(shed_margin)
        self.degraded_margin = float(degraded_margin)
        self._ewma = float(ewma)

    # -- the frontier ------------------------------------------------------
    @property
    def max_batch(self) -> int:
        return self._batches[-1]

    @property
    def min_batch(self) -> int:
        return self._batches[0]

    def batch_sizes(self) -> Tuple[int, ...]:
        return tuple(self._batches)

    def _fit(self, n: int) -> int:
        """Smallest operating point that can carry ``n`` frames (padded),
        the largest point when ``n`` exceeds them all."""
        for b in self._batches:
            if b >= n:
                return b
        return self._batches[-1]

    def service_ms(self, batch: int) -> float:
        """Device time for a dispatch carrying ``batch`` frames (the
        operating point it pads up to)."""
        return self._service_ms[self._fit(max(1, batch))]

    def per_frame_ms(self, batch: int) -> float:
        b = self._fit(max(1, batch))
        return self._service_ms[b] / b

    def capacity_fps(self) -> float:
        """Best sustained throughput on the frontier (the B8 point,
        unless the EWMA has learned otherwise)."""
        return max(b / ms * 1000.0 for b, ms in self._service_ms.items())

    # -- decisions ---------------------------------------------------------
    def choose_batch(self, backlog: int) -> int:
        """Largest operating point the backlog can fill — B1 when idle
        (latency), B8 under load (throughput) — stepping down if a
        point's device time ALONE exceeds the SLO (a misconfigured
        table must not admit work it can never serve in time)."""
        want = max(1, int(backlog))
        chosen = self._batches[0]
        for b in self._batches:
            if b <= want and self._service_ms[b] <= self.slo_ms:
                chosen = b
        return chosen

    def budget_ms(self, degraded: bool = False) -> float:
        """The admission budget: how much predicted sojourn a new frame
        may carry and still be admitted."""
        return self.slo_ms * (
            self.degraded_margin if degraded else self.shed_margin
        )

    def predict_sojourn_ms(
        self, queue_len: int, weight: int, active_weight_total: int
    ) -> float:
        """Queue wait + device time a frame admitted NOW would see — the
        admission estimate (ISSUE 12, refined by ISSUE 13).

        Batch-quantized: the frame completes when its BATCH completes,
        so it waits ``ceil(position / B)`` dispatches of its own tenant,
        each costing the max operating point, interleaved per the WDRR
        share ``weight / active_weight_total``.

        ``active_weight_total`` is where the MEASURED per-tenant arrival
        rates enter (ISSUE 13): the gateway sums the weights of every
        tenant that is backlogged OR offering at a live rate — a tenant
        whose queue happens to be momentarily empty but whose offered-
        rate series is hot WILL take its WDRR turns during this frame's
        wait, and the backlog-only estimate (the PR 12 behavior, which
        counted only currently-backlogged tenants) under-predicted by
        exactly that tenant's share."""
        b = self.max_batch
        svc = self.service_ms(b)
        share = weight / max(weight, active_weight_total)
        batches_ahead = (queue_len + 1 + b - 1) // b
        return batches_ahead * svc / share

    def observe_service(self, batch: int, measured_ms: float) -> None:
        """Feed one dispatch's measured wall time back into the table
        (single writer: the gateway dispatch loop)."""
        if measured_ms <= 0:
            return
        b = self._fit(max(1, batch))
        cur = self._service_ms[b]
        self._service_ms[b] = cur + self._ewma * (measured_ms - cur)

    def snapshot(self) -> dict:
        return {
            "slo_ms": self.slo_ms,
            "service_ms": {
                str(b): round(ms, 4) for b, ms in self._service_ms.items()
            },
            "capacity_fps": round(self.capacity_fps(), 1),
        }
