"""The serving gateway: admission, deadline shedding, weighted fair-share.

Sits between the queue transport and the device consumers (ISSUE 12).
Frames enter through :meth:`ServingGateway.offer` — the FRONT DOOR —
where a frame that cannot meet its deadline is shed immediately, before
any batcher or device time is spent on it. Admitted frames queue per
tenant; the dispatch loop (:meth:`run` / :meth:`serve_queue`) serves
tenants by weighted deficit round-robin, re-checks every frame's
deadline AT DEQUEUE (a frame that aged out in the queue is dropped
loudly — breadcrumb + counter — never processed late), picks the batch
size adaptively from the :class:`~psana_ray_tpu.serving.policy.
SloPolicy` frontier, and feeds each dispatch's measured wall time back
into the policy.

Shedding is NEVER silent: every shed path (admission, dequeue age-out,
stall escalation) increments the same counter family in
:class:`~psana_ray_tpu.serving.telemetry.GatewayTelemetry` and leaves a
flight breadcrumb (rate-limited per path so an overload cannot flood
the bounded flight ring). The conservation identity — offered ==
completed + shed + backlog — is pinned by tests/test_serving.py.

The stall detector escalates the gateway (``escalate``/``restore``,
wired by :meth:`psana_ray_tpu.obs.stall.StallDetector.bind_gateway`):
while degraded, admission runs against the shrunken
``degraded_margin`` budget, so the system sheds MORE at the door
instead of letting every queue keep growing — graceful degradation
instead of collapse.

Zero-copy contract: a shed frame's transport lease is released here
(the only owner left); admitted frames keep their leases until the
dispatch callable consumes them (``make_batch_dispatch`` copies into a
batch arena via ``FrameBatcher.push_view``, exactly one memcpy — the
copies/frame 1.00 / allocs 0 pins hold through the gateway path, see
tests/test_serving.py).

The dispatch loop is part of the blocking-hot-path audited graph
(lint): no sleeps, no unbounded waits — idle pauses ride a bounded,
offer()-woken Event wait.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.records import EndOfStream, EosTally
from psana_ray_tpu.serving.policy import SloPolicy
from psana_ray_tpu.serving.telemetry import (
    GatewayTelemetry,
    PATH_ADMISSION,
    PATH_DEADLINE,
    PATH_STALL,
)
from psana_ray_tpu.transport.registry import TransportClosed

# breadcrumb rate limit: first shed on a path always leaves one, then
# one per this many sheds (cumulative count rides the breadcrumb) — the
# flight ring is bounded, an overload must not evict the rare events
# the ring exists for
_BREADCRUMB_EVERY = 256


def _release(rec) -> None:
    """Return a shed frame's transport lease (pooled TCP recv buffer /
    shm slot) — no-op for records that own their memory."""
    release = getattr(rec, "release", None)
    if release is not None:
        release()


def _trace_id(rec) -> Optional[int]:
    """The sampled trace id riding a record's envelope, or None — the
    exemplar the latency histograms retain per bucket (ISSUE 13)."""
    ctx = getattr(rec, "trace", None)
    if ctx is not None and getattr(ctx, "sampled", False):
        return ctx.trace_id
    return None


class _TenantQ:
    """One tenant's admitted-frame queue + its WDRR deficit + the
    measured arrival-rate window (ISSUE 13: admission predicts from
    rate + backlog, not backlog alone)."""

    __slots__ = ("name", "weight", "q", "deficit", "arrivals")

    # arrival timestamps kept at most this many (bounds memory under a
    # flood; the rate window trims by TIME, this trims by count)
    ARRIVALS_CAP = 4096

    def __init__(self, name: str, weight: int):
        self.name = name
        self.weight = max(1, int(weight))
        self.q: deque = deque()  # (deadline, admit_t, rec) in admit order
        self.deficit = 0.0
        self.arrivals: deque = deque(maxlen=self.ARRIVALS_CAP)  # offer() times

    def note_arrival(self, now: float, window_s: float) -> None:
        """Record one offer() (admitted OR shed — offered rate is the
        demand signal) and trim the window."""
        self.arrivals.append(now)
        cutoff = now - window_s
        while self.arrivals and self.arrivals[0] < cutoff:
            self.arrivals.popleft()

    def rate_active(self, now: float, window_s: float) -> bool:
        """Did this tenant offer anything within the rate window?"""
        cutoff = now - window_s
        while self.arrivals and self.arrivals[0] < cutoff:
            self.arrivals.popleft()
        return bool(self.arrivals)

    def offered_fps(self, now: float, window_s: float) -> float:
        cutoff = now - window_s
        while self.arrivals and self.arrivals[0] < cutoff:
            self.arrivals.popleft()
        if not self.arrivals:
            return 0.0
        return len(self.arrivals) / window_s


class ServingGateway:
    """Admission + shedding + WDRR dispatch over per-tenant queues.

    ``dispatch(records, batch_size)`` drives the device: ``records`` is
    the admitted, deadline-checked frame list (``len(records) <=
    batch_size``; the operating point pads the remainder) and MUST
    consume the records' transport leases (``make_batch_dispatch`` does).
    ``weights`` maps tenant name -> integer weight (unlisted tenants get
    ``default_weight``); goodput under overload converges to the weight
    shares. ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        dispatch: Callable[[List[Any], int], None],
        policy: Optional[SloPolicy] = None,
        weights: Optional[Dict[str, int]] = None,
        default_weight: int = 1,
        telemetry: Optional[GatewayTelemetry] = None,
        clock: Callable[[], float] = time.monotonic,
        rate_window_s: float = 2.0,
    ):
        self._dispatch = dispatch
        self.policy = policy or SloPolicy()
        self._weights = dict(weights or {})
        self._default_weight = max(1, int(default_weight))
        self._clock = clock
        # admission rate window (ISSUE 13): a tenant that offered within
        # this window counts toward the predicted WDRR interleave even
        # while its queue is momentarily empty; 0 restores the PR 12
        # backlog-only prediction
        self._rate_window_s = max(0.0, float(rate_window_s))
        self._lock = threading.Lock()
        # serializes dispatch_once end to end: the dispatch callable is
        # NOT required to be thread-safe (make_batch_dispatch's
        # FrameBatcher arenas are not), and the documented run()-thread
        # + drain()-caller pattern would otherwise drive it from two
        # threads at once. offer() never takes this lock, so admission
        # stays concurrent with a dispatch in flight.
        self._dispatch_serial = threading.Lock()
        self._tenants: Dict[str, _TenantQ] = {}  # guarded-by: _lock
        self._order: deque = deque()  # WDRR tenant rotation  # guarded-by: _lock
        self._degraded = False  # guarded-by: _lock
        self._backlog = 0  # frames admitted, not yet dispatched  # guarded-by: _lock
        self._shed_since_crumb: Dict[str, int] = {}  # guarded-by: _lock
        self._work = threading.Event()  # offer() -> wake an idle dispatch loop
        self.telemetry = telemetry or GatewayTelemetry()
        self.telemetry.attach(self)

    # -- tenants -----------------------------------------------------------
    def _tenant(self, name: str, weight: Optional[int]) -> _TenantQ:
        # guarded-by-caller: _lock
        tq = self._tenants.get(name)
        if tq is None:
            if weight is None:
                weight = self._weights.get(name, self._default_weight)
            tq = self._tenants[name] = _TenantQ(name, weight)
            self._order.append(name)
        elif weight is not None:
            tq.weight = max(1, int(weight))
        return tq

    def backlog(self) -> int:
        with self._lock:
            return self._backlog

    def offered_fps_by_tenant(self, now: Optional[float] = None) -> Dict[str, float]:
        """Measured per-tenant offered rate over the admission rate
        window — the series ISSUE 13's history sampler records (and the
        admission predictor consumes); empty when rate tracking is off."""
        if self._rate_window_s <= 0.0:
            return {}
        now = self._clock() if now is None else now
        with self._lock:
            return {
                name: round(tq.offered_fps(now, self._rate_window_s), 3)
                for name, tq in self._tenants.items()
            }

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    # -- stall-detector escalation ----------------------------------------
    def escalate(self, reason: Any = None) -> None:
        """Raise the shed threshold (admission budget shrinks to the
        policy's ``degraded_margin``). Idempotent; restored by
        :meth:`restore`."""
        with self._lock:
            was = self._degraded
            self._degraded = True
        if not was:
            self.telemetry.escalated()
            FLIGHT.record("gateway_degraded", reason=str(reason or ""))

    def restore(self) -> None:
        with self._lock:
            was = self._degraded
            self._degraded = False
        if was:
            self.telemetry.restored()
            FLIGHT.record("gateway_restored")

    # -- admission (the front door) ---------------------------------------
    def _predicted_sojourn_ms(self, tq: _TenantQ, now: float) -> float:
        """Queue wait + device time a frame admitted NOW would see —
        :meth:`SloPolicy.predict_sojourn_ms` over the ACTIVE weight
        total: a tenant counts toward the predicted WDRR interleave
        when it is backlogged OR its measured offered-rate window is
        hot (ISSUE 13 — a burster whose queue just drained still takes
        its turns during this frame's wait; the PR 12 backlog-only
        share under-predicted by exactly that tenant's slice, and the
        tail admissions landed late)."""
        # guarded-by-caller: _lock
        total_w = tq.weight
        for other in self._tenants.values():
            if other is tq:
                continue
            if other.q or (
                self._rate_window_s > 0.0
                and other.rate_active(now, self._rate_window_s)
            ):
                total_w += other.weight
        return self.policy.predict_sojourn_ms(len(tq.q), tq.weight, total_w)

    def offer(
        self,
        rec: Any,
        tenant: str = "default",
        deadline: Optional[float] = None,
        weight: Optional[int] = None,
        now: Optional[float] = None,
    ) -> bool:
        """Admit-or-shed one frame. ``deadline`` (clock units) defaults
        to now + SLO. Returns True when admitted; a shed frame's lease
        is released and the shed is counted + breadcrumbed (path
        ``admission``, or ``stall`` when only the escalated threshold
        rejected it)."""
        now = self._clock() if now is None else now
        with self._lock:
            tq = self._tenant(tenant, weight)
            if self._rate_window_s > 0.0:
                # the offer itself is the arrival signal (admitted or
                # shed — offered rate measures DEMAND), recorded before
                # the prediction so a tenant's own burst is visible to
                # every same-instant competitor
                tq.note_arrival(now, self._rate_window_s)
            if deadline is None:
                deadline = now + self.policy.slo_ms / 1000.0
            remain_ms = (deadline - now) * 1000.0
            predicted = self._predicted_sojourn_ms(tq, now)
            path = None
            if predicted > min(self.policy.budget_ms(self._degraded), remain_ms):
                # the stall path: this frame would have been admitted at
                # the NORMAL threshold — the escalation is what shed it
                if self._degraded and predicted <= min(
                    self.policy.budget_ms(False), remain_ms
                ):
                    path = PATH_STALL
                else:
                    path = PATH_ADMISSION
            if path is None:
                tq.q.append((deadline, now, rec))
                self._backlog += 1
            else:
                crumb = self._note_shed(path)
        if path is None:
            self.telemetry.admitted(tenant)
            self._work.set()
            return True
        self.telemetry.shed(path, tenant, 1, at_door=True)
        if crumb:
            FLIGHT.record(
                "gateway_shed", path=path, tenant=tenant,
                predicted_ms=round(predicted, 2), shed_so_far=crumb,
            )
        _release(rec)
        return False

    def _note_shed(self, path: str) -> int:
        """Rate-limit breadcrumbs per path; returns the cumulative count
        to stamp on the breadcrumb, or 0 to stay quiet this time."""
        # guarded-by-caller: _lock
        n = self._shed_since_crumb.get(path, 0) + 1
        if n == 1 or n % _BREADCRUMB_EVERY == 0:
            self._shed_since_crumb[path] = n
            return n
        self._shed_since_crumb[path] = n
        return 0

    # -- dispatch (WDRR + dequeue deadline re-check) ----------------------
    def _pick_tenant(self) -> Optional[_TenantQ]:
        # guarded-by-caller: _lock
        backlogged = [t for t in self._tenants.values() if t.q]
        if not backlogged:
            return None
        for _replenished in (False, True):
            for _ in range(len(self._order)):
                name = self._order[0]
                self._order.rotate(-1)
                tq = self._tenants[name]
                if tq.q and tq.deficit >= 1.0:
                    return tq
            # nobody eligible: a new WDRR round — each backlogged tenant
            # earns quantum * weight frames of deficit (quantum = the
            # max operating point, so one round is a handful of batches)
            q = self.policy.max_batch
            for tq in backlogged:
                tq.deficit = min(
                    2.0 * q * tq.weight, max(0.0, tq.deficit) + q * tq.weight
                )
        return backlogged[0]  # unreachable: replenish made one eligible

    def dispatch_once(self, now: Optional[float] = None) -> int:
        """One WDRR dispatch: pick a tenant, re-check deadlines at
        dequeue (aged-out frames shed loudly), batch adaptively, drive
        the device, feed the measured service time back. Returns the
        number of frames HANDLED (dispatched + shed) — 0 means idle.
        Serialized: concurrent callers (a run() thread racing a
        drain()) queue behind ``_dispatch_serial``, so the dispatch
        callable is never re-entered."""
        # lock-order: ServingGateway._dispatch_serial -> ServingGateway._lock
        # (the serial gate is always the outer lock; _lock-holding paths
        # never wait on the gate)
        with self._dispatch_serial:
            return self._dispatch_once_locked(now)

    def _dispatch_once_locked(self, now: Optional[float]) -> int:
        # guarded-by-caller: _dispatch_serial
        now = self._clock() if now is None else now
        shed_recs: List[Any] = []
        with self._lock:
            tq = self._pick_tenant()
            if tq is None:
                return 0
            batch_size = self.policy.choose_batch(len(tq.q))
            svc_s = self.policy.service_ms(batch_size) / 1000.0
            batch: List[tuple] = []
            while tq.q and len(batch) < batch_size:
                deadline, admit_t, rec = tq.q.popleft()
                self._backlog -= 1
                if now + svc_s > deadline:
                    # aged out in the queue: it cannot complete in time —
                    # drop loudly, never process late
                    shed_recs.append(rec)
                    continue
                batch.append((deadline, admit_t, rec))
            tq.deficit -= len(batch)
            tenant = tq.name
            crumb = self._note_shed(PATH_DEADLINE) if shed_recs else 0
        if shed_recs:
            self.telemetry.shed(PATH_DEADLINE, tenant, len(shed_recs))
            if crumb:
                FLIGHT.record(
                    "gateway_shed", path=PATH_DEADLINE, tenant=tenant,
                    count=len(shed_recs), shed_so_far=crumb,
                )
            for rec in shed_recs:
                _release(rec)
        if not batch:
            return len(shed_recs)
        recs = [rec for (_d, _t, rec) in batch]
        # exemplar capture BEFORE dispatch consumes the leases: a
        # sampled record's trace id tags the latency observation so a
        # bad bucket resolves to that frame's cross-host timeline
        exemplars = [_trace_id(rec) for (_d, _t, rec) in batch]
        t0 = self._clock()
        self._dispatch(recs, batch_size)
        t1 = self._clock()
        self.policy.observe_service(batch_size, (t1 - t0) * 1000.0)
        self.telemetry.dispatched(batch_size, len(recs))
        for (deadline, admit_t, _rec), tid in zip(batch, exemplars):
            self.telemetry.completed(
                tenant, t1 - admit_t, in_slo=(t1 <= deadline), exemplar=tid
            )
        return len(recs) + len(shed_recs)

    def run(self, stop: Optional[threading.Event] = None,
            idle_wait_s: float = 0.02) -> None:
        """The standalone dispatch loop: serve until ``stop`` is set.
        Idle pauses are bounded Event waits woken by :meth:`offer` —
        no sleeps (blocking-hot-path audited)."""
        while not (stop is not None and stop.is_set()):
            if self.dispatch_once() == 0:
                self._work.wait(timeout=idle_wait_s)
                self._work.clear()

    def drain(self, deadline_s: float = 30.0) -> None:
        """Dispatch until the backlog empties (EOS / end-of-run tail)."""
        deadline = self._clock() + deadline_s
        while self.backlog() and self._clock() < deadline:
            self.dispatch_once()

    # -- transport pump ----------------------------------------------------
    def serve_queue(
        self,
        queue,
        tenant_of: Optional[Callable[[Any], str]] = None,
        stop: Optional[threading.Event] = None,
        poll_interval_s: float = 0.01,
        max_wait_s: Optional[float] = None,
        prefer_stream: bool = True,
    ) -> None:
        """Pump a transport queue through admission into the dispatch
        loop until EOS (the consumer drive path behind a gateway).

        Same drain preference and EOS-tally semantics as
        :func:`~psana_ray_tpu.infeed.batcher.batches_from_queue`:
        server-push stream > zero-copy view drain > plain ``get_batch``,
        multiple producer shards covered by :class:`EosTally`, duplicate
        sibling markers returned to the queue. ``tenant_of(rec)`` names
        the tenant per frame (default: one shared tenant). At EOS the
        remaining admitted backlog is drained through the device, then
        this returns. ``max_wait_s`` bounds total starvation."""
        tally = EosTally()
        pop = (
            getattr(queue, "get_batch_stream", None) if prefer_stream else None
        ) or (getattr(queue, "get_batch_view", None) or queue.get_batch)
        starved_since: Optional[float] = None
        try:
            while True:
                if stop is not None and stop.is_set():
                    return
                timeout = 0.0 if self.backlog() else poll_interval_s
                try:
                    items = pop(self.policy.max_batch * 2, timeout=timeout)
                except TransportClosed:
                    break  # transport died: drain what we admitted
                if not items:
                    if tally.flush_duplicates(queue):
                        # yield before re-reading a returned sibling
                        # marker (the competing-consumer livelock,
                        # batches_from_queue) — bounded, offer()-woken
                        self._work.wait(timeout=max(poll_interval_s, 0.02))
                        self._work.clear()
                    now = self._clock()
                    starved_since = starved_since if starved_since is not None else now
                    if max_wait_s is not None and now - starved_since >= max_wait_s:
                        break
                    self.dispatch_once()
                    continue
                starved_since = None
                tally.flush_duplicates(queue)
                now = self._clock()
                stream_done = False
                for pos, item in enumerate(items):
                    if isinstance(item, EndOfStream):
                        if tally.process(item):
                            for rest in items[pos + 1:]:
                                if isinstance(rest, EndOfStream):
                                    tally.process(rest)
                                else:  # popped past the marker: still ours
                                    self.offer(
                                        rest,
                                        tenant=tenant_of(rest)
                                        if tenant_of is not None else "default",
                                        now=now,
                                    )
                            stream_done = True
                            break
                        continue
                    self.offer(
                        item,
                        tenant=tenant_of(item) if tenant_of is not None else "default",
                        now=now,
                    )
                # serve what admission let through before the next pop —
                # admission bounds the backlog to ~an SLO budget of work,
                # so this inner drain is bounded too
                while self.dispatch_once():
                    pass
                if stream_done:
                    FLIGHT.record("eos_complete", source="serving_gateway")
                    break
        finally:
            tally.flush_duplicates(queue, final=True)
        self.drain()


def make_batch_dispatch(
    consume: Callable[..., None],
    n_buffers: int = 0,
    dtype=None,
):
    """Adapt a ``consume(batch)`` consumer (fixed-shape
    :class:`~psana_ray_tpu.infeed.batcher.Batch` eater — a pjit'd step,
    a device_put pipeline) into a gateway ``dispatch`` callable.

    Keeps one :class:`FrameBatcher` PER operating-point batch size (pjit
    compiles one program per shape, so the adaptive sizes are a fixed
    menu, not a continuum) and copies each record into the batch arena
    via ``push_view`` — the record's transport lease is released right
    after the single memcpy, so the zero-copy pins (copies/frame 1.00,
    allocs 0 steady-state with ``n_buffers``) hold through the gateway
    path. The tail is padded to the operating point with the usual
    validity mask."""
    from psana_ray_tpu.infeed.batcher import FrameBatcher

    batchers: Dict[int, Any] = {}

    def dispatch(records: List[Any], batch_size: int) -> None:
        b = batchers.get(batch_size)
        if b is None:
            b = batchers[batch_size] = FrameBatcher(
                batch_size, dtype=dtype, n_buffers=n_buffers
            )
        out = None
        for rec in records:
            out = b.push_view(rec)
            if out is not None:
                consume(out)
        if out is None:  # partial dispatch: pad + emit now (never hold
            # admitted frames hostage to a future dispatch's fill)
            tail = b.flush()
            if tail is not None:
                consume(tail)

    return dispatch
