"""CXI (HDF5) peak-list output: writer, readers, merge/dedupe tool.

Host-only — imports nothing beyond numpy/h5py, so the
``psana-ray-tpu-cxi-merge`` CLI and any analysis-host reader load in
milliseconds with no jax/flax requirement (the device-side peak
EXTRACTION lives in :mod:`psana_ray_tpu.models.peaks`, which re-exports
everything here for compatibility).

The file layout (under ``/entry_1/result_1``: ``nPeaks``,
``peakXPosRaw`` / ``peakYPosRaw`` / ``peakTotalIntensity``) is the one
CrystFEL's CXI interface and psocake consume; it closes the loop the
reference's own packaging names as its mission — "Save PeakNet inference
results to CXI" (reference ``setup.py:11``; SFX keyword at
``setup.py:15``) — but which exists nowhere in its code.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass
class PeakSet:
    """Host-side peak list for one event (unpadded)."""

    event_idx: int
    shard_rank: int
    y: np.ndarray  # [n] float32 row position
    x: np.ndarray  # [n] float32 col position
    intensity: np.ndarray  # [n] float32
    photon_energy: float = 0.0

    @property
    def n(self) -> int:
        return len(self.y)


def unpad_peaks(yx, score, n, event_idx=None, shard_rank=None, photon_energy=None):
    """Device outputs of ``find_peaks`` -> list of host PeakSets."""
    yx = np.asarray(yx)
    score = np.asarray(score)
    n = np.asarray(n)
    out = []
    for i in range(len(n)):
        k = int(n[i])
        out.append(
            PeakSet(
                event_idx=int(event_idx[i]) if event_idx is not None else i,
                shard_rank=int(shard_rank[i]) if shard_rank is not None else 0,
                y=yx[i, :k, 0].astype(np.float32),
                x=yx[i, :k, 1].astype(np.float32),
                intensity=score[i, :k].astype(np.float32),
                photon_energy=float(photon_energy[i]) if photon_energy is not None else 0.0,
            )
        )
    return out


class CxiWriter:
    """Append peak lists to a CXI (HDF5) file in the peakfinder layout.

    Datasets (under ``/entry_1/result_1``): ``nPeaks [N]``,
    ``peakXPosRaw / peakYPosRaw / peakTotalIntensity [N, max_peaks]`` —
    the layout CrystFEL's CXI interface and psocake write/read. Event
    provenance (``shard_rank``/``event_idx``) and photon energy
    (``/LCLS/photon_energy_eV``) ride along. Resizable, chunked, flushed
    per batch: a crash loses at most the unflushed tail.

    ``mode='w'`` (default) creates/truncates; ``mode='a'`` re-opens an
    existing file and APPENDS after its last event — the crash-resume
    path (``psana-ray-tpu-sfx --cursor_path``), where truncating would
    permanently lose every durably-written event the cursor has already
    marked done. Appending requires the same ``max_peaks`` the file was
    created with (the row width is baked into the datasets).
    """

    def __init__(self, path: str, max_peaks: int = 128, mode: str = "w"):
        import os

        import h5py

        self.path = path
        self.max_peaks = max_peaks
        if mode not in ("w", "a"):
            raise ValueError(f"mode must be 'w' or 'a', got {mode!r}")
        if mode == "a" and os.path.exists(path):
            self._f = h5py.File(path, "r+")
            try:
                g = self._f["entry_1/result_1"]
                lcls = self._f["LCLS"]
                self._n = g["nPeaks"]
                self._x = g["peakXPosRaw"]
                self._y = g["peakYPosRaw"]
                self._i = g["peakTotalIntensity"]
                self._energy = lcls["photon_energy_eV"]
                self._rank = lcls["shard_rank"]
                self._event = lcls["event_idx"]
                existing = int(self._x.shape[1])
                if existing != max_peaks:
                    raise ValueError(
                        f"cannot append with max_peaks={max_peaks}: {path} "
                        f"was created with max_peaks={existing}"
                    )
            except BaseException as e:
                # close the r+ handle on ANY failure (it holds the HDF5
                # lock); a missing dataset means a foreign HDF5 layout
                self._f.close()
                if isinstance(e, KeyError):
                    raise ValueError(
                        f"{path} exists but is not a CxiWriter file "
                        f"(missing {e}); refusing to append to a foreign "
                        f"HDF5 layout"
                    ) from e
                raise
            self._count = int(self._n.shape[0])
            return
        self._f = h5py.File(path, "w")
        g = self._f.create_group("entry_1").create_group("result_1")
        mk = lambda name, shape, dtype: g.create_dataset(  # noqa: E731
            name, shape=(0, *shape), maxshape=(None, *shape), dtype=dtype,
            chunks=(256, *shape),
        )
        self._n = mk("nPeaks", (), np.int32)
        self._x = mk("peakXPosRaw", (max_peaks,), np.float32)
        self._y = mk("peakYPosRaw", (max_peaks,), np.float32)
        self._i = mk("peakTotalIntensity", (max_peaks,), np.float32)
        lcls = self._f.create_group("LCLS")
        self._energy = lcls.create_dataset(
            "photon_energy_eV", shape=(0,), maxshape=(None,), dtype=np.float64,
            chunks=(256,),
        )
        self._rank = lcls.create_dataset(
            "shard_rank", shape=(0,), maxshape=(None,), dtype=np.int32, chunks=(256,)
        )
        self._event = lcls.create_dataset(
            "event_idx", shape=(0,), maxshape=(None,), dtype=np.int64, chunks=(256,)
        )
        self._count = 0

    def append(self, peaks: Sequence[PeakSet]):
        """Append a batch of events. The padded rows are assembled in
        numpy first and written as ONE slice per dataset (7 h5py calls
        per batch, not per event) — at merge/serving batch sizes the
        per-call h5py overhead would otherwise dominate the write side."""
        if not peaks:
            return
        m = self.max_peaks
        b = len(peaks)
        start, end = self._count, self._count + b
        n_a = np.zeros(b, np.int32)
        x_a = np.zeros((b, m), np.float32)
        y_a = np.zeros((b, m), np.float32)
        i_a = np.zeros((b, m), np.float32)
        e_a = np.zeros(b, np.float64)
        r_a = np.zeros(b, np.int32)
        ev_a = np.zeros(b, np.int64)
        for j, p in enumerate(peaks):
            k = min(p.n, m)
            n_a[j] = k
            x_a[j, :k] = p.x[:k]
            y_a[j, :k] = p.y[:k]
            i_a[j, :k] = p.intensity[:k]
            e_a[j] = p.photon_energy * 1000.0  # keV -> eV
            r_a[j] = p.shard_rank
            ev_a[j] = p.event_idx
        for d in (self._n, self._x, self._y, self._i, self._energy, self._rank, self._event):
            d.resize(end, axis=0)
        self._n[start:end] = n_a
        self._x[start:end] = x_a
        self._y[start:end] = y_a
        self._i[start:end] = i_a
        self._energy[start:end] = e_a
        self._rank[start:end] = r_a
        self._event[start:end] = ev_a
        self._count = end
        self._f.flush()

    @property
    def n_events(self) -> int:
        return self._count

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_cxi_peaks(path: str):
    """Read back (nPeaks, x, y, intensity, event_idx) from a CXI file."""
    f, refs = _open_cxi_readonly(path)
    with f:
        return (
            refs["n"][:], refs["x"][:], refs["y"][:], refs["i"][:],
            refs["event"][:],
        )


def read_cxi_peaksets(path: str) -> list:
    """Full round trip: every event of a CxiWriter file as an unpadded
    :class:`PeakSet` list (provenance + photon energy included)."""
    f, refs = _open_cxi_readonly(path)
    with f:
        n = refs["n"][:]
        x, y, inten = refs["x"][:], refs["y"][:], refs["i"][:]
        energy = refs["energy"][:]
        rank = refs["rank"][:]
        event = refs["event"][:]
    out = []
    for i in range(len(n)):
        k = int(n[i])
        out.append(
            PeakSet(
                event_idx=int(event[i]), shard_rank=int(rank[i]),
                y=y[i, :k].astype(np.float32), x=x[i, :k].astype(np.float32),
                intensity=inten[i, :k].astype(np.float32),
                photon_energy=float(energy[i]) / 1000.0,  # eV -> keV
            )
        )
    return out


def _open_cxi_readonly(path: str):
    """Open a CxiWriter-layout file for reading; a foreign HDF5 layout
    raises a clear ValueError (mirrors CxiWriter's append-mode check)."""
    import h5py

    f = h5py.File(path, "r")
    try:
        g = f["entry_1/result_1"]
        refs = {
            "n": g["nPeaks"], "x": g["peakXPosRaw"], "y": g["peakYPosRaw"],
            "i": g["peakTotalIntensity"],
            "energy": f["LCLS/photon_energy_eV"],
            "rank": f["LCLS/shard_rank"], "event": f["LCLS/event_idx"],
        }
    except KeyError as e:
        f.close()
        raise ValueError(
            f"{path} is not a CxiWriter file (missing {e}); refusing to "
            f"read a foreign HDF5 layout"
        ) from e
    return f, refs


def merge_cxi(inputs: Sequence[str], output: str,
              max_peaks: Optional[int] = None, keep: str = "last",
              chunk_events: int = 1024) -> int:
    """Merge per-run CXI files into one, deduplicating at-least-once
    replays on the ``(shard_rank, event_idx)`` provenance stamp.

    This is the other half of the resume story: a crash-resume may
    re-append events the previous run already wrote (documented in
    :mod:`psana_ray_tpu.sfx`), and separate runs may write separate
    files. ``keep='last'`` (default) keeps the LATEST occurrence in
    input-then-row order — a resumed run's re-processed event supersedes
    the crashed run's; ``'first'`` keeps the earliest. Output events are
    sorted by ``(shard_rank, event_idx)`` so the merged file is
    deterministic regardless of arrival order. Returns the event count.

    Two-pass streaming merge, sized for real runs (a 120 Hz shift is
    millions of events): pass 1 reads only the provenance key columns to
    resolve winners (O(events) small tuples resident); pass 2 copies the
    winning rows in ``chunk_events``-sized slabs, grouping each slab's
    rows BY INPUT FILE so every dataset is read once per (file, slab)
    with one sorted fancy-index selection — not 5 h5py calls per event —
    while full padded peak rows never exceed one slab in memory.

    ``max_peaks`` defaults to the WIDEST input's row width (a merge must
    be lossless); an explicit value narrower than some input is refused
    rather than silently truncating peak lists. ``output`` must not
    already exist — the merge tool follows the same no-clobber
    convention as the sfx CLI (which also rules out output==input)."""
    import contextlib
    import os

    if keep not in ("last", "first"):
        raise ValueError(f"keep must be 'last' or 'first', got {keep!r}")
    if chunk_events < 1:
        raise ValueError(f"chunk_events must be >= 1, got {chunk_events}")
    if os.path.exists(output):
        raise ValueError(
            f"refusing to overwrite existing {output}; point --output at "
            f"a new file"
        )

    with contextlib.ExitStack() as stack:
        handles = []
        for path in inputs:
            f, refs = _open_cxi_readonly(path)
            stack.callback(f.close)
            handles.append(refs)

        widths = {p: int(h["x"].shape[1]) for p, h in zip(inputs, handles)}
        if max_peaks is None:
            max_peaks = max(widths.values())
        else:
            too_wide = {p: w for p, w in widths.items() if w > max_peaks}
            if too_wide:
                raise ValueError(
                    f"max_peaks={max_peaks} would truncate peak lists from "
                    f"{sorted(too_wide)} (row width {max(too_wide.values())}); "
                    f"a merge must be lossless — raise max_peaks or omit it"
                )

        # pass 1: provenance keys only -> winner (input_idx, row_idx)
        winners: dict = {}
        for fi, refs in enumerate(handles):
            rank = refs["rank"][:]
            event = refs["event"][:]
            for ri in range(len(rank)):
                key = (int(rank[ri]), int(event[ri]))
                if keep == "last" or key not in winners:
                    winners[key] = (fi, ri)
        ordered = sorted(winners)

        # pass 2: slab-at-a-time copy in sorted-key order, batched reads
        with CxiWriter(output, max_peaks=max_peaks) as w:
            for c0 in range(0, len(ordered), chunk_events):
                slab = ordered[c0 : c0 + chunk_events]
                by_file: dict = {}
                for pos, key in enumerate(slab):
                    fi, ri = winners[key]
                    by_file.setdefault(fi, []).append((ri, pos))
                rows: list = [None] * len(slab)
                for fi, pairs in by_file.items():
                    refs = handles[fi]
                    # h5py fancy selection needs increasing indices; the
                    # (fi, ri) winner rows are unique, so sorted is strict
                    pairs.sort()
                    ris = [ri for ri, _ in pairs]
                    n_a = refs["n"][ris]
                    y_a = refs["y"][ris]
                    x_a = refs["x"][ris]
                    i_a = refs["i"][ris]
                    e_a = refs["energy"][ris]
                    for j, (_, pos) in enumerate(pairs):
                        k = int(n_a[j])
                        key = slab[pos]
                        rows[pos] = PeakSet(
                            event_idx=key[1], shard_rank=key[0],
                            y=y_a[j, :k].astype(np.float32),
                            x=x_a[j, :k].astype(np.float32),
                            intensity=i_a[j, :k].astype(np.float32),
                            photon_energy=float(e_a[j]) / 1000.0,
                        )
                w.append(rows)
    return len(ordered)


def merge_cxi_main(argv=None):
    """``psana-ray-tpu-cxi-merge`` — merge + dedupe per-run CXI files."""
    import argparse

    ap = argparse.ArgumentParser(prog="psana-ray-tpu-cxi-merge")
    ap.add_argument("inputs", nargs="+", help="CXI files, oldest run first")
    ap.add_argument("--output", required=True, help="must not already exist")
    ap.add_argument(
        "--max_peaks", type=int, default=None,
        help="output row width (default: widest input — lossless); a "
        "narrower value is refused rather than truncating",
    )
    ap.add_argument(
        "--keep", choices=["last", "first"], default="last",
        help="which duplicate of a (shard_rank, event_idx) to keep "
        "(default: last — a resumed run supersedes the crashed one)",
    )
    ap.add_argument(
        "--chunk_events", type=int, default=1024,
        help="events copied per slab in pass 2 (peak memory scales with "
        "chunk_events * row width; lower it on memory-constrained hosts)",
    )
    import sys

    a = ap.parse_args(argv)
    try:
        n = merge_cxi(a.inputs, a.output, max_peaks=a.max_peaks, keep=a.keep,
                      chunk_events=a.chunk_events)
    except (ValueError, OSError) as e:
        # ValueError: clobber/width/foreign-layout refusals; OSError:
        # h5py on a missing/unreadable input path — both are operator
        # errors, not bugs: explain and exit, no traceback
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"merged {len(a.inputs)} file(s) -> {a.output}: {n} unique events")
    return 0
