"""Pipeline parallelism: GPipe microbatch schedule over a 'pipe' mesh axis.

The reference has no model code, hence no pipeline parallelism beyond the
macro produce→queue→consume pipe (SURVEY.md §2 "Parallelism strategies");
the task spec makes PP a first-class sharding for the TPU build. This is
the TPU-idiomatic realization: no per-stage processes, no send/recv
threads — ONE SPMD program over a ``pipe`` mesh axis where

- stage parameters are stacked along a leading axis sharded
  ``P('pipe')`` (each device physically holds only its own stage);
- the microbatch schedule is a ``lax.scan`` over ``M + S - 1`` ticks;
- activations hop stage→stage with ``lax.ppermute`` — neighbor ICI
  traffic, overlapped with the next tick's compute by XLA;
- the bubble is the standard GPipe ``(S-1)/(M+S-1)`` and shrinks as the
  microbatch count grows.

Because every collective here (``ppermute``, the final masked ``psum``)
has a registered transpose, ``jax.grad`` THROUGH :func:`pipeline_apply`
yields the reverse pipeline schedule automatically — the backward pass
runs the same scan in reverse with cotangents hopping the ring the other
way. One definition, forward and backward pipelining both real.

Composition: the batch dim may simultaneously be sharded over a ``data``
axis (DP×PP) — each data-group runs an independent pipeline. TP inside a
stage composes the same way (stage params additionally sharded on
``model``), giving the full DP×PP×TP layout on a 3-axis mesh.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from psana_ray_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    microbatches: Optional[int] = None,
    data_axis: Optional[str] = None,
) -> jax.Array:
    """Run ``x`` through ``S`` pipeline stages with GPipe microbatching.

    ``stage_fn(params_slice, x_mb) -> y_mb`` applies ONE stage; output
    shape must equal input shape (true of transformer blocks — the hop
    buffer that rides the ring is shape-uniform). ``stacked_params`` is a
    pytree whose leaves carry a leading stage axis of size
    ``S = mesh.shape[pipe_axis]``; under jit they should be sharded
    ``P(pipe_axis)`` so each device materializes only its stage.

    ``x`` is the global batch ``[B, ...]`` with ``B`` divisible by
    ``microbatches`` (default ``S``, the smallest count that fills the
    pipeline). The result is ``stage_S(...stage_1(x))``, replicated over
    ``pipe_axis`` (a masked ``psum`` fans the last stage's outputs back
    out — activations-sized, the price of returning a mesh-global value).
    """
    n_stages = mesh.shape[pipe_axis]
    m = microbatches or n_stages
    b_local = x.shape[0] // (mesh.shape[data_axis] if data_axis else 1)
    if b_local % m:
        raise ValueError(
            f"per-data-group batch {b_local} not divisible by microbatches={m} "
            f"(each data group runs its own pipeline over its local rows)"
        )

    def local(params, x):
        # params: leaves [1, ...] (this device's stage slice); x: [B_local, ...]
        params = jax.tree.map(lambda p: p[0], params)
        idx = lax.axis_index(pipe_axis)
        mb = x.shape[0] // m
        xs = x.reshape(m, mb, *x.shape[1:])
        hop = jnp.zeros((mb, *x.shape[1:]), x.dtype)  # activation arriving on the ring
        outs = jnp.zeros_like(xs)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            hop, outs = carry
            # stage 0 feeds microbatch t (clipped reads past the end are
            # bubble work whose result is never written or hopped onward
            # into anything real)
            x_t = lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            y = stage_fn(params, jnp.where(idx == 0, x_t, hop))
            # last stage finishes microbatch t-(S-1) at tick t
            o = jnp.clip(t - (n_stages - 1), 0, m - 1)
            cur = lax.dynamic_index_in_dim(outs, o, 0, keepdims=False)
            write = jnp.logical_and(idx == n_stages - 1, t >= n_stages - 1)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, y, cur), o, 0
            )
            return (lax.ppermute(y, pipe_axis, perm), outs), None

        (_, outs), _ = lax.scan(tick, (hop, outs), jnp.arange(m + n_stages - 1))
        # only the last stage holds real outputs; masked psum replicates them
        outs = lax.psum(jnp.where(idx == n_stages - 1, outs, 0), pipe_axis)
        return outs.reshape(x.shape)

    param_spec = jax.tree.map(lambda _: P(pipe_axis), stacked_params)
    x_spec = P(data_axis)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(param_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stacked_params, x)


def stack_stages(stacked_depth_params: Any, n_stages: int) -> Any:
    """Regroup depth-stacked params ``[D, ...] -> [S, D/S, ...]``.

    Flax's ``nn.scan`` trunk (``models.vit.ViTHitClassifier(scan_trunk=
    True)``) produces one leading ``depth`` axis; pipeline stages each own
    ``D/S`` consecutive blocks, so the stage axis is the outer factor."""

    def regroup(p):
        d = p.shape[0]
        if d % n_stages:
            raise ValueError(f"depth {d} not divisible by {n_stages} stages")
        return p.reshape(n_stages, d // n_stages, *p.shape[1:])

    return jax.tree.map(regroup, stacked_depth_params)
