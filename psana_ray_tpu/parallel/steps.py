"""Sharded init / inference / training steps over a mesh.

The bridge between mesh-agnostic flax models (models/) and the device mesh:
params are initialized directly into their mesh shardings (no host-side
giant pytree), inference and train steps are jit'd with explicit
in/out shardings, and gradient reduction across the data axis is implicit
in the shardings — XLA inserts the psums over ICI (scaling-book recipe:
annotate, don't hand-write collectives).

The reference has no counterpart (its consumers are opaque torch loops);
this is the "pjit'd model" half of the BASELINE north star.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax.core import meta as nn_meta
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from psana_ray_tpu.parallel.sharding import ShardingRules


def _mesh_shardings_for_variables(abstract_vars, mesh: Mesh, rules: ShardingRules):
    """Logical-axis metadata (nn.with_logical_partitioning) -> NamedShardings.
    Unannotated leaves replicate."""
    logical = nn.get_partition_spec(abstract_vars)
    rules_tuple = tuple((l, a) for l, a in rules.rules)
    return nn.logical_to_mesh_sharding(logical, mesh, rules_tuple)


def init_sharded(
    model: nn.Module,
    rng: jax.Array,
    sample: jax.Array,
    mesh: Mesh,
    rules: Optional[ShardingRules] = None,
):
    """Initialize variables directly into their mesh shardings.

    Returns an *unboxed* params pytree (plain arrays, each carrying its
    NamedSharding) — optax and checkpointing consume it directly."""
    rules = rules or ShardingRules()
    abstract = jax.eval_shape(model.init, rng, sample)
    shardings = _mesh_shardings_for_variables(abstract, mesh, rules)
    variables = jax.jit(model.init, out_shardings=shardings)(rng, sample)
    return nn_meta.unbox(variables)


def make_infer_step(model: nn.Module, mesh: Mesh, data_axis: str = "data"):
    """jit'd ``(variables, x) -> logits`` with batch rows over the data axis."""
    x_sharding = NamedSharding(mesh, P(data_axis))

    @jax.jit
    def infer(variables, x):
        return model.apply(variables, x)

    def step(variables, x):
        return infer(variables, jax.device_put(x, x_sharding) if not isinstance(x, jax.Array) else x)

    return step


@dataclasses.dataclass
class TrainState:
    """Minimal train state (params + opt state + step counter)."""

    variables: Any
    opt_state: Any
    step: jax.Array


def make_train_step(
    model: nn.Module,
    optimizer: optax.GradientTransformation,
    loss_fn: Callable[..., jax.Array],
    donate: bool = True,
    remat: bool = False,
):
    """Build ``(state, batch) -> (state, loss)``.

    ``loss_fn(logits, batch) -> scalar``. Gradient reduction over the data
    axis happens inside jit via the sharding propagation (batch sharded on
    'data', params replicated/TP -> XLA inserts psum on the grads).
    ``donate=True`` donates the state buffers, so params update in place —
    essential at ResNet-50 scale on a 16 GB chip. ``remat=True`` wraps the
    forward in ``jax.checkpoint`` so the backward pass recomputes
    activations instead of storing them — the FLOPs-for-HBM trade that
    makes long-sequence / deep-model training fit on chip."""

    def _step(state: TrainState, x: jax.Array, batch_aux) -> Tuple[TrainState, jax.Array]:
        apply = model.apply
        if remat:
            apply = jax.checkpoint(apply)

        def loss_of(variables):
            logits = apply(variables, x)
            return loss_fn(logits, batch_aux)

        loss, grads = jax.value_and_grad(loss_of)(state.variables)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.variables)
        variables = optax.apply_updates(state.variables, updates)
        return TrainState(variables, opt_state, state.step + 1), loss

    return jax.jit(_step, donate_argnums=(0,) if donate else ())


def create_train_state(
    model: nn.Module,
    optimizer: optax.GradientTransformation,
    rng: jax.Array,
    sample: jax.Array,
    mesh: Mesh,
    rules: Optional[ShardingRules] = None,
) -> TrainState:
    variables = init_sharded(model, rng, sample, mesh, rules)
    # Moment buffers inherit the param shardings; scalar leaves (e.g. adam's
    # count) must be explicitly replicated across the mesh — left on a
    # single device, the first train step after a checkpoint restore fails
    # with "incompatible devices" (restore preserves committed shardings).
    opt_state = jax.jit(optimizer.init)(variables)
    replicated = NamedSharding(mesh, P())
    opt_state = jax.tree.map(
        lambda x: jax.device_put(x, replicated)
        if hasattr(x, "sharding") and len(x.sharding.device_set) < mesh.size
        else x,
        opt_state,
    )
    step = jax.device_put(jnp.zeros((), jnp.int32), replicated)
    return TrainState(variables, opt_state, step)


jax.tree_util.register_dataclass(
    TrainState, data_fields=["variables", "opt_state", "step"], meta_fields=[]
)
