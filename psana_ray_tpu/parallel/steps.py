"""Sharded init / inference / training steps over a mesh.

The bridge between mesh-agnostic flax models (models/) and the device mesh:
params are initialized directly into their mesh shardings (no host-side
giant pytree), inference and train steps are jit'd with explicit
in/out shardings, and gradient reduction across the data axis is implicit
in the shardings — XLA inserts the psums over ICI (scaling-book recipe:
annotate, don't hand-write collectives).

The reference has no counterpart (its consumers are opaque torch loops);
this is the "pjit'd model" half of the BASELINE north star.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax.core import meta as nn_meta
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from psana_ray_tpu.parallel.sharding import ShardingRules


def _mesh_shardings_for_variables(abstract_vars, mesh: Mesh, rules: ShardingRules):
    """Logical-axis metadata (nn.with_logical_partitioning) -> NamedShardings.
    Unannotated leaves replicate; rules naming a mesh axis the mesh lacks
    degrade to replication on that axis (ShardingRules.spec), so e.g. an
    'expert'-annotated MoE still initializes on a plain ('data','model')
    mesh."""
    logical = nn.get_partition_spec(abstract_vars)
    return jax.tree.map(
        lambda spec: rules.sharding(tuple(spec), mesh)
        if isinstance(spec, P)
        else NamedSharding(mesh, P()),
        logical,
        is_leaf=lambda x: isinstance(x, P),
    )


def init_sharded(
    model: nn.Module,
    rng: jax.Array,
    sample: jax.Array,
    mesh: Mesh,
    rules: Optional[ShardingRules] = None,
):
    """Initialize variables directly into their mesh shardings.

    Returns an *unboxed* params pytree (plain arrays, each carrying its
    NamedSharding) — optax and checkpointing consume it directly."""
    rules = rules or ShardingRules()
    abstract = jax.eval_shape(model.init, rng, sample)
    shardings = _mesh_shardings_for_variables(abstract, mesh, rules)
    variables = jax.jit(model.init, out_shardings=shardings)(rng, sample)
    return nn_meta.unbox(variables)


def make_infer_step(model: nn.Module, mesh: Mesh, data_axis: str = "data"):
    """jit'd ``(variables, x) -> logits`` with batch rows over the data axis.

    Host arrays are ``device_put`` on the caller's thread each call — one
    synchronous full-frame H2D copy per batch. That is fine for scripts
    and tests; a streaming loop should feed pre-placed ``jax.Array``s
    (which pass through untouched) from the double-buffered prefetcher
    (``infeed.pipeline.DevicePrefetcher``) so transfers overlap compute."""
    x_sharding = NamedSharding(mesh, P(data_axis))

    @jax.jit
    def infer(variables, x):
        return model.apply(variables, x)

    def step(variables, x):
        return infer(variables, jax.device_put(x, x_sharding) if not isinstance(x, jax.Array) else x)

    return step


@dataclasses.dataclass
class TrainState:
    """Minimal train state (params + opt state + step counter)."""

    variables: Any
    opt_state: Any
    step: jax.Array


def make_train_step(
    model: nn.Module,
    optimizer: optax.GradientTransformation,
    loss_fn: Callable[..., jax.Array],
    donate: bool = True,
    remat: bool = False,
    aux_loss_weight: float = 0.0,
):
    """Build ``(state, batch) -> (state, loss)``.

    ``loss_fn(logits, batch) -> scalar``. Gradient reduction over the data
    axis happens inside jit via the sharding propagation (batch sharded on
    'data', params replicated/TP -> XLA inserts psum on the grads).
    ``donate=True`` donates the state buffers, so params update in place —
    essential at ResNet-50 scale on a 16 GB chip. ``remat=True`` wraps the
    forward in ``jax.checkpoint`` so the backward pass recomputes
    activations instead of storing them — the FLOPs-for-HBM trade that
    makes long-sequence / deep-model training fit on chip.

    ``aux_loss_weight>0`` runs the forward with the ``intermediates``
    collection mutable and adds ``weight · Σ`` of every sown ``aux_loss``
    to the objective — the MoE router's load-balancing term
    (:mod:`psana_ray_tpu.parallel.moe`). Intermediates are consumed here,
    never carried into the returned state."""

    def _step(state: TrainState, x: jax.Array, batch_aux) -> Tuple[TrainState, jax.Array]:
        # Gradients flow to the 'params' collection only. norm='batch'
        # models additionally carry running statistics in a mutable
        # 'batch_stats' collection (the train→serve export form,
        # models/fold.py): the updated stats ride back in the new state.
        # Stats are computed on the GLOBAL (sharded) batch inside jit —
        # XLA inserts the cross-device mean reductions, so multi-host
        # training needs no axis_name plumbing.
        params = state.variables["params"]
        other = {k: v for k, v in state.variables.items() if k != "params"}
        has_stats = "batch_stats" in other

        def fwd(p, x):
            variables = {**other, "params": p}
            mutable = (("batch_stats",) if has_stats else ()) + (
                ("intermediates",) if aux_loss_weight else ()
            )
            if mutable:
                return model.apply(variables, x, mutable=mutable)
            return model.apply(variables, x), {}

        if remat:
            fwd = jax.checkpoint(fwd)

        def loss_of(p):
            logits, mutated = fwd(p, x)
            loss = loss_fn(logits, batch_aux)
            if aux_loss_weight:
                from psana_ray_tpu.parallel.moe import total_aux_loss

                mutated = dict(mutated)
                loss = loss + aux_loss_weight * total_aux_loss(
                    mutated.pop("intermediates", {})
                )
            return loss, mutated

        (loss, mutated), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
        updates, opt_state = optimizer.update(
            {"params": grads}, state.opt_state, {"params": params}
        )
        params = optax.apply_updates({"params": params}, updates)["params"]
        variables = {**other, "params": params, **mutated}
        return TrainState(variables, opt_state, state.step + 1), loss

    return jax.jit(_step, donate_argnums=(0,) if donate else ())


def create_train_state(
    model: nn.Module,
    optimizer: optax.GradientTransformation,
    rng: jax.Array,
    sample: jax.Array,
    mesh: Mesh,
    rules: Optional[ShardingRules] = None,
) -> TrainState:
    variables = init_sharded(model, rng, sample, mesh, rules)
    # Moment buffers inherit the param shardings; scalar leaves (e.g. adam's
    # count) must be explicitly replicated across the mesh — left on a
    # single device, the first train step after a checkpoint restore fails
    # with "incompatible devices" (restore preserves committed shardings).
    # Optimizer state covers the 'params' collection only (make_train_step
    # updates {'params': ...}); non-param collections like 'batch_stats'
    # are carried by the train step, not the optimizer.
    opt_state = jax.jit(optimizer.init)({"params": variables["params"]})
    replicated = NamedSharding(mesh, P())
    opt_state = jax.tree.map(
        lambda x: jax.device_put(x, replicated)
        if hasattr(x, "sharding") and len(x.sharding.device_set) < mesh.size
        else x,
        opt_state,
    )
    step = jax.device_put(jnp.zeros((), jnp.int32), replicated)
    return TrainState(variables, opt_state, step)


jax.tree_util.register_dataclass(
    TrainState, data_fields=["variables", "opt_state", "step"], meta_fields=[]
)
