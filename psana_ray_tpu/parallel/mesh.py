"""Device mesh construction + host-local batch geometry.

Axes convention (scaling-book style):
- ``data``  — batch rows (DP across hosts and chips)
- ``model`` — tensor/spatial sharding within the model (TP)
Optionally ``seq`` for sequence/context parallelism (ring attention).

`create_mesh` infers -1 axes from the device count, so the same config runs
on 1 real chip, an 8-device virtual CPU mesh, or a v5e-16 pod slice.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from psana_ray_tpu.config import MeshConfig


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    axis_names: Tuple[str, ...]
    axis_shape: Tuple[int, ...]

    @staticmethod
    def from_config(cfg: MeshConfig) -> "MeshSpec":
        return MeshSpec(tuple(cfg.axis_names), tuple(cfg.axis_shape))


def _resolve_shape(shape: Sequence[int], n_devices: int) -> Tuple[int, ...]:
    shape = list(shape)
    unknown = [i for i, s in enumerate(shape) if s == -1]
    known = int(np.prod([s for s in shape if s != -1])) if shape else 1
    if len(unknown) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if unknown:
        if n_devices % known != 0:
            raise ValueError(f"{n_devices} devices not divisible by fixed axes {shape}")
        shape[unknown[0]] = n_devices // known
    if int(np.prod(shape)) != n_devices:
        raise ValueError(f"mesh shape {shape} != device count {n_devices}")
    return tuple(shape)


def create_mesh(
    axis_names: Sequence[str] = ("data", "model"),
    axis_shape: Sequence[int] = (-1, 1),
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh over the available devices, inferring any -1 axis.

    Device order follows ``jax.devices()`` — on real pods that order is
    ICI-contiguous, so neighboring mesh coordinates are ICI neighbors and
    collectives ride ICI, not DCN."""
    devices = list(devices if devices is not None else jax.devices())
    shape = _resolve_shape(list(axis_shape), len(devices))
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def data_axis_size(mesh: Mesh, data_axis: str = "data") -> int:
    return mesh.shape[data_axis]


def local_batch_slice(global_batch: int, mesh: Mesh, data_axis: str = "data") -> int:
    """Rows this *process* contributes to a global batch (multi-host DP).

    Validates both constraints a ``P(data_axis)`` sharding imposes: rows
    must split evenly over the mesh's data axis AND over the hosts."""
    d = data_axis_size(mesh, data_axis)
    if global_batch % d != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by data axis size {d}"
        )
    if global_batch % jax.process_count() != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by {jax.process_count()} hosts"
        )
    return global_batch // jax.process_count()
