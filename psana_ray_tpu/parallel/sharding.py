"""Named sharding rules: map logical array axes -> mesh axes.

A tiny, explicit version of the "logical axis rules" idiom: each parameter
or activation names its axes (e.g. ``("batch", "panel", "height", "width")``)
and the rules table maps logical names to mesh axis names (or None =
replicate). This keeps model code free of mesh knowledge — the same flax
module pjit's under any rules table.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Logical-name -> mesh-axis mapping."""

    rules: Tuple[Tuple[str, Optional[str]], ...] = (
        ("batch", "data"),
        ("embed", None),
        ("heads", "model"),
        ("kv", None),
        ("mlp", "model"),
        ("channels_in", None),
        ("channels_out", "model"),
        ("classes", None),
        ("panel", None),
        ("height", None),
        ("width", None),
        ("seq", "seq"),
        ("expert", "expert"),
        ("layers", None),
        ("stage", "pipe"),
    )

    def mesh_axis(self, logical: Optional[str]) -> Optional[str]:
        if logical is None:
            return None
        for name, axis in self.rules:
            if name == logical:
                return axis
        return None

    def spec(self, logical_axes: Sequence[Optional[str]], mesh: Mesh) -> P:
        """PartitionSpec for an array with the given logical axis names.
        Mesh axes absent from the mesh degrade to replication, so rules
        mentioning 'seq' still work on a ('data','model') mesh."""
        return P(
            *(
                axis if (axis := self.mesh_axis(l)) in mesh.axis_names else None
                for l in logical_axes
            )
        )

    def sharding(self, logical_axes: Sequence[Optional[str]], mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes, mesh))


def infer_sharding(pytree_logical, mesh: Mesh, rules: Optional[ShardingRules] = None):
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    rules = rules or ShardingRules()
    return jax.tree.map(
        lambda axes: rules.sharding(axes, mesh),
        pytree_logical,
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x),
    )
