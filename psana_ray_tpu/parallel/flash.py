"""Flash attention (Pallas TPU kernel) + ring composition over the mesh.

:func:`ring_attention` (ring_attention.py) is the exact XLA formulation —
differentiable, runs anywhere, materializes one [Sq, Sk] score block per
hop. This module is the serving-optimized TPU path:

- :func:`attention_with_stats` — one device's attention returning the
  online-softmax statistics (normalized output + row log-sum-exp). On TPU
  with kernel-friendly shapes it runs a vendored Pallas flash kernel
  (below — no private JAX APIs) so the score matrix never leaves VMEM;
  elsewhere (or for odd shapes) an XLA fallback computes the same
  statistics.
- :func:`ring_flash_attention` — K/V shards rotate around the ``seq``
  mesh axis (``lax.ppermute`` — neighbor ICI traffic only); each hop runs
  a full flash attention against the visiting K/V block and hops combine
  by log-sum-exp, which is exact (softmax is associative under LSE
  renormalization). Causal hops use BLOCK-level structure: a visiting
  block entirely in the future contributes nothing (skipped — no wasted
  FLOPs), entirely in the past attends unmasked, and only the diagonal
  block runs the masked kernel.

Dtype contract: ``o`` matches the query dtype; the log-sum-exp statistics
are ALWAYS float32 regardless of input dtype (bf16 stats lose peaks and
break cross-hop renormalization), and the ring's running (m, num, den)
carry is float32 for the same reason.

Layouts match ring_attention.py: global ``[B, S, H, D]`` sharded
``P(None, seq_axis)``.

Differentiability: :func:`flash_attention` carries a full flash VJP
(backward kernels regenerate probability tiles from the saved row
log-sum-exp — no stored score matrix in either direction), which powers
``ulysses_attention(impl='flash')`` for long-context training. The
stats-returning :func:`attention_with_stats` is ALSO differentiable —
its lse cotangent folds into the backward's delta term (∂lse/∂s = p), so
the same two backward kernels serve it — which makes the hop-combining
:func:`ring_flash_attention` trainable end to end: gradients flow through
the LSE renormalization, the ``lax.switch`` causal hop structure, the
``fori_loop`` rotation (static trip count → scan), and the ``ppermute``
(whose transpose is the reverse rotation).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from psana_ray_tpu.parallel.compat import shard_map
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30
# Minimum tile edge (Mosaic lane constraint) — also the divisibility floor
# the kernel requires of Sq/Sk. ACTUAL block sizes are picked per call by
# :func:`_pick_blocks`: 128x128 tiles leave the kernel vector-bound (the
# f32 softmax/rescale work on a tile rivals its two 128-wide matmuls);
# growing the K edge amortizes the online-softmax state updates over more
# MXU work. Measured on v5e-1 at the ViT serving shape [2, 8448, 4, 128]:
# 128x128 = 16.9 ms, 256x256 = 8.3 ms, 384x1408 = 2.81 ms, plateau ~2.7 ms
# (~55% MXU util vs the 1.5 ms FLOP floor) — a 6x kernel speedup from
# block shape alone.
_BLOCK_MIN = 128
_MAX_BLOCK_Q = 512
_MAX_TILE_ELEMS = 1 << 20  # bq*bk cap: the f32 score tile stays ~4 MB VMEM
_MAX_KV_TILE_ELEMS = 1 << 18  # bk*d cap: K/V tiles (and the dkv backward's
# two f32 scratches) are double-buffered across grid steps — without this
# a small-sq / large-d call could pick a bk whose tiles alone blow VMEM


def _pick_blocks(sq: int, sk: int, d: int, backward: bool = False) -> Tuple[int, int]:
    """Largest (block_q, block_k) multiples of 128 that divide (sq, sk),
    with block_q capped and both the f32 score tile (bq*bk) and the K/V
    tile (bk*d) footprints bounded.

    ``backward=True`` halves both caps: the backward kernels keep THREE
    score-shaped f32 temps live at once (p, dp, ds) plus f32 dk/dv
    accumulator scratches, so forward-sized blocks can exceed VMEM on
    shapes (e.g. sq=sk=2048, d=128) that the forward compiles fine."""
    tile_cap = _MAX_TILE_ELEMS // (2 if backward else 1)
    kv_cap = _MAX_KV_TILE_ELEMS // (2 if backward else 1)
    bq = max(
        b for b in range(_BLOCK_MIN, min(sq, _MAX_BLOCK_Q) + 1, _BLOCK_MIN)
        if sq % b == 0
    )
    bk_cap = max(_BLOCK_MIN, min(tile_cap // bq, kv_cap // d))
    bk = max(
        b for b in range(_BLOCK_MIN, min(sk, bk_cap) + 1, _BLOCK_MIN)
        if sk % b == 0
    )
    return bq, bk


def _xla_attention_with_stats(q, k, v, causal: bool) -> Tuple[jax.Array, jax.Array]:
    """[B,H,Sq,D] x [B,H,Sk,D] -> (o [B,H,Sq,D] q.dtype, lse [B,H,Sq] f32)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where((ki > qi)[None, None], NEG_INF, s)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    ) / jnp.maximum(l, 1e-30)[..., None]
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return o.astype(q.dtype), lse


# ---------------------------------------------------------------------------
# Vendored Pallas TPU flash kernel (public pallas APIs only).
#
# Grid (BH, Sq/block_q, Sk/block_k), key blocks iterating fastest: per step
# ONE [block_q, d] query tile and ONE [block_k, d] K/V tile are resident in
# VMEM (Pallas pipelines the tile DMAs across grid steps), so VMEM use is
# independent of sequence length — a [block_q, Sk] score matrix never
# exists and neither does a full K/V copy.  The online-softmax state
# (m, l, acc) lives in f32 VMEM scratch, which persists across grid steps;
# it is reset when a new query tile begins (kb == 0) and the normalized
# output + lse are written on the tile's last key step.  Scores/stats are
# f32; the p @ v matmul runs in the value dtype on the MXU with f32
# accumulation.  Causal tiles mask with NEG_INF; the masked-out entries
# are explicitly zeroed in p (exp(NEG_INF - NEG_INF) would otherwise
# contribute 1 on fully-dead tiles).
# ---------------------------------------------------------------------------


def _causal_tile_mask(qi, kb, block_q, block_k):
    """[block_q, block_k] bool, True where the entry is in the FUTURE
    (k index > q index) — shared by the forward and backward kernels so
    their masking can never desynchronize."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    cols = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return cols > rows


def _tile_live(qi, kb, block_q, block_k):
    """False when the whole (qi, kb) tile is in the causal future — its
    contribution is exactly zero, so kernels skip the tile body outright
    (~2x FLOPs saved on causal at long S; the README advertises this at
    hop level for the ring, the same structure applies at tile level)."""
    return (qi + 1) * block_q > kb * block_k


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    *, sm_scale, causal, n_kb
):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]

    @pl.when(kb == 0)
    def _reset():
        m_ref[:] = jnp.full((block_q, 1), NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros((block_q, 1), jnp.float32)
        acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    def _tile_body():
        s = (
            jax.lax.dot_general(
                q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # [block_q, block_k]
        if causal:
            s = jnp.where(_causal_tile_mask(qi, kb, block_q, block_k), NEG_INF, s)

        m = m_ref[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # masked scores are exactly NEG_INF; on a fully-dead tile m_new
        # stays NEG_INF and exp(s - m_new) would be exp(0) = 1 — zero
        # them explicitly
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + pv

    if causal:
        pl.when(_tile_live(qi, kb, block_q, block_k))(_tile_body)
    else:
        _tile_body()

    @pl.when(kb == n_kb - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # lse rides in [bh, 1, sq] layout: 2D [bh, sq] blocks would need a
        # (1, block_q) block whose second-to-last dim Mosaic rejects (must
        # be divisible by 8 or equal the array dim)
        lse_ref[0, 0] = (m_ref[:] + jnp.log(l_safe))[:, 0]


def _pallas_attention_with_stats(
    q, k, v, causal: bool, interpret: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """Vendored flash kernel entry. [B,H,S,D] layout, S/D multiples of 128."""
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    qf = q.reshape(bh, sq, d)
    kf = k.reshape(bh, sk, d)
    vf = v.reshape(bh, sk, d)
    block_q, block_k = _pick_blocks(sq, sk, d)
    n_kb = sk // block_k

    kernel = functools.partial(
        _flash_kernel, sm_scale=d**-0.5, causal=causal, n_kb=n_kb
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, sq // block_q, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kb: (i, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return o.reshape(b, h, sq, d), lse.reshape(b, h, sq)


# Largest head dim the kernels accept: beyond this even the minimum
# 128-wide K/V block exceeds the BACKWARD kv-tile cap (bk*d with the
# halved budget), so _pick_blocks' >=128 floor would silently void the
# documented VMEM bound — such shapes go to the XLA fallback instead.
_MAX_HEAD_DIM = _MAX_KV_TILE_ELEMS // (2 * _BLOCK_MIN)


def _kernel_shapes_ok(q, k) -> bool:
    sq, d = q.shape[2], q.shape[3]
    sk = k.shape[2]
    return (
        d % 128 == 0
        and d <= _MAX_HEAD_DIM
        and sq % _BLOCK_MIN == 0
        and sk % _BLOCK_MIN == 0
    )


# ---------------------------------------------------------------------------
# Flash backward (the standard two-kernel formulation).  With the forward's
# residuals (q, k, v, o, lse) the normalized probabilities regenerate per
# tile as p = exp(scale·qk − lse) — no stored score matrix, same VMEM
# independence from sequence length as the forward.  Given
# delta_i = Σ_d do_id·o_id (precomputed in XLA, one cheap fused reduce):
#
#     dv = pᵀ @ do
#     ds = p ⊙ (do @ vᵀ − delta)          (softmax Jacobian, normalized p)
#     dq = scale · ds @ k                  (accumulated over key blocks)
#     dk = scale · dsᵀ @ q                 (accumulated over query blocks)
#
# Two kernels because the two accumulations want opposite grid orders:
# dkv iterates query blocks innermost (dk/dv tiles resident), dq iterates
# key blocks innermost (dq tile resident).  Masked entries are explicitly
# zeroed in p — exp(NEG_INF − lse) is NOT reliably 0 when a row is fully
# masked (lse ≈ NEG_INF makes the exponent ≈ 0, i.e. p ≈ 1).
# ---------------------------------------------------------------------------


def _bwd_tile_p_ds(q_blk, k_blk, v_blk, do_blk, lse_blk, delta_blk,
                   sm_scale, causal, qi, kb, block_q, block_k):
    """Shared per-tile math: normalized probabilities + ds (both f32)."""
    s = (
        jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * sm_scale
    )  # [block_q, block_k]
    p = jnp.exp(s - lse_blk[:, None])
    if causal:
        p = jnp.where(_causal_tile_mask(qi, kb, block_q, block_k), 0.0, p)
    dp = jax.lax.dot_general(
        do_blk, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_blk[:, None]) * sm_scale
    return p, ds


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, sm_scale, causal, n_qb
):
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    block_k, d = k_ref.shape[1], k_ref.shape[2]
    block_q = q_ref.shape[1]

    @pl.when(qi == 0)
    def _reset():
        dk_acc[:] = jnp.zeros((block_k, d), jnp.float32)
        dv_acc[:] = jnp.zeros((block_k, d), jnp.float32)

    def _tile_body():
        p, ds = _bwd_tile_p_ds(
            q_ref[0], k_ref[0], v_ref[0], do_ref[0], lse_ref[0, 0],
            delta_ref[0, 0], sm_scale, causal, qi, kb, block_q, block_k,
        )
        # dv += pᵀ @ do ; dk += dsᵀ @ q  (contract the query axis)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_tile_live(qi, kb, block_q, block_k))(_tile_body)
    else:
        _tile_body()

    @pl.when(qi == n_qb - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, sm_scale, causal, n_kb
):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]

    @pl.when(kb == 0)
    def _reset():
        dq_acc[:] = jnp.zeros((block_q, d), jnp.float32)

    def _tile_body():
        _, ds = _bwd_tile_p_ds(
            q_ref[0], k_ref[0], v_ref[0], do_ref[0], lse_ref[0, 0],
            delta_ref[0, 0], sm_scale, causal, qi, kb, block_q, block_k,
        )
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_tile_live(qi, kb, block_q, block_k))(_tile_body)
    else:
        _tile_body()

    @pl.when(kb == n_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _pallas_attention_bwd(
    q, k, v, o, lse, do, causal: bool, interpret: bool = False, dlse=None
):
    """[B,H,S,D] flash backward; returns (dq, dk, dv) in the input dtypes.

    ``dlse`` (optional, [B,H,Sq] f32) is the cotangent of the row
    log-sum-exp output. Since ∂lse_i/∂s_ij = p_ij, it enters the softmax
    Jacobian as ``ds = p·(dp − delta + dlse)·scale`` — algebraically just
    ``delta → delta − dlse``, so the kernels need no changes at all."""
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bh = b * h
    sm_scale = d**-0.5
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    qf, kf, vf = (x.reshape(bh, -1, d) for x in (q, k, v))
    dof = do.reshape(bh, sq, d)
    # [bh, 1, sq] stats layout — see the forward's lse note on Mosaic's
    # last-two-dims block constraint
    lsef = lse.reshape(bh, 1, sq)
    deltaf = delta.reshape(bh, 1, sq)
    block_q, block_k = _pick_blocks(sq, sk, d, backward=True)
    n_qb, n_kb = sq // block_q, sk // block_k

    qspec = pl.BlockSpec((1, block_q, d), lambda i, a, b_: (i, b_, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda i, a, b_: (i, a, 0))
    rowspec = pl.BlockSpec((1, 1, block_q), lambda i, a, b_: (i, 0, b_))
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal, n_qb=n_qb
        ),
        grid=(bh, n_kb, n_qb),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, a, b_: (i, a, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, a, b_: (i, a, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    qspec2 = pl.BlockSpec((1, block_q, d), lambda i, a, b_: (i, a, 0))
    kspec2 = pl.BlockSpec((1, block_k, d), lambda i, a, b_: (i, b_, 0))
    rowspec2 = pl.BlockSpec((1, 1, block_q), lambda i, a, b_: (i, 0, a))
    (dq,) = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal, n_kb=n_kb
        ),
        grid=(bh, n_qb, n_kb),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[pl.BlockSpec((1, block_q, d), lambda i, a, b_: (i, a, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, sq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    return (
        dq.reshape(b, h, sq, d),
        dk.reshape(b, h, sk, d),
        dv.reshape(b, h, sk, d),
    )


def _xla_attention_bwd(q, k, v, o, lse, do, causal: bool, dlse=None):
    """Reference backward from the same residuals (normalized p from lse);
    used off-TPU and for odd shapes — materializes the score matrix.
    ``dlse`` folds into delta exactly as in :func:`_pallas_attention_bwd`."""
    sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where((ki > qi)[None, None], NEG_INF, s)
    p = jnp.exp(s - lse[..., None])
    if causal:
        p = jnp.where((ki > qi)[None, None], 0.0, p)
    dof = do.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, v.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * sm_scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _attention_core(q, k, v, causal: bool) -> Tuple[jax.Array, jax.Array]:
    """Undifferentiated (o, lse) in ``[B, H, S, D]``: Pallas flash kernel
    when the backend and shapes allow (D and both sequence lengths
    multiples of 128), else the XLA formulation."""
    if jax.default_backend() == "tpu" and _kernel_shapes_ok(q, k):
        return _pallas_attention_with_stats(q, k, v, causal)
    return _xla_attention_with_stats(q, k, v, causal)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def attention_with_stats(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """Attention + row log-sum-exp, ``[B, H, S, D]`` layout.

    Both paths return ``o`` in the query dtype and ``lse`` in float32 —
    the statistics two hops combine must never be bf16.

    Differentiable IN BOTH OUTPUTS: the VJP handles the lse cotangent by
    folding it into the softmax-Jacobian delta term (∂lse/∂s = p, so
    ``ds = p·(dp − delta + dlse)·scale`` — the same two flash backward
    kernels, with ``delta − dlse`` as their delta input). This is what
    makes :func:`ring_flash_attention` trainable: the ring's LSE
    hop-combining differentiates through these stats.
    """
    return _attention_core(q, k, v, causal)


def _aws_fwd(q, k, v, causal):
    o, lse = _attention_core(q, k, v, causal)
    return (o, lse), (q, k, v, o, lse)


def _aws_bwd(causal, res, g):
    do, dlse = g
    q, k, v, o, lse = res
    if jax.default_backend() == "tpu" and _kernel_shapes_ok(q, k):
        return _pallas_attention_bwd(q, k, v, o, lse, do, causal, dlse=dlse)
    return _xla_attention_bwd(q, k, v, o, lse, do, causal, dlse=dlse)


attention_with_stats.defvjp(_aws_fwd, _aws_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Single-device attention, repo layout ``[B, S, H, D]`` (the
    long-sequence path when the whole context fits one chip).

    Differentiable: the VJP regenerates probabilities per tile from the
    saved (q, k, v, o, lse) residuals — flash memory behavior in both
    directions, no stored score matrix (kernel shapes permitting; odd
    shapes and non-TPU backends use the XLA formulation).

    ``causal`` uses TOP-LEFT-aligned absolute indices: q row ``i`` attends
    k cols ``<= i``, i.e. q and k are assumed to share an origin. With
    ``sq != sk`` this differs from FlashAttention's usual bottom-right
    alignment — cross-attention callers whose queries are OFFSET into the
    key sequence must bake the offset into the mask themselves (internally
    consistent here: forward, backward, and the XLA oracle all use the
    same ``k_index > q_index`` rule)."""
    qh, kh, vh = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o, _ = _attention_core(qh, kh, vh, causal)
    return o.transpose(0, 2, 1, 3)


def _fa_fwd(q, k, v, causal):
    qh, kh, vh = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o, lse = _attention_core(qh, kh, vh, causal)
    return o.transpose(0, 2, 1, 3), (qh, kh, vh, o, lse)


def _fa_bwd(causal, res, g):
    qh, kh, vh, o, lse = res
    doh = g.transpose(0, 2, 1, 3)
    if jax.default_backend() == "tpu" and _kernel_shapes_ok(qh, kh):
        dq, dk, dv = _pallas_attention_bwd(qh, kh, vh, o, lse, doh, causal)
    else:
        dq, dk, dv = _xla_attention_bwd(qh, kh, vh, o, lse, doh, causal)
    return tuple(x.transpose(0, 2, 1, 3) for x in (dq, dk, dv))


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    causal: bool = False,
    data_axis: Optional[str] = None,
) -> jax.Array:
    """Exact ring attention with per-hop flash kernels + LSE combining.

    q/k/v: global ``[B, S, H, D]`` sharded ``P(data_axis, seq_axis)``
    (``data_axis=None`` replicates the batch; name a mesh axis to compose
    DP × SP — each data group runs its own independent ring). Under a
    causal mask the hop whose K/V block lies entirely in this shard's
    future is skipped outright (zero FLOPs), past blocks run unmasked, and
    only the diagonal hop pays the masked kernel — the block-level
    causal structure a token-level mask can't exploit.

    Trainable: every piece is reverse-differentiable — the per-hop
    :func:`attention_with_stats` carries a VJP with lse cotangent
    handling, and gradients flow back through the hop LSE-combine and the
    ring rotation (gradient parity vs :func:`ring_attention` is tested on
    the 8-device mesh, ``tests/test_ring_attention.py``).
    """
    n_ring = mesh.shape[seq_axis]
    spec = P(data_axis, seq_axis, None, None)

    def local(q, k, v):
        idx = lax.axis_index(seq_axis)
        qh = q.transpose(0, 2, 1, 3)  # [B,H,Sq,D]
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        b, h, sq, d = qh.shape

        # running stats in f32 ALWAYS (see module docstring): both kernel
        # and fallback emit f32 lse, and the hop-combine arithmetic below
        # must not round peaks through bf16
        mx = jnp.full((b, h, sq), NEG_INF, jnp.float32)
        num = jnp.zeros((b, h, sq, d), jnp.float32)
        den = jnp.zeros((b, h, sq), jnp.float32)

        def hop_outputs(k_cur, v_cur, src):
            if not causal:
                return attention_with_stats(qh, k_cur, v_cur, causal=False)

            def skip(k_cur, v_cur):
                return (
                    jnp.zeros_like(qh),
                    jnp.full((b, h, sq), NEG_INF, jnp.float32),
                )

            def full(k_cur, v_cur):
                return attention_with_stats(qh, k_cur, v_cur, causal=False)

            def diag(k_cur, v_cur):
                return attention_with_stats(qh, k_cur, v_cur, causal=True)

            branch = (src < idx).astype(jnp.int32) + 2 * (src == idx).astype(jnp.int32)
            return lax.switch(branch, (skip, full, diag), k_cur, v_cur)

        def body(step, carry):
            mx, num, den, k_cur, v_cur = carry
            src = (idx - step) % n_ring
            o_i, lse_i = hop_outputs(k_cur, v_cur, src)
            m_new = jnp.maximum(mx, lse_i)
            # guards: exp(NEG_INF - NEG_INF) = 1 would pollute the sums on
            # skipped hops / before the first contributing hop
            alpha = jnp.where(mx <= NEG_INF / 2, 0.0, jnp.exp(mx - m_new))
            w = jnp.where(lse_i <= NEG_INF / 2, 0.0, jnp.exp(lse_i - m_new))
            num = num * alpha[..., None] + o_i.astype(jnp.float32) * w[..., None]
            den = den * alpha + w
            perm = [(i, (i + 1) % n_ring) for i in range(n_ring)]
            k_nxt = lax.ppermute(k_cur, seq_axis, perm)
            v_nxt = lax.ppermute(v_cur, seq_axis, perm)
            return m_new, num, den, k_nxt, v_nxt

        mx, num, den, _, _ = lax.fori_loop(0, n_ring, body, (mx, num, den, kh, vh))
        o = (num / jnp.maximum(den, 1e-30)[..., None]).astype(q.dtype)
        return o.transpose(0, 2, 1, 3)

    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
