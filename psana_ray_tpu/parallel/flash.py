"""Flash attention (Pallas TPU kernel) + ring composition over the mesh.

:func:`ring_attention` (ring_attention.py) is the exact XLA formulation —
differentiable, runs anywhere, materializes one [Sq, Sk] score block per
hop. This module is the serving-optimized TPU path:

- :func:`attention_with_stats` — one device's attention returning the
  online-softmax statistics (normalized output + row log-sum-exp). On TPU
  with kernel-friendly shapes it runs the stock Pallas flash kernel
  (``jax.experimental.pallas.ops.tpu.flash_attention``) so the score
  matrix never leaves VMEM; elsewhere (or for odd shapes) an XLA fallback
  computes the same statistics.
- :func:`ring_flash_attention` — K/V shards rotate around the ``seq``
  mesh axis (``lax.ppermute`` — neighbor ICI traffic only); each hop runs
  a full flash attention against the visiting K/V block and hops combine
  by log-sum-exp, which is exact (softmax is associative under LSE
  renormalization). Causal hops use BLOCK-level structure: a visiting
  block entirely in the future contributes nothing (skipped — no wasted
  FLOPs), entirely in the past attends unmasked, and only the diagonal
  block runs the masked kernel.

Layouts match ring_attention.py: global ``[B, S, H, D]`` sharded
``P(None, seq_axis)``. The flash kernel path is forward-only (the stock
kernel's residual-returning entry point has no VJP); use
:func:`ring_attention` for training.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _xla_attention_with_stats(q, k, v, causal: bool) -> Tuple[jax.Array, jax.Array]:
    """[B,H,Sq,D] x [B,H,Sk,D] -> (o [B,H,Sq,D], lse [B,H,Sq])."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where((ki > qi)[None, None], NEG_INF, s)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v) / jnp.maximum(l, 1e-30)[..., None]
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return o, lse


def _kernel_shapes_ok(q, k) -> bool:
    b, h, sq, d = q.shape
    sk = k.shape[2]
    return d % 128 == 0 and sq % 128 == 0 and sk % 128 == 0


def attention_with_stats(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """Attention + row log-sum-exp, ``[B, H, S, D]`` layout.

    Dispatches to the Pallas TPU flash kernel when the backend and shapes
    allow (D and both sequence lengths multiples of 128), else the XLA
    formulation. Both return bit-compatible statistics for LSE combining.
    """
    if jax.default_backend() == "tpu" and _kernel_shapes_ok(q, k):
        from jax.experimental.pallas.ops.tpu import flash_attention as fa

        block = 128
        o, l, m = fa._flash_attention_impl(
            q, k, v, None, None, True, causal, q.shape[-1] ** -0.5,
            1, block, block, block, False,
        )
        return o, m + jnp.log(jnp.maximum(l, 1e-30))
    return _xla_attention_with_stats(q, k, v, causal)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Single-device attention, repo layout ``[B, S, H, D]`` (the
    long-sequence path when the whole context fits one chip)."""
    o, _ = attention_with_stats(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
        causal=causal,
    )
    return o.transpose(0, 2, 1, 3)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    causal: bool = False,
) -> jax.Array:
    """Exact ring attention with per-hop flash kernels + LSE combining.

    q/k/v: global ``[B, S, H, D]`` sharded ``P(None, seq_axis)``. Under a
    causal mask the hop whose K/V block lies entirely in this shard's
    future is skipped outright (zero FLOPs), past blocks run unmasked, and
    only the diagonal hop pays the masked kernel — the block-level
    causal structure a token-level mask can't exploit.
    """
    n_ring = mesh.shape[seq_axis]
    spec = P(None, seq_axis, None, None)

    def local(q, k, v):
        idx = lax.axis_index(seq_axis)
        qh = q.transpose(0, 2, 1, 3)  # [B,H,Sq,D]
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        b, h, sq, d = qh.shape

        mx = jnp.full((b, h, sq), NEG_INF, qh.dtype)
        num = jnp.zeros_like(qh)
        den = jnp.zeros((b, h, sq), qh.dtype)

        def hop_outputs(k_cur, v_cur, src):
            if not causal:
                return attention_with_stats(qh, k_cur, v_cur, causal=False)

            def skip(k_cur, v_cur):
                return jnp.zeros_like(qh), jnp.full((b, h, sq), NEG_INF, qh.dtype)

            def full(k_cur, v_cur):
                return attention_with_stats(qh, k_cur, v_cur, causal=False)

            def diag(k_cur, v_cur):
                return attention_with_stats(qh, k_cur, v_cur, causal=True)

            branch = (src < idx).astype(jnp.int32) + 2 * (src == idx).astype(jnp.int32)
            return lax.switch(branch, (skip, full, diag), k_cur, v_cur)

        def body(step, carry):
            mx, num, den, k_cur, v_cur = carry
            src = (idx - step) % n_ring
            o_i, lse_i = hop_outputs(k_cur, v_cur, src)
            m_new = jnp.maximum(mx, lse_i)
            # guards: exp(NEG_INF - NEG_INF) = 1 would pollute the sums on
            # skipped hops / before the first contributing hop
            alpha = jnp.where(mx <= NEG_INF / 2, 0.0, jnp.exp(mx - m_new))
            w = jnp.where(lse_i <= NEG_INF / 2, 0.0, jnp.exp(lse_i - m_new))
            num = num * alpha[..., None] + o_i * w[..., None]
            den = den * alpha + w
            perm = [(i, (i + 1) % n_ring) for i in range(n_ring)]
            k_nxt = lax.ppermute(k_cur, seq_axis, perm)
            v_nxt = lax.ppermute(v_cur, seq_axis, perm)
            return m_new, num, den, k_nxt, v_nxt

        mx, num, den, _, _ = lax.fori_loop(0, n_ring, body, (mx, num, den, kh, vh))
        o = num / jnp.maximum(den, 1e-30)[..., None]
        return o.transpose(0, 2, 1, 3)

    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
