"""jax version compatibility shims for the parallel layer.

``shard_map`` moved twice across the jax line this repo spans: modern
jax exports it as ``jax.shard_map`` (with ``check_vma``); older builds
(e.g. 0.4.x, the toolchain baked into some containers) only ship
``jax.experimental.shard_map.shard_map`` whose equivalent knob is
``check_rep``. ``from jax import shard_map`` is therefore an ImportError
on those builds — it took out 10 tests and 17 collection errors on this
container's seed. Import it from HERE instead; the wrapper presents the
modern keyword surface on both.
"""

from __future__ import annotations

from typing import Any

try:  # modern jax: top-level export, check_vma spelling
    from jax import shard_map as _shard_map_new

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True) -> Any:
        return _shard_map_new(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )

except ImportError:  # jax <= 0.4.x: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True) -> Any:
        return _shard_map_old(
            f, mesh, in_specs, out_specs, check_rep=check_vma
        )
