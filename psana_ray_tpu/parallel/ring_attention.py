"""Long-context attention over the mesh: ring attention + Ulysses all-to-all.

The reference has no model code, hence no sequence parallelism (SURVEY.md
§5 "Long-context: absent"); the task spec makes it first-class for the TPU
build. Two standard schemes, both pure-JAX (shard_map + XLA collectives
over ICI — no hand-written sends):

- :func:`ring_attention` — K/V shards rotate around the 'seq' mesh axis via
  ``lax.ppermute`` while each device holds its Q shard, accumulating with
  the online-softmax (flash) recurrence. Memory per device is O(S/P); the
  P-step rotation overlaps compute with neighbor ICI transfers.
- :func:`ulysses_attention` — all-to-all re-shards sequence -> heads, runs
  ordinary attention on full sequences of H/P heads, and all-to-alls back.
  Cheaper at moderate S, needs H % P == 0.

Layouts: q/k/v are ``[B, S, H, D]`` global arrays sharded
``P(None, 'seq', None, None)``; outputs identical. Causal masking uses
global positions, so results match single-device attention bit-for-bit
(up to reduction order).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from psana_ray_tpu.parallel.compat import shard_map

NEG_INF = -1e30


def reference_attention(q, k, v, causal: bool = False):
    """Plain softmax attention, [B,S,H,D] — the single-device oracle."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qi = jnp.arange(q.shape[1])[:, None]
        ki = jnp.arange(k.shape[1])[None, :]
        s = jnp.where((ki > qi)[None, None], NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block_attn_accumulate(q, k_blk, v_blk, m, l, o, q_pos, k_pos, causal):
    """One online-softmax accumulation step against a K/V block.

    q [B,Sq,H,D]; k_blk/v_blk [B,Sk,H,D]; m,l [B,H,Sq]; o [B,Sq,H,D];
    q_pos [Sq], k_pos [Sk] global positions for causal masking."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale  # [B,H,Sq,Sk]
    if causal:
        mask = (k_pos[None, :] > q_pos[:, None])[None, None]
        s = jnp.where(mask, NEG_INF, s)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be exp(0)=1
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    if causal:
        p = jnp.where(mask, 0.0, p)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None].transpose(0, 2, 1, 3) + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v_blk
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    causal: bool = False,
    data_axis: Optional[str] = None,
) -> jax.Array:
    """Exact attention with K/V rotating around the ring.

    q/k/v: global ``[B, S, H, D]``, sharded ``P(None, seq_axis)``. Each of
    the P devices holds S/P queries and rotates its K/V shard P times, so
    every Q block sees every K/V block with only neighbor ICI traffic
    (the ring-collective pattern XLA uses for all-gather, but with the
    flash accumulation fused between hops). ``data_axis`` additionally
    shards the batch dim for DP x SP composition (independent rings per
    data group)."""
    n_ring = mesh.shape[seq_axis]
    spec = P(data_axis, seq_axis, None, None)

    def local(q, k, v):
        # q,k,v local shards [B, S/P, H, D]
        idx = lax.axis_index(seq_axis)
        b, sq, h, d = q.shape
        sk = k.shape[1]
        m = jnp.full((b, h, sq), NEG_INF, q.dtype)
        l = jnp.zeros((b, h, sq), q.dtype)
        o = jnp.zeros_like(q)
        q_pos = idx * sq + jnp.arange(sq)

        def body(step, carry):
            m, l, o, k_cur, v_cur = carry
            # K/V currently held arrived from device (idx - step) % P
            src = (idx - step) % n_ring
            k_pos = src * sk + jnp.arange(sk)
            m, l, o = _block_attn_accumulate(q, k_cur, v_cur, m, l, o, q_pos, k_pos, causal)
            # rotate: send our block to the next device, receive previous
            perm = [(i, (i + 1) % n_ring) for i in range(n_ring)]
            k_nxt = lax.ppermute(k_cur, seq_axis, perm)
            v_nxt = lax.ppermute(v_cur, seq_axis, perm)
            return m, l, o, k_nxt, v_nxt

        m, l, o, _, _ = lax.fori_loop(0, n_ring, body, (m, l, o, k, v))
        l = jnp.maximum(l, 1e-30)  # fully-masked rows (strict causal tails)
        return o / l.transpose(0, 2, 1)[..., None]

    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    seq_axis: str = "seq",
    causal: bool = False,
    impl: str = "reference",
    data_axis: Optional[str] = None,
) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Re-shards ``[B, S/P, H, D] -> [B, S, H/P, D]`` with one all-to-all,
    runs full-sequence attention per head group, and restores the layout
    with a second all-to-all. Requires H % P == 0.

    ``impl='flash'`` runs the per-head-group attention through
    :func:`parallel.flash.flash_attention` — fully differentiable with
    flash memory behavior in both directions (its VJP regenerates
    probability tiles from the saved lse instead of storing the score
    matrix), making this the long-context TRAINING path at scale;
    ``'reference'`` is the exact O(S²)-memory formulation.

    ``data_axis`` names the mesh axis the BATCH dim is sharded over, so
    DP and SP compose (each data-group runs its own independent
    all-to-alls over ``seq_axis``) — the ('data', 'seq') serving mesh of
    :class:`psana_ray_tpu.models.vit.ViTHitClassifier`."""
    p_devices = mesh.shape[seq_axis]
    if q.shape[2] % p_devices != 0:
        raise ValueError(f"heads {q.shape[2]} not divisible by {seq_axis}={p_devices}")
    if impl not in ("reference", "flash"):
        raise ValueError(f"impl must be 'reference' or 'flash', got {impl!r}")
    spec = P(data_axis, seq_axis, None, None)

    def local(q, k, v):
        # local [B, S/P, H, D] -> [B, S, H/P, D]
        def scatter_heads(x):
            return lax.all_to_all(x, seq_axis, split_axis=2, concat_axis=1, tiled=True)

        def gather_seq(x):
            return lax.all_to_all(x, seq_axis, split_axis=1, concat_axis=2, tiled=True)

        qf, kf, vf = scatter_heads(q), scatter_heads(k), scatter_heads(v)
        if impl == "flash":
            from psana_ray_tpu.parallel.flash import flash_attention

            of = flash_attention(qf, kf, vf, causal=causal)
        else:
            of = reference_attention(qf, kf, vf, causal=causal)
        return gather_seq(of)

    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
