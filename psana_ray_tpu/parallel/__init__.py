"""Distribution: device meshes, sharding rules, collectives, long-context.

The reference's only parallelism is data parallelism over events (SURVEY.md
§2): MPI ranks shard the stream, competing consumers shard the queue. Here
distribution is mesh-native: a ``jax.sharding.Mesh`` with named axes, pjit'd
steps with NamedSharding rules, XLA collectives over ICI, plus the
capabilities the reference lacks entirely — tensor/spatial sharding of the
model and ring-attention sequence parallelism for long contexts.
"""

from psana_ray_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    create_mesh,
    local_batch_slice,
)
from psana_ray_tpu.parallel.sharding import ShardingRules, infer_sharding  # noqa: F401
from psana_ray_tpu.parallel.flash import (  # noqa: F401
    attention_with_stats,
    flash_attention,
    ring_flash_attention,
)
from psana_ray_tpu.parallel.ring_attention import (  # noqa: F401
    reference_attention,
    ring_attention,
    ulysses_attention,
)
from psana_ray_tpu.parallel.pp import pipeline_apply, stack_stages  # noqa: F401
from psana_ray_tpu.parallel.moe import SwitchMoEMlp, total_aux_loss  # noqa: F401
