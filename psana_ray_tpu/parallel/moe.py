"""Expert parallelism: capacity-bounded switch-routing mixture of experts.

The reference has no model code, hence no expert parallelism (SURVEY.md §2
"Parallelism strategies: TP/PP/SP/EP — none"); the task spec makes EP a
first-class sharding for the TPU build. This is the TPU-idiomatic
formulation — the GShard/Switch dense-dispatch pattern rather than any
ragged scatter/gather:

- routing produces a fixed-shape dispatch tensor (expert capacity is
  STATIC, derived from the token count at trace time), so the whole layer
  is three einsums with no dynamic shapes — XLA tiles them onto the MXU
  and, with the expert axis of the weights sharded ``P('expert')``,
  lowers the token⇄expert re-layout to an all-to-all over ICI;
- the token axis is CHUNKED into groups (the GShard/MaxText ``group_size``
  idiom): capacity is allocated per group of ``G`` consecutive tokens and
  the dispatch tensor is ``[B·T/G, G, E, C_g]`` with
  ``C_g = ceil(G·cf/E)`` — its footprint scales with ``T·C_g``, not
  ``T·C``. The monolithic form at the ViT serving shape (T=8448, E=4,
  cf=2) is a ~1.1 GB f32 tensor PER LAYER; grouped at G≤512 it is ~9 MB.
  The trade is that overflow drops are decided within each group instead
  of globally FIFO (the standard grouped-Switch semantics);
- tokens that overflow an expert's capacity are *dropped at this layer
  only*: their combine weight is zero, and the transformer block's
  residual connection passes them through unchanged (the standard Switch
  behavior);
- the router's load-balancing loss (Switch eq. 4: ``E · Σ_e f_e · p_e``)
  is sown into the ``intermediates`` collection;
  ``parallel.steps.make_train_step(aux_loss_weight=...)`` folds it into
  the training objective.

Sharding: expert weights carry the logical axis ``('expert', ...)`` which
``ShardingRules`` maps to the mesh's ``expert`` axis; activations need no
manual constraints — XLA propagates the expert sharding through the
dispatch einsum (scaling-book recipe: annotate the weights, let the
compiler place the collectives).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any


def pick_group_size(t: int, max_group_size: int) -> int:
    """Largest divisor of ``t`` that is <= ``max_group_size`` (falls back
    to ``t`` when nothing smaller divides it — tiny sequences simply stay
    monolithic). Static: derived from trace-time shapes."""
    if max_group_size <= 0 or t <= max_group_size:
        return t
    for g in range(max_group_size, 0, -1):
        if t % g == 0:
            return g
    return t


class SwitchMoEMlp(nn.Module):
    """Drop-in replacement for a transformer MLP: ``[B, T, D] -> [B, T, D]``.

    Top-1 (switch) routing over ``num_experts`` independent
    ``D -> mlp_ratio·D -> D`` GELU FFNs with per-group expert capacity
    ``C_g = ceil(G · capacity_factor / E)``. The gate value scales the
    chosen expert's output, so the router receives gradients through the
    scale (the Switch trick that makes hard top-1 routing trainable).

    ``group_size`` chunks the token axis for dispatch (see module
    docstring): None auto-picks the largest divisor of T that is
    <= ``max_group_size``; pass an explicit divisor of T to pin it.
    Routing probabilities and gates are per-token and unaffected; only
    which overflow tokens drop changes (per group vs globally)."""

    embed_dim: int
    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 2.0
    dtype: Dtype = jnp.bfloat16
    group_size: Any = None  # None = auto (largest divisor <= max_group_size)
    max_group_size: int = 512

    @nn.compact
    def __call__(self, x):
        b_in, t_in, d = x.shape
        e, f = self.num_experts, self.mlp_ratio * self.embed_dim
        g = (
            int(self.group_size)
            if self.group_size is not None
            else pick_group_size(t_in, self.max_group_size)
        )
        if t_in % g:
            raise ValueError(
                f"group_size={g} does not divide the {t_in}-token sequence"
            )
        # groups fold into the batch axis: every downstream einsum sees
        # [B*T/G, G, ...] and the dispatch tensor scales with G, not T
        x = x.reshape(b_in * (t_in // g), g, d)
        b, t = x.shape[:2]
        cap = max(1, math.ceil(t * self.capacity_factor / e))  # static

        # ---- route (f32: softmax over a handful of logits, negligible) ----
        logits = nn.Dense(
            e, dtype=jnp.float32, param_dtype=jnp.float32, name="router"
        )(x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [B, T, E]
        gate = jnp.max(probs, axis=-1)  # [B, T]
        sel = jax.nn.one_hot(jnp.argmax(probs, axis=-1), e, dtype=jnp.float32)
        # FIFO position of each token in its expert's queue; -1 where unrouted,
        # so the capacity one-hot below zeroes both overflow AND unrouted slots
        pos = jnp.cumsum(sel, axis=1) * sel - 1.0  # [B, T, E]
        dispatch = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
        combine = dispatch * gate[..., None, None]  # [B, T, E, C]

        # load-balance loss on the PRE-capacity assignment (Switch eq. 4)
        f_frac = jnp.mean(sel, axis=(0, 1))  # fraction of tokens per expert
        p_mean = jnp.mean(probs, axis=(0, 1))  # mean router prob per expert
        self.sow("intermediates", "aux_loss", e * jnp.sum(f_frac * p_mean))

        # ---- dispatch -> expert FFN -> combine (three MXU einsums) ----
        def ep_param(name, init, shape, axes):
            return self.param(
                name, nn.with_logical_partitioning(init, axes), shape, jnp.float32
            )

        w_up = ep_param(
            "w_up",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e, d, f),
            ("expert", "embed", "mlp"),
        )
        b_up = ep_param("b_up", nn.initializers.zeros, (e, f), ("expert", "mlp"))
        w_dn = ep_param(
            "w_dn",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e, f, d),
            ("expert", "mlp", "embed"),
        )
        b_dn = ep_param("b_dn", nn.initializers.zeros, (e, d), ("expert", "embed"))

        dt = self.dtype
        xin = jnp.einsum("btec,btd->ebcd", dispatch.astype(dt), x.astype(dt))
        h = nn.gelu(
            jnp.einsum("ebcd,edf->ebcf", xin, w_up.astype(dt))
            + b_up[:, None, None, :].astype(dt)
        )
        # empty capacity slots compute gelu(bias) garbage here; their combine
        # weight is zero, so nothing of it reaches the output
        out = (
            jnp.einsum("ebcf,efd->ebcd", h, w_dn.astype(dt))
            + b_dn[:, None, None, :].astype(dt)
        )
        y = jnp.einsum("btec,ebcd->btd", combine.astype(dt), out)
        return y.reshape(b_in, t_in, d).astype(x.dtype)


def total_aux_loss(intermediates) -> jax.Array:
    """Sum every sown ``aux_loss`` in an ``intermediates`` collection
    (sown values are tuples; scanned trunks stack them along depth).

    Filters by key path — only leaves under a dict key named ``aux_loss``
    count, so other sown intermediates (debug stats, activation probes)
    can never silently leak into the training objective via
    ``make_train_step(aux_loss_weight=...)``."""
    total = jnp.zeros((), jnp.float32)
    for path, leaf in jax.tree_util.tree_leaves_with_path(intermediates):
        if any(
            isinstance(k, jax.tree_util.DictKey) and k.key == "aux_loss"
            for k in path
        ):
            total = total + jnp.sum(leaf)
    return total
