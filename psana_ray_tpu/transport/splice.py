"""Kernel pass-through for payload bytes: sendfile spans + capability probe.

The brokered hot path's remaining Python-byte source (PERF_NOTES ISSUE
16) is the durable spill read: ``SegmentLog.read`` copies the payload
out of the mmap into interpreter-owned bytes just so the evloop can
hand them back to ``socket.sendmsg``. But the bytes at rest in a
segment ARE the wire payload (tag byte + record body, written verbatim
at append time) — the copy exists only because the write engine speaks
buffers. This module teaches it to speak FILE REGIONS instead:

- :class:`FileSpan` — a (fd, offset, nbytes) triple the evloop's write
  queue holds alongside ordinary buffers. The flush pump moves it with
  ``os.sendfile`` — payload bytes go mmap-page -> socket inside the
  kernel and never enter the interpreter; only the ~9-byte frame header
  stays Python. ``py_bytes_per_frame ~= 0`` on the spliced path, by
  construction, and the PR 16 cost model measures it.
- **capability probe** — ``os.sendfile`` is Linux/macOS/FreeBSD; exotic
  sockets (AF_UNIX on some kernels, TLS wrappers) refuse it at call
  time with ENOTSOCK/EINVAL. :func:`sendfile_capable` answers the
  startup question; a per-call refusal downgrades THAT span to the
  existing sendmsg scatter-gather path with a loud flight breadcrumb
  (``splice_fallback``) — degrade, never die.
- **MSG_ZEROCOPY** — probed (:func:`zerocopy_capable`) and reported in
  telemetry, but NOT wired into the pump: its completion notifications
  arrive on the socket error queue, and releasing a staging lease
  before the kernel is done with the pages would corrupt in-flight
  sends — the exact contract ``_out_releases`` exists to protect. The
  probe keeps the capability visible so a future PR can add errqueue
  reaping; sendfile needs no such dance (it copies into the socket
  buffer kernel-side, or pins the page cache itself).

Telemetry rides the obs registry as the ``splice`` source, mirroring
``wire_codec``: spliced frames/bytes, per-reason fallbacks, capability
flags. The flush pump joins the ``event-loop-blocking`` audited graph
(the checker roots at it): ``os.sendfile`` on a non-blocking socket
returns short or raises ``BlockingIOError`` — it never blocks the loop.
"""

from __future__ import annotations

import errno
import os
import socket
import threading
from typing import Dict, Optional

from psana_ray_tpu.obs.flight import FLIGHT

__all__ = [
    "FileSpan",
    "sendfile_capable",
    "zerocopy_capable",
    "probe_report",
    "SPLICE",
]

#: errnos that mean "this socket/fd pair can't splice" — downgrade the
#: span, keep the connection (anything else is a real send error and
#: propagates like a failed sendmsg)
_FALLBACK_ERRNOS = frozenset(
    getattr(errno, n) for n in ("EINVAL", "ENOSYS", "ENOTSOCK", "ENOTSUP", "EOPNOTSUPP", "EBADF")
    if hasattr(errno, n)
)


class FileSpan:
    """A payload region of an on-disk segment, queued for kernel-side
    transmission.

    Holds the segment's OPEN file object (not a dup'd fd): the span is
    only ever queued while its record sits in the durable queue's
    ``_outstanding`` table, which pins the commit floor below the
    record's offset, which blocks ``_maybe_recycle`` from retiring the
    segment — the file object outlives every queued span by contract
    (see ``storage/log.py``). ``advance`` mutates in place so the flush
    pump resumes a partial sendfile without re-queueing.
    """

    __slots__ = ("_file", "pos", "nbytes")

    def __init__(self, file, pos: int, nbytes: int):
        self._file = file
        self.pos = int(pos)
        self.nbytes = int(nbytes)

    def fileno(self) -> int:
        return self._file.fileno()

    def advance(self, sent: int) -> None:
        """Consume ``sent`` bytes off the front (partial sendfile)."""
        self.pos += sent
        self.nbytes -= sent

    def materialize(self) -> bytes:
        """The remaining span as interpreter bytes — the sendmsg
        fallback (one pread; no seek, so the segment's own file
        position is untouched). Counted against the wire copy counters:
        these are exactly the payload bytes the spliced path keeps out
        of the interpreter, and the cost model's ``py_bytes_per_frame``
        must see the downgrade."""
        buf = os.pread(self._file.fileno(), self.nbytes, self.pos)
        try:
            from psana_ray_tpu.utils.bufpool import WIRE

            WIRE.add(len(buf))
        except Exception:
            pass
        return buf

    def __repr__(self) -> str:  # debugging/flight only
        return f"FileSpan(fd={self._file.fileno()}, pos={self.pos}, nbytes={self.nbytes})"


class SpliceTelemetry:
    """Counters for the kernel pass-through path (obs source
    ``splice``). Single-writer per counter in practice (the evloop
    thread owns the pump) but lock-guarded anyway: fallbacks can be
    noted from open/encode paths too."""

    def __init__(self):
        self._lock = threading.Lock()
        self._registered = False
        self.spliced_frames = 0  # guarded-by: _lock
        self.spliced_bytes = 0  # guarded-by: _lock
        self.sendfile_calls = 0  # guarded-by: _lock
        self.fallbacks: Dict[str, int] = {}  # reason -> count  # guarded-by: _lock

    def ensure_registered(self):
        with self._lock:
            if self._registered:
                return
            self._registered = True
        try:
            from psana_ray_tpu.obs.registry import MetricsRegistry

            MetricsRegistry.default().register("splice", self)
        except Exception:  # obs optional: splice must work without it
            pass

    def note_sendfile(self, nbytes: int) -> None:
        with self._lock:
            self.spliced_bytes += nbytes
            self.sendfile_calls += 1

    def note_frame(self) -> None:
        with self._lock:
            self.spliced_frames += 1

    def note_fallback(self, reason: str) -> None:
        """Count a downgrade to the sendmsg path; the FIRST sight of
        each reason leaves a flight breadcrumb (loud once, a counter
        forever — the runbook's 'reading the fallback breadcrumb')."""
        with self._lock:
            first = reason not in self.fallbacks
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1
        if first:
            FLIGHT.record("splice_fallback", reason=reason)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "capable": 1 if sendfile_capable() else 0,
                "zerocopy_capable": 1 if zerocopy_capable() else 0,
                "spliced_frames_total": self.spliced_frames,
                "spliced_bytes_total": self.spliced_bytes,
                "sendfile_calls_total": self.sendfile_calls,
                "fallback_total": sum(self.fallbacks.values()),
            }
            for reason, n in self.fallbacks.items():
                out[f"fallback_{reason}_total"] = n
            return out


SPLICE = SpliceTelemetry()

_sendfile_capable: Optional[bool] = None
_zerocopy_capable: Optional[bool] = None


def sendfile_capable() -> bool:
    """Does this platform splice file->socket in the kernel? Answered
    once per process: ``os.sendfile`` exists AND works fd->fd here
    (probed with a real pipe-free socketpair + tempfile round trip —
    some platforms export the symbol but refuse sockets)."""
    global _sendfile_capable
    if _sendfile_capable is not None:
        return _sendfile_capable
    if not hasattr(os, "sendfile"):
        _sendfile_capable = False
        SPLICE.note_fallback("no_os_sendfile")
        return False
    try:
        import tempfile

        a, b = socket.socketpair()
        try:
            with tempfile.TemporaryFile() as f:
                f.write(b"probe")
                f.flush()
                # the kernel accepting all 5 bytes proves the fd pair
                # splices; no read-back needed (and none wanted — this
                # probe is reachable from telemetry snapshots, which
                # must never wait on a socket)
                _sendfile_capable = os.sendfile(a.fileno(), f.fileno(), 0, 5) == 5
        finally:
            a.close()
            b.close()
    except OSError:
        _sendfile_capable = False
    if not _sendfile_capable:
        SPLICE.note_fallback("probe_refused")
    return _sendfile_capable


def zerocopy_capable() -> bool:
    """MSG_ZEROCOPY support (Linux >= 4.14): probed for telemetry and
    the runbook, NOT used by the pump — see the module docstring for
    why (errqueue completions vs. the lease-release contract)."""
    global _zerocopy_capable
    if _zerocopy_capable is not None:
        return _zerocopy_capable
    if not (hasattr(socket, "SO_ZEROCOPY") and hasattr(socket, "MSG_ZEROCOPY")):
        _zerocopy_capable = False
        return False
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_ZEROCOPY, 1)
            _zerocopy_capable = True
        finally:
            s.close()
    except OSError:
        _zerocopy_capable = False
    return _zerocopy_capable


def fallback_errno(exc: OSError) -> bool:
    """Is this OSError a "can't splice HERE" refusal (downgrade the
    span) rather than a real send failure (kill the connection)?"""
    return exc.errno in _FALLBACK_ERRNOS


def probe_report() -> dict:
    """Startup-log summary (queue_server prints it once)."""
    return {
        "sendfile": sendfile_capable(),
        "msg_zerocopy": zerocopy_capable(),
    }
