"""Cross-host transport: a TCP queue server + client with the transport
contract.

The reference's cross-node data plane is Ray's object store + actor RPC
(SURVEY.md §5 "Distributed communication backend"). Here the cross-host
hop is an explicit length-prefixed TCP protocol over any local queue
(RingBuffer or ShmRingBuffer): producers on ingest nodes connect and PUT,
consumers on TPU hosts connect and GET. One server per queue — the same
single-serialization-point design as the reference's actor, without the
object-store copy.

Wire protocol (all little-endian):
    request:  op:u8 ('P'|'G'|'S'|'C') + [P only] len:u32 + payload
              'B' (get-batch) + max_items:u32
              'D' (get-batch, bounded server-side wait) + max_items:u32
                  + timeout_ms:u32 — the server blocks up to the timeout
                  (capped at ``_SERVER_WAIT_CAP_S``) for the FIRST item,
                  so a momentarily empty queue costs one round trip per
                  cap interval instead of one per client poll tick
              'Q' (put-batch) + count:u32 + count x (len:u32 + payload)
              'U' (put, bounded server-side wait) + timeout_ms:u32
                  + len:u32 + payload — the server blocks for queue
                  space up to the (capped) timeout before answering
                  '1'/'0', the producer-side mirror of 'D'
              'W' (windowed put) + seq:u64 + len:u32 + payload —
                  pipelined: the client does NOT wait for the response
                  before the next request; see streaming contract below
              'M' (stream subscribe) + credits:u32 — switch this
                  connection to server-push delivery; see below
              'K' (stream ack) + seq:u64 — cumulative consumption ack
                  on a streamed connection (credit replenish)
              'O' (open) + ns_len:u16 + ns + name_len:u16 + name
                         + maxsize:u32
              'T' (stats) — queue-health RPC: depth, high-water mark,
                  put/get counters, liveness ages of the bound queue
              'A' (anchor) — clock ping/anchor exchange (the stats RPC's
                  tracing sibling): client sends its wall:f64 + mono:f64,
                  server replies with its own pair; the client records
                  the exchange so the trace merge tool (obs.trace_merge)
                  can align this host's clock to the server's, bounded
                  by the measured RTT
              'N' (cluster/group RPC) + len:u32 + JSON — consumer-group
                  coordination (join/heartbeat/leave/drained/info against
                  the server's :class:`psana_ray_tpu.cluster.coordinator.
                  GroupRegistry`); by convention clients send it to the
                  FIRST server of the cluster address list
              'R' (replay-open) + from:u64 + group_len:u16 + group —
                  durable queues only (ISSUE 8): switch this
                  connection's READS to a non-destructive cursor over
                  the queue's retained segment-log range for the named
                  consumer group (live consumers undisturbed). ``from``
                  is an offset or a sentinel (u64 max = begin/earliest
                  retained, u64 max-1 = resume at the group's committed
                  offset). Subsequent G/B/D serve from the cursor;
                  delivered records are committed for the group at the
                  connection's implicit-ACK points, so crash-redelivery
                  is re-open at resume
              'J' (commit-offset) + offset:u64 + group_len:u16 + group —
                  durable queues only: persist the group's committed
                  offset (offset u64 max = "everything delivered to this
                  connection's replay cursor so far"); '0' when the
                  bound queue has no log
              'Z' (capability exchange) + len:u16 + comma-separated
                  entries — wire-compression negotiation (ISSUE 9) plus
                  per-connection capability FIELDS (ISSUE 12): plain
                  entries are codec names the client can decode, in
                  preference order; entries of the form ``key=value``
                  are capability fields (currently
                  ``tenant=<name>[:<weight>]`` — the tenant identity +
                  fair-share weight the event loop's weighted
                  deficit-round-robin stream pump serves this
                  connection under). The server picks the first codec
                  it also implements (or "none") and BOTH sides apply
                  it to frame payloads on THIS connection from the
                  next message on (payload tag 'C', transport/codec.py;
                  a frame that expands under the codec still ships raw
                  — compression is an encoding, never a requirement).
                  Servers predating a capability field ignore it (the
                  codec picker skips entries it does not recognize);
                  clients that never negotiate see byte-identical wire
                  traffic to pre-codec peers
              'H' (replica-subscribe) + ns_len:u16 + ns + name_len:u16
                  + name — replication (ISSUE 11): switch this
                  connection to REPLICA mode for the named queue's
                  replica log on a durable server. The response carries
                  the replica's current tail so the owner's shipper
                  resumes exactly there; from here the connection
                  carries only 'V' appends and 'F'. '0' when this
                  server cannot host the replica (no --durable_dir, the
                  queue is mounted live here, or the replica was
                  already promoted — the fencing answer a zombie owner
                  sees after a failover)
              'V' (replica-append) + offset:u64 + floor:u64 + len:u32
                  + payload — one chain-replicated record at an
                  explicit log offset (the owner's offset space is
                  mirrored verbatim; divergence reconciles by
                  truncate-to-offset, gaps by reset — both
                  breadcrumbed). ``floor`` piggybacks the owner's live
                  committed offset (u64 max = none) so a promoted
                  replica re-exposes only the unacked window.
                  Windowed like 'W': the owner pipelines appends and
                  reads cumulative '1'+offset acks; the acked offset IS
                  the replicated ack floor gating producer acks on the
                  owner. 'E' = refused (promoted/fenced or disk fault)
              'Y' (promote) + ns_len:u16 + ns + name_len:u16 + name —
                  failover: finalize the named replica log on this
                  server (fence further 'V' appends, flush, release the
                  mapping) so the next OPEN mounts it as the LIVE
                  durable queue, serving the replicated backlog and
                  retained range. Answers the retained range; '0' when
                  no replica exists here (the queue starts empty)
              'F' (bye) — no response; acks the last delivery and ends
                  the connection cleanly (see delivery contract below)
    response: status:u8 ('1' ok | '0' full/empty | 'X' closed | 'E' error)
              + [G ok] len:u32 + payload   + [S] size:u32
              + [B/D ok] count:u32 + count x (len:u32 + payload)
              + [Q ok] accepted:u32
              + [W ok] seq:u64 (the acknowledged put's sequence number)
              + [T ok] len:u32 + JSON stats object
              + [A ok] wall:f64 + mono:f64
              + [N ok] len:u32 + JSON group-state object
              + [R ok] start:u64 + end:u64 (resolved cursor start and
                the log tail at open time; the cursor follows the tail)
              + [Z ok] len:u16 + chosen codec name ("none" = stay raw)
              + [H ok] tail:u64 (the replica log's next offset)
              + [V ok] offset:u64 (cumulative replicated-ack floor)
              + [Y ok] start:u64 + end:u64 (the promoted retained range)
    stream push (server -> client, after 'M'):
              status:u8 ('1') + seq:u64 + len:u32 + payload per frame;
              'X' when the bound queue closes (the stream is over)

Delivery contract (PART OF THE WIRE PROTOCOL, not a server detail): the
server holds each GET/B/D delivery as in-flight until the SAME
connection's next opcode arrives (implicit ACK — a client can only send
its next request after fully reading the previous response) or BYE acks
it on clean disconnect. This assumes ONE outstanding request per
connection: a pipelining client that sends request N+1 before reading
response N would silently forfeit in-flight protection (the early opcode
acks a delivery the client has not read). Duplicates are therefore
possible on crash/retry (at-least-once), silent loss is not. Duplicated
control records are benign: EndOfStream markers tally idempotently
(coverage is keyed by ``producer_rank`` —
:class:`psana_ray_tpu.records.EosTally`), and FrameRecord duplicates
carry their ``(shard_rank, event_idx)`` provenance for downstream dedup.

Streaming contract (ISSUE 5): the request/response exchange above pays
one full RTT per round trip under exactly one outstanding request, so on
any real link throughput is RTT-bound (~1/RTT frames/s/connection at
queue-limited batch sizes). Two connection modes deliberately REPLACE
the implicit next-request ACK with explicit sequence/credit ACKs so the
link can stay full of in-flight work:

- ``STREAM`` ('M'): the client subscribes with an initial credit count
  W; the server pushes queued frames as they arrive — scatter-gather,
  straight from the queued record's pooled lease — tagging each with a
  per-connection sequence number and decrementing credits, and blocks
  once W pushes are unacknowledged. The client replenishes credits with
  cumulative 'K' acks as it CONSUMES (it acks everything previously
  returned when it comes back for more — the same point the implicit
  ACK fired in request/response mode), so the credit window bounds
  client-side memory exactly like a prefetch depth. Pushed-but-unacked
  frames are held server-side and RE-ENQUEUED (head placement) when the
  connection dies — at-least-once crash-redelivery, exactly as
  in-flight GETs. A streamed connection carries ONLY pushes downstream
  and 'K'/'F' upstream — plus 'M' again as a live credit-window RESIZE
  (ISSUE 15 autotune: the budget shifts in place, no response, seq
  state untouched); any other opcode on it is a protocol error.
- windowed PUT ('W'): up to W sequence-numbered puts in flight before
  the client blocks reading statuses. The server enqueues each (waiting
  for space — backpressure arrives as delayed acks) and answers
  '1'+seq. On reconnect the client resends the entire unacknowledged
  tail, in order, before anything else touches the fresh connection —
  duplicates possible, holes never.

Client threading: :class:`TcpQueueClient` serializes every exchange under
one lock, satisfying the one-outstanding-request rule; during an outage a
reconnecting call holds that lock through the backoff cycle, so OTHER
threads sharing the client (e.g. a monitor calling ``size()``) block for
up to the full reconnect envelope — use one client per thread where that
matters.

The batch opcodes exist so a cross-host consumer drains N records per
round trip instead of reintroducing the reference's one-RPC-per-event
bottleneck (reference ``data_reader.py:35``, SURVEY.md §3.1) over the
network hop.

The OPEN opcode makes one server a *cluster registry of named queues* —
Ray-GCS parity for the only transport that crosses hosts (reference
``shared_queue.py:33-38`` registers the actor by (namespace, name);
``data_reader.py:20`` resolves it the same way). OPEN get-or-creates the
(namespace, queue_name) queue server-side and binds this connection to
it; connections that never send OPEN use the server's default queue
(back-compat with single-queue deployments). Named queues are detached:
they live until the server process stops, regardless of which client
created them (parity: ``lifetime="detached"``, ``shared_queue.py:35``).

Payloads reuse the shm codec (records wire format / tagged pickle).

Zero-copy datapath (ISSUE 2): frame payloads are never materialized as
fresh bytes on either side of the socket. Sends go out via
``socket.sendmsg`` scatter-gather straight from the record's panel
memory (``FrameRecord.wire_parts``); receives land via ``recv_into`` in
recycled leases from the process :class:`~psana_ray_tpu.utils.bufpool.
BufferPool` and decode as VIEWS of that memory, with the lease riding
the record until the payload is copied onward (``FrameBatcher.
push_view``) or the record dies. The server's relay path is therefore
alloc-free and copy-free per brokered frame at steady state: a PUT's
pooled buffer is the very memory a later GET response streams from.
This composes with the delivery contract below — an in-flight record's
lease is released only when the record itself is dropped after the
implicit ACK (or re-enqueued intact on connection death), never while
redelivery could still need the payload.

In-flight items are never dropped on a consumer crash: if the connection
dies between the queue pop and the response write, the server re-enqueues
the popped item(s).

Server architecture (ISSUE 6): the server IS a single selectors/epoll
readiness loop (:mod:`psana_ray_tpu.transport.evloop`) driving a
per-connection state machine over all 22 opcodes — memory O(connections
x small struct), thread count independent of connection count, blocking
waits ('W'/'U'/'D', stream credit stalls) held as timer/deferred-
callback state instead of parked threads. The legacy thread-per-
connection implementation was retained one release behind
``mode="threads"`` and has been REMOVED (ISSUE 7); the wire bytes and
delivery contract are pinned by test_wire_zero_copy / test_tcp /
test_tcp_stream and the wire-opcode checker. This module keeps the
protocol definition (opcode constants, framing helpers) and the client.

Cluster (ISSUE 7): N servers become one logical queue service through
:mod:`psana_ray_tpu.cluster` — a logical queue shards into partitions,
each an ordinary named queue here (``<queue>#p<N>`` via OPEN), placed by
rendezvous hashing over the live server set; :class:`psana_ray_tpu.
cluster.client.ClusterClient` wraps one TcpQueueClient per partition
and presents this module's transport contract unchanged.
"""

from __future__ import annotations

import json
import random
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, List, Optional

from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.obs.profiling.stagetag import TAG_ENQUEUE, set_stage, swap_stage
from psana_ray_tpu.obs.stages import HOP_ENQ, STAGE_QUEUE_DWELL
from psana_ray_tpu.obs.tracing import SPAN_RELAY, TRACER
from psana_ray_tpu.records import mark_hop
from psana_ray_tpu.transport.registry import TransportClosed
from psana_ray_tpu.transport.ring import EMPTY, RingBuffer
from psana_ray_tpu.transport.codec import (
    CODEC_NONE,
    CODEC_STATS,
    available_codecs,
    decode_payload as _decode,
    encode_for_wire as _wire_encode,
    get_codec,
    payload_nbytes as _parts_nbytes,
)
from psana_ray_tpu.utils.bufpool import BufferPool
from psana_ray_tpu.utils.metrics import probe_queue_stats

_OP_PUT = b"P"
_OP_GET = b"G"
_OP_SIZE = b"S"
_OP_CLOSE = b"C"
_OP_GET_BATCH = b"B"
_OP_GET_BATCH_WAIT = b"D"
_OP_PUT_BATCH = b"Q"
_OP_PUT_WAIT = b"U"
_OP_PUT_SEQ = b"W"
_OP_STREAM = b"M"
_OP_STREAM_ACK = b"K"
_OP_OPEN = b"O"
_OP_STATS = b"T"
_OP_ANCHOR = b"A"
_OP_CLUSTER = b"N"
_OP_REPLAY = b"R"
_OP_COMMIT = b"J"
_OP_CODEC = b"Z"
_OP_REPL_OPEN = b"H"
_OP_REPL_APPEND = b"V"
_OP_PROMOTE = b"Y"
_OP_BYE = b"F"
_ST_OK = b"1"
_ST_NO = b"0"
_ST_CLOSED = b"X"
_ST_ERR = b"E"
# 'V' replica-append floor field sentinel: no committed floor to
# piggyback (nothing consumed on the owner yet)
_REPL_NO_FLOOR = (1 << 64) - 1

# The longest one bounded-wait request ('D'/'U' timeout field) may defer
# server-side: long enough that an idle consumer costs ~one round trip
# per interval, short enough that drain/shutdown and connection-death
# detection stay timely.
_SERVER_WAIT_CAP_S = 2.0
# default credit window (frames in flight) for stream subscriptions and
# the windowed-put pipeline — bounds client memory like a prefetch depth
DEFAULT_STREAM_WINDOW = 32


class StreamTelemetry:
    """Credit/in-flight-window accounting for the streaming transport
    (obs source ``stream``): how full the credit windows run, how much
    sits unacknowledged, and how often crash-redelivery fired. One
    process-wide instance (:data:`STREAM`), registered in the default
    MetricsRegistry on first streaming use."""

    def __init__(self):
        self._lock = threading.Lock()
        self._registered = False  # guarded-by: _lock
        self.streams_opened = 0  # guarded-by: _lock
        self.frames_pushed = 0  # guarded-by: _lock
        self.acks = 0  # ack messages seen (client+server side)  # guarded-by: _lock
        self.redelivered = 0  # frames requeued off dead streams  # guarded-by: _lock
        self.inflight = 0  # pushed-not-yet-acked, all server streams  # guarded-by: _lock
        self.inflight_peak = 0  # guarded-by: _lock
        self.credit_window = 0  # sum of active subscriptions' windows  # guarded-by: _lock
        self.put_window_depth = 0  # client-side unacked windowed puts  # guarded-by: _lock
        self.put_window_peak = 0  # guarded-by: _lock
        self.put_resent = 0  # windowed puts resent after reconnect  # guarded-by: _lock

    def ensure_registered(self):
        with self._lock:
            if self._registered:
                return
            self._registered = True
        try:
            from psana_ray_tpu.obs import MetricsRegistry

            MetricsRegistry.default().register("stream", self)
        except Exception:  # obs optional: transport must work without it
            pass

    def opened(self, window: int):
        self.ensure_registered()
        with self._lock:
            self.streams_opened += 1
            self.credit_window += window

    def closed(self, window: int):
        with self._lock:
            self.credit_window -= window

    def resized(self, old: int, new: int):
        """Live credit-window resize (ISSUE 15 autotune): adjust the
        aggregate gauge without counting a new subscription."""
        with self._lock:
            self.credit_window += new - old

    def pushed(self, n: int):
        with self._lock:
            self.frames_pushed += n
            self.inflight += n
            if self.inflight > self.inflight_peak:
                self.inflight_peak = self.inflight

    def pruned(self, n: int):
        with self._lock:
            self.inflight -= n

    def acked_msg(self):
        with self._lock:
            self.acks += 1

    def redelivered_n(self, n: int):
        with self._lock:
            self.redelivered += n

    def put_depth(self, depth: int):
        self.ensure_registered()
        with self._lock:
            self.put_window_depth = depth
            if depth > self.put_window_peak:
                self.put_window_peak = depth

    def resent(self, n: int):
        with self._lock:
            self.put_resent += n

    def stats(self) -> dict:
        with self._lock:
            return {
                "streams_opened": self.streams_opened,
                "frames_pushed_total": self.frames_pushed,
                "acks_total": self.acks,
                "redelivered_total": self.redelivered,
                "inflight": self.inflight,
                "inflight_peak": self.inflight_peak,
                "credit_window": self.credit_window,
                "put_window_depth": self.put_window_depth,
                "put_window_peak": self.put_window_peak,
                "put_resent_total": self.put_resent,
            }

    # obs registry source protocol
    def snapshot(self) -> dict:
        return self.stats()


STREAM = StreamTelemetry()



def _queue_stats_payload(queue) -> dict:
    """JSON-safe stats for any backing queue: full ``stats()`` when the
    backing provides it (RingBuffer, ShmRingBuffer), depth-only otherwise.
    A dead queue reports ``closed`` instead of erroring the whole RPC."""
    try:
        return probe_queue_stats(queue)
    except TransportClosed:
        return {"closed": True}
    except Exception as e:  # noqa: BLE001 — stats must not kill serving
        return {"error": repr(e)}


def _recv_into(sock: socket.socket, mv: memoryview) -> None:
    """Fill ``mv`` exactly from ``sock`` with ``recv_into`` — the wire
    payload lands in caller-owned (pooled) memory with ZERO intermediate
    bytes objects and linear cost. THE one receive primitive of this
    module: every read, control or payload, goes through here."""
    got = 0
    n = len(mv)
    while got < n:
        k = sock.recv_into(mv[got:])
        if not k:
            raise ConnectionError("peer closed")
        got += k


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Exactly ``n`` bytes for CONTROL fields (opcodes, lengths — a few
    bytes). Frame payloads must use :func:`_recv_into` on a pooled
    buffer instead. Linear: fills one preallocated buffer in place (the
    old chunked ``recv()`` + accumulate pattern re-copied the prefix on
    every chunk)."""
    buf = bytearray(n)
    _recv_into(sock, memoryview(buf))
    return bytes(buf)


# sendmsg scatter-gather: bounded iovec count per call (Linux IOV_MAX is
# 1024; staying far below keeps each call cheap to assemble) with partial
# sends resumed mid-part. Falls back to sendall-per-part where sendmsg is
# unavailable (non-POSIX).
_SENDMSG_IOV = 64
# consecutive parts at or below this size are joined before sending:
# copying a run of few-byte control fields (opcodes, lengths, record
# headers) is free and keeps the iovec count low for small-record
# batches, while frame payloads above it always pass through zero-copy
_COALESCE_MAX = 4096


def _gather_parts(parts) -> List[memoryview]:
    """Normalize a scatter-gather part list for sending: empty parts are
    dropped, runs of tiny control parts (opcodes, lengths, record
    headers) are coalesced up to ``_COALESCE_MAX``, frame-sized payloads
    pass through as zero-copy memoryviews. Shared by the blocking
    :func:`_sendmsg_all` sender and the event-loop server's non-blocking
    outbound write queue (:mod:`psana_ray_tpu.transport.evloop`), so the
    bytes on the wire are identical in both modes."""
    bufs: List[memoryview] = []
    small: List[memoryview] = []

    def _flush_small():
        if not small:
            return
        bufs.append(small[0] if len(small) == 1 else memoryview(b"".join(small)))
        small.clear()

    for p in parts:
        m = p if isinstance(p, memoryview) else memoryview(p)
        if not m.nbytes:
            continue
        if m.nbytes <= _COALESCE_MAX:
            small.append(m)
            if sum(s.nbytes for s in small) >= _COALESCE_MAX:
                _flush_small()
        else:
            _flush_small()
            bufs.append(m)
    _flush_small()
    return bufs


def _sendmsg_all(sock: socket.socket, parts) -> None:
    """Send every buffer in ``parts`` without concatenating the large
    ones — the scatter-gather complement of :func:`_recv_into`. A 4.3 MB
    frame goes from the record's own panel memory to the kernel in one
    hop; the old ``b"".join`` path paid a frame-sized copy per message.
    Runs of tiny control parts are coalesced (see ``_COALESCE_MAX``)."""
    bufs = _gather_parts(parts)
    if not hasattr(sock, "sendmsg"):  # platform fallback: copy-free per part
        for m in bufs:
            sock.sendall(m)
        return
    i = 0
    while i < len(bufs):
        sent = sock.sendmsg(bufs[i : i + _SENDMSG_IOV])
        if sent <= 0:
            raise ConnectionError("peer closed during sendmsg")
        while sent > 0:
            m = bufs[i]
            if sent >= m.nbytes:
                sent -= m.nbytes
                i += 1
            else:
                bufs[i] = m[sent:]
                sent = 0


# Upper bound on one tagged payload (u32 on the wire allows 4 GiB): a
# corrupt or hostile length field must not size a pool lease — the
# largest real frame (jungfrau4M f64) is ~67 MB, so 256 MB is generous.
# Oversized lengths surface as ConnectionError so the server's in-flight
# requeue path runs (the stream is desynced; the connection must die).
_MAX_PAYLOAD = 256 * 1024 * 1024


def _recv_payload(sock: socket.socket, n: int, pool: BufferPool):
    """Receive an ``n``-byte tagged payload into a pooled buffer and
    decode it. Frame records come back ZERO-COPY (panels view the pooled
    buffer, lease attached — see records.decode); other payloads release
    the lease at decode. On any failure the lease goes straight back."""
    if n > _MAX_PAYLOAD:
        raise ConnectionError(
            f"payload length {n} exceeds wire maximum {_MAX_PAYLOAD}"
        )
    lease = pool.lease(n)
    try:
        _recv_into(sock, lease.mv)
        return _decode(lease.mv, lease=lease)
    except BaseException:
        lease.release()  # idempotent: double-release after decode is safe
        raise


# -- relay-side tracing (sampled frames only; gated on TRACER.enabled) ----
def _stamp_relay_arrival(item) -> None:
    """Mark a sampled frame's arrival at the relay (server PUT decode) —
    the start of its queue-dwell span. The stamp lives in the record's
    process-local hops dict, which survives the in-memory queue hop to
    the GET that delivers it (shm-backed queues re-encode and lose it;
    the merge timeline shows dwell as the producer->consumer gap there)."""
    trace = getattr(item, "trace", None)
    if trace is not None and trace.sampled:
        mark_hop(item, HOP_ENQ)


def _emit_relay_spans(items, t_send0: float) -> None:
    """After a GET/B response went out: per sampled frame, a
    ``queue_dwell`` span (relay arrival -> response start) and a
    ``relay`` span (response serialization + send)."""
    t_done = time.monotonic()
    for item in items:
        trace = getattr(item, "trace", None)
        if trace is None or not trace.sampled:
            continue
        hops = getattr(item, "hops", None)
        t_arrived = hops.get(HOP_ENQ) if hops else None
        if t_arrived is not None:
            TRACER.span(trace.trace_id, STAGE_QUEUE_DWELL, t_arrived, t_send0)
        TRACER.span(trace.trace_id, SPAN_RELAY, t_send0, t_done)


# -- server mode -----------------------------------------------------------
# "evloop" is THE server: ONE selectors/epoll readiness loop serves
# every connection through per-connection state machines — O(connections
# x small struct) memory, thread count independent of connection count
# (ISSUE 6; implementation in transport/evloop.py). The legacy
# thread-per-connection mode ("threads") was retained one release behind
# this knob and removed in ISSUE 7.
DEFAULT_SERVER_MODE = "evloop"
_SERVER_MODES = ("evloop",)


def _resolve_server_mode(mode: Optional[str]) -> str:
    import os

    m = mode or os.environ.get("PSANA_TCP_SERVER_MODE") or DEFAULT_SERVER_MODE
    if m not in _SERVER_MODES:
        raise ValueError(
            f"unknown server mode {m!r}; expected one of {_SERVER_MODES} "
            f"(the legacy thread-per-connection mode was removed one "
            f"release after the event-loop server became the default)"
        )
    return m


def _refuse_conn(conn: socket.socket, port: int, active: int, limit: int):
    """Admission control: accept-then-refuse with a clean ``_ST_ERR``
    payload instead of letting an accept storm OOM the relay. The
    refused client's next ``_status()`` read surfaces it as a protocol
    error immediately (no hang, no half-open connection)."""
    FLIGHT.record("conn_refused", port=port, active=active, max_conns=limit)
    try:
        conn.send(_ST_ERR)
    except OSError:
        pass
    try:
        conn.close()
    except OSError:
        pass


class TcpQueueServer:
    """Serve queues over TCP: one default queue plus any number of named
    queues that clients OPEN by (namespace, queue_name) — see the module
    docstring. Start with ``serve_background()``.

    The serving architecture is one epoll readiness loop with
    per-connection state machines for all 22 opcodes, blocking waits as
    timer/deferred state (:mod:`psana_ray_tpu.transport.evloop`) —
    scales to thousands of streamed subscribers with O(1) threads. The
    legacy thread-per-connection mode was removed (ISSUE 7); ``mode``
    remains as a guard that rejects anything but ``"evloop"``.

    ``max_conns`` (0 = unlimited) refuses connections past the limit
    with a clean ``_ST_ERR`` instead of accepting unboundedly. The
    server also hosts the cluster consumer-group coordinator state
    (``groups`` — :class:`psana_ray_tpu.cluster.coordinator.
    GroupRegistry`) behind the 'N' RPC; it is inert unless a cluster
    client elects this server as its coordinator."""

    def __init__(
        self,
        queue=None,
        host: str = "0.0.0.0",
        port: int = 0,
        maxsize: int = 100,
        queue_factory=None,
        pool: Optional[BufferPool] = None,
        mode: Optional[str] = None,
        max_conns: int = 0,
        group_store_path: Optional[str] = None,
        replication=None,
        reuseport: bool = False,
        worker_ctx=None,
    ):
        self.queue = queue if queue is not None else RingBuffer(maxsize)
        # multi-process data plane (ISSUE 17): a transport.workers.
        # WorkerContext makes this server ONE of N forked evloop workers
        # sharing the port via SO_REUSEPORT — the loop registers its
        # adoption socket and routes queue ops to partition owners over
        # SCM_RIGHTS fd migration. None = classic single-process server.
        self.worker_ctx = worker_ctx
        self._maxsize = maxsize
        # recv-buffer pool for the relay path: every PUT payload lands in
        # a recycled lease and is decoded zero-copy, so a brokered frame
        # costs no allocation per hop (the lease returns to the pool when
        # the frame's delivery is acknowledged and the record dies)
        self._pool = pool if pool is not None else BufferPool.default()
        # factory for OPENed queues: (namespace, name, maxsize) -> queue.
        # Default in-process rings; a server may hand out shm-backed rings
        # instead so local clients can bypass TCP (queue_server.py --shm)
        self._queue_factory = queue_factory or (
            lambda ns, name, maxsize: RingBuffer(maxsize, name=f"{ns}__{name}")
        )
        self._queues = {}  # (namespace, name) -> queue  # guarded-by: _queues_lock
        self._queues_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuseport:
            # N worker processes each bind their own listener to the
            # SAME port; the kernel shards incoming CONNECTIONS across
            # them (queue partitioning is the workers' fd-migration
            # job, not the kernel's)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._draining = False
        self._threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []  # guarded-by: _conns_lock
        self._conns_lock = threading.Lock()
        self.mode = _resolve_server_mode(mode)
        self.max_conns = int(max_conns)
        self._loop = None  # the EventLoop driving this server
        # consumer-group coordinator state (cluster 'N' RPC). Imported
        # lazily: psana_ray_tpu.cluster's client half imports this module.
        # With a store path (queue_server --durable_dir) the control
        # state snapshots to disk and a coordinator restart recovers
        # groups instead of emptying them (ISSUE 8).
        from psana_ray_tpu.cluster.coordinator import GroupRegistry

        self.groups = GroupRegistry(store_path=group_store_path)
        # chain replication (ISSUE 11): a cluster.replication.
        # ReplicationManager makes this server BOTH an owner that ships
        # its durable queues' segment logs to their follower ('V' over a
        # dedicated link, producer acks gated on the replicated floor)
        # AND a follower hosting passive replica logs ('H'/'V' inbound,
        # 'Y' promote on failover) — None = unreplicated, zero new cost
        self.replication = replication
        if replication is not None:
            replication.attach(self)

    def open_named(self, namespace: str, queue_name: str, maxsize: Optional[int] = None):
        """Get-or-create the named queue (the OPEN opcode server-side;
        also callable in-process, e.g. for a host-local consumer of a
        queue remote producers feed over TCP)."""
        key = (namespace, queue_name)
        with self._queues_lock:
            q = self._queues.get(key)
            if q is None:
                if self.replication is not None:
                    # an OPEN of a queue this server holds a REPLICA of
                    # is a failover landing here: finalize the replica
                    # log first (fence + unmap) so the durable factory's
                    # recovery scan mounts the replicated backlog —
                    # defense in depth behind the explicit 'Y' promote
                    self.replication.ensure_promoted(namespace, queue_name)
                q = self._queue_factory(namespace, queue_name, maxsize or self._maxsize)
                self._queues[key] = q
                if self.replication is not None:
                    # owner half: if this server is in the partition's
                    # chain with a next link, start shipping its log
                    self.replication.queue_mounted(namespace, queue_name, q)
                FLIGHT.record("queue_opened", namespace=namespace, name=queue_name)
            return q

    def has_named_queue(self, namespace: str, queue_name: str) -> bool:
        """Is ``(namespace, queue_name)`` mounted LIVE here? (The
        replica-subscribe refusal check: a server never hosts a passive
        replica of a queue it is serving.)"""
        with self._queues_lock:
            return (namespace, queue_name) in self._queues

    def named_queues(self) -> List[tuple]:
        with self._queues_lock:
            return sorted(self._queues)

    def queues_by_name(self) -> dict:
        """``{label: queue}`` over the default + every named queue —
        the stall detector's dynamic watch population (labels are
        ``default`` and ``<namespace>/<queue_name>``)."""
        with self._queues_lock:
            out = {f"{ns}/{nm}": q for (ns, nm), q in self._queues.items()}
        out["default"] = self.queue
        return out

    def stats_all(self) -> dict:
        """``{label: stats dict}`` for every queue — the server's
        registry source (``--metrics_port`` on queue_server)."""
        out = {}
        for label, q in self.queues_by_name().items():
            out[label] = _queue_stats_payload(q)
        return out

    def all_queues(self) -> List[Any]:
        with self._queues_lock:  # snapshot: OPENs race with shutdown
            return [self.queue, *self._queues.values()]

    def begin_drain(self):
        """Stop accepting PUTs on every queue (producers see the dead-queue
        signal and exit cleanly) while GETs keep serving — the graceful
        half of teardown: consumers drain in-flight frames instead of
        losing them to an abrupt ``close_all`` (the reference's ``ray
        stop`` kills the actor with whatever the deque still holds).
        Propagates to the backing queues themselves so producers that
        BYPASS TCP (shm-backed deployments, queue_server --shm) are
        refused too, not just the ones speaking the wire protocol."""
        FLIGHT.record("begin_drain", port=self.port)
        self._draining = True
        for q in self.all_queues():
            drain = getattr(q, "begin_drain", None)
            if drain is not None:
                try:
                    drain()
                except Exception:
                    pass

    @property
    def draining(self) -> bool:
        return self._draining

    def depth(self) -> int:
        """Total items still queued across the default + named queues."""
        total = 0
        for q in self.all_queues():
            try:
                total += q.size()
            except Exception:
                pass
        return total

    def close_all(self):
        """Close the default + every named queue (server teardown: every
        blocked client must observe a dead transport, ``ray stop`` parity)."""
        FLIGHT.record("close_all", port=self.port)
        for q in self.all_queues():
            try:
                q.close()
            except Exception:
                pass

    def serve_background(self) -> "TcpQueueServer":
        from psana_ray_tpu.transport.evloop import EventLoop

        self._loop = EventLoop(self)
        t = threading.Thread(
            target=self._loop.run, daemon=True, name="tcp-evloop"
        )
        t.start()
        self._accept_thread = t
        self._threads.append(t)
        return self

    def _requeue(self, queue, items):
        """Put back items popped but never delivered (the client connection
        died mid-response) via the shared recovery path: queue HEAD so they
        precede any EOS markers already enqueued (a tally-driven consumer
        would otherwise stop without reading them), timed tail retries with
        a logged drop for backings without ``put_front`` (shm ring)."""
        from psana_ray_tpu.transport.recovery import return_to_queue

        if items:
            FLIGHT.record("requeue_in_flight", count=len(items))
        return_to_queue(queue, items, what="in-flight frame")

    def shutdown(self):
        self._stop.set()
        # evloop mode: kick the selector out of its wait so _stop is
        # observed immediately (no 0.2 s poll to lean on)
        if self._loop is not None:
            self._loop.wake()
        # join the accept loop BEFORE closing: a thread blocked inside
        # accept() keeps the listening socket alive past close(), so a
        # supervisor rebinding the same port immediately would race it
        # (the loop polls _stop every 0.2 s)
        t = getattr(self, "_accept_thread", None)
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        if self.replication is not None:
            # stop the shipping senders + coordinator sync and unmap the
            # replica logs AFTER the loop is down (no more 'V' appends)
            self.replication.shutdown()
        try:
            self._sock.close()
        except OSError:
            pass
        # close accepted connections too: an ESTABLISHED conn keeps the
        # port busy and would block a supervisor restarting the service on
        # the same address (clients reconnect-with-backoff and re-dial it)
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            # SHUT_RDWR first: close() alone does not interrupt a serve
            # thread blocked in recv() (the kernel file description stays
            # alive), which would leave a zombie thread answering a client
            # that should be reconnecting to the supervisor's new server
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class TcpQueueClient:
    """Client with the transport contract (put/get/size/get_wait/...).

    Transient connection failures (network blip, server restart under a
    supervisor) are RECONNECTED with exponential backoff and the
    interrupted operation retried once on the fresh connection — a named
    binding (OPEN) is replayed first, so the client lands on the same
    (namespace, queue_name) queue. Delivery across failures is
    AT-LEAST-ONCE, never silent loss: the server holds popped items as
    in-flight until the client's next request implicitly acknowledges the
    response (or BYE does, on clean disconnect), and re-enqueues them
    when the connection dies first — so a retried GET re-reads anything
    the dead connection had in the air, and a crashed client's unacked
    frames go to another consumer (possibly twice; records carry
    ``(shard_rank, event_idx)`` provenance for downstream dedup, and
    producer PUT retries are at-least-once the same way). Only RAW socket
    failures reconnect; an explicit server refusal (closed/draining
    queue) is a protocol answer, not an outage.

    A server that stays dead through every reconnect attempt surfaces as
    :class:`TransportClosed` from every contract method — the same signal
    a gracefully closed queue sends — so consumers' dead-transport
    handling (``DataReaderError``, batcher tail-flush) works for both
    (parity role: ``RayActorError``, reference ``data_reader.py:36-37``)."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 30.0,
        namespace: Optional[str] = None,
        queue_name: Optional[str] = None,
        maxsize: int = 0,
        reconnect_tries: int = 4,
        reconnect_base_s: float = 0.5,
        pool: Optional[BufferPool] = None,
        put_window: int = DEFAULT_STREAM_WINDOW,
        codec: Optional[str] = None,
        tenant: Optional[str] = None,
        tenant_weight: int = 1,
    ):
        """``codec`` opts this connection into wire compression (ISSUE
        9): ``"auto"`` (ISSUE 15) DECIDES per connection from a brief
        link-rate probe at connect — compression on when the measured
        link is slower than the codec break-even rate (tunnels), off on
        fast LANs where the codec only burns CPU — re-decided on every
        reconnect, with a ``codec_auto_decision`` flight breadcrumb
        either way; a name (or comma list) advertises exactly those;
        None/"none" (the default) skips negotiation entirely — wire
        bytes stay byte-identical to pre-codec clients. The SERVER
        picks the codec (opcode 'Z'); an old server that answers the
        opcode with a protocol error degrades this client to
        uncompressed, loudly (flight breadcrumb), not fatally.

        ``tenant`` (ISSUE 12) names this connection's fair-share tenant
        and ``tenant_weight`` (1-64) its weight; both ride the same 'Z'
        capability exchange as ``key=value`` entries, so a tenant hello
        costs zero new opcodes and an old server that refuses 'Z'
        degrades the hello away with the codec (the connection then
        serves under the default tenant, loudly breadcrumbed, never
        fatally)."""
        self.host, self.port = host, port
        self._timeout_s = timeout_s
        # pooled receive staging: GET/B payloads land via recv_into in
        # recycled leases and decode zero-copy (consumer-side copy count
        # drops to the single batch-arena copy; see FrameBatcher.push_view)
        self._pool = pool if pool is not None else BufferPool.default()
        self._reconnect_tries = reconnect_tries
        self._reconnect_base_s = reconnect_base_s
        self._binding: Optional[tuple] = None  # (ns, name, maxsize) to replay
        # durable replay subscription to re-establish on reconnect:
        # (position sentinel, group) — always RESUME, so the server's
        # committed offset carries the position across drops
        self._replay_args: Optional[tuple] = None
        self._lock = threading.Lock()
        # streaming / windowed-put state — initialized BEFORE the dial so
        # _reconnect (reachable from __init__) can consult it safely.
        # _stream: once subscribed, this connection carries only pushes
        # and acks; request/response ops route to a lazy side channel.
        self._stream: Optional["TcpStreamReader"] = None
        self._side: Optional["TcpQueueClient"] = None
        # windowed pipelined PUT: monotonically numbered, unacked tail
        # kept for resend-on-reconnect (duplicates possible, holes never)
        self._put_seq = 0  # guarded-by: _lock
        self._put_unacked: deque = deque()  # (seq, item)  # guarded-by: _lock
        self._put_window = max(1, int(put_window))
        # wire compression (ISSUE 9): the advertised codec list, the
        # NEGOTIATED codec object (None = uncompressed), and the
        # old-peer latch that stops renegotiation storms on reconnect
        self._codec_arg = codec
        self._codec_names: Optional[List[str]] = None
        # "auto" (ISSUE 15, the parked ISSUE 9 follow-up): the codec is
        # DECIDED at connect from a brief link-rate probe — off on fast
        # LANs where the codec CPU only costs, on through slow tunnels
        # where the bandwidth win dominates — and RE-DECIDED on every
        # reconnect (the link may have changed). Explicit names still
        # mean exactly what they say.
        self._codec_auto = codec == "auto"
        if codec and codec != CODEC_NONE and not self._codec_auto:
            names = [n.strip() for n in codec.split(",") if n.strip()]
            for n in names:
                get_codec(n)  # fail fast on unknown names
            self._codec_names = names
        self._codec = None  # guarded-by: _lock
        self._codec_refused = False  # guarded-by: _lock
        # tenant hello (ISSUE 12): capability fields appended to the 'Z'
        # advert. Validated here so a malformed name fails fast instead
        # of desyncing the comma-separated wire list.
        self._hello_fields: List[str] = []
        if tenant is not None:
            if not tenant or any(c in tenant for c in ",=:\n"):
                raise ValueError(
                    f"tenant name {tenant!r} may not be empty or contain "
                    f"',' '=' ':' or newlines (it rides a comma-separated "
                    f"capability list)"
                )
            w = int(tenant_weight)
            if not 1 <= w <= 64:
                raise ValueError(
                    f"tenant_weight must be in [1, 64], got {tenant_weight}"
                )
            self._hello_fields.append(f"tenant={tenant}:{w}")
        self.tenant = tenant
        # the INITIAL dial goes through the same backoff machinery as
        # mid-stream drops: a consumer starting while the server is mid-
        # restart under a supervisor must wait it out, not crash with a
        # raw ConnectionRefusedError that dead-transport handlers (which
        # catch TransportClosed) don't recognize
        try:
            self._sock = socket.create_connection((host, port), timeout=timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except (ConnectionError, socket.timeout, OSError) as e:
            self._reconnect(e)  # raises TransportClosed when exhausted
        if namespace is not None or queue_name is not None:
            self.open(namespace or "default", queue_name or "default", maxsize)
        if self._codec_auto:
            with self._lock:
                try:
                    self._decide_auto_codec_raw()
                except (ConnectionError, socket.timeout, OSError) as e:
                    self._reconnect(e)  # re-probes + renegotiates itself
        if self._codec_names or self._hello_fields:
            self._negotiate()

    def open(self, namespace: str, queue_name: str, maxsize: int = 0):
        """Bind this connection to the server-side queue named
        ``(namespace, queue_name)``, get-or-creating it (``maxsize`` is
        used only on create; 0 = server default). Ray-GCS named-actor
        parity (reference ``shared_queue.py:33-38``, ``data_reader.py:20``)."""
        with self._lock:
            # binding stored under the lock: _reconnect reads it mid-
            # replay and a racing rebind must never hand it a torn value
            self._binding = (namespace, queue_name, maxsize)
            # no _retrying here: _reconnect itself replays the binding, so
            # the usual retry-the-exchange step would send a second OPEN
            try:
                self._open_raw(namespace, queue_name, maxsize)
            except (ConnectionError, socket.timeout, OSError) as e:
                self._reconnect(e)  # raises TransportClosed when it can't

    def _open_raw(self, namespace: str, queue_name: str, maxsize: int):
        # guarded-by-caller: _lock
        ns, nm = namespace.encode(), queue_name.encode()
        self._sock.sendall(
            _OP_OPEN
            + struct.pack("<H", len(ns)) + ns
            + struct.pack("<H", len(nm)) + nm
            + struct.pack("<I", maxsize)
        )
        self._status()

    # -- wire-compression negotiation (opcode 'Z', ISSUE 9) ---------------
    def _negotiate(self):
        with self._lock:
            try:
                self._negotiate_raw()
            except (ConnectionError, socket.timeout, OSError) as e:
                self._reconnect(e)  # renegotiates itself on success

    def _negotiate_raw(self):
        """One 'Z' exchange on the current socket. A peer that predates
        the opcode answers protocol-error (and drops the connection):
        that DEGRADES this client to uncompressed — latched, so
        reconnects stop re-asking — instead of failing the transport.
        Caller holds ``self._lock``."""
        # guarded-by-caller: _lock
        if self._codec_refused:
            return
        # codec names first (the server picks the first it knows), then
        # the capability fields; with no codecs the explicit "none"
        # keeps the server's pick unambiguous
        advert = [*(self._codec_names or [CODEC_NONE]), *self._hello_fields]
        names = ",".join(advert).encode()
        self._sock.sendall(_OP_CODEC + struct.pack("<H", len(names)) + names)
        try:
            self._status()
        except RuntimeError:
            # old peer: 'E' answer, connection about to close server-side.
            # Degrade to uncompressed; the next op reconnects normally.
            self._codec = None
            self._codec_refused = True
            FLIGHT.record(
                "codec_refused", host=self.host, port=self.port
            )
            return
        (n,) = struct.unpack("<H", _recv_exact(self._sock, 2))
        try:
            chosen = _recv_exact(self._sock, n).decode()
            self._codec = get_codec(chosen)
        except ValueError:
            # buggy peer/proxy: a name we never advertised (or not even
            # UTF-8). Same contract as the old-peer refusal: degrade to
            # uncompressed and latch, never fail the transport.
            self._codec = None
            self._codec_refused = True
            FLIGHT.record(
                "codec_refused", host=self.host, port=self.port
            )
            return
        CODEC_STATS.negotiated(chosen)
        FLIGHT.record(
            "codec_negotiated", host=self.host, port=self.port, codec=chosen
        )

    # -- link-rate probe + auto codec decision (ISSUE 15) ------------------
    # Bandwidth below which wire compression wins on this build: the
    # pure-numpy codec moves ~200 MB/s at ~3x on detector frames, so the
    # break-even link is ~rate x (1 - 1/ratio) ~ 133 MB/s; 125 keeps a
    # margin on the codec side (a borderline LAN stays raw — the codec
    # only costs CPU there). PSANA_AUTO_CODEC_MB_S overrides.
    AUTO_CODEC_THRESHOLD_MB_S = 125.0
    # Padded control-RPC size per bandwidth probe: large enough that the
    # transfer time dominates RTT on any link slow enough to matter,
    # small enough to stay far under the 1 MB control-plane cap. Three
    # probes ship back to back and the MEDIAN decides — a token-bucket
    # burst (or warm TCP window) can fake one fast sample, a scheduler
    # blip one slow sample; the median survives either.
    AUTO_CODEC_PROBE_BYTES = 640 * 1024

    def _probe_link_raw(self) -> tuple:
        """Measure (link MB/s, RTT s) on the current socket: RTT from
        two 'A' anchor exchanges (min), bandwidth from timing padded 'N'
        ping RPCs through the link (the server must read the whole
        request before answering, so elapsed ~ RTT + bytes/bandwidth).
        Runs only at connect/reconnect time, pre-stream — nothing is in
        flight to desync. Caller holds ``self._lock``."""
        # guarded-by-caller: _lock
        sock = self._sock
        rtt = float("inf")
        for _ in range(2):
            t0 = time.monotonic()
            sock.sendall(_OP_ANCHOR + struct.pack("<dd", time.time(), t0))
            self._status()
            _recv_exact(sock, 16)
            rtt = min(rtt, time.monotonic() - t0)
        # hand-assembled so the bytes match the server's O(1) ping
        # prefix fast path (evloop._cluster_finish) — a json.dumps of a
        # 640 KB string costs client time the measurement would absorb
        body = (
            b'{"op": "ping", "pad": "'
            + b"x" * self.AUTO_CODEC_PROBE_BYTES
            + b'"}'
        )
        samples = []
        for _ in range(3):
            t0 = time.monotonic()
            sock.sendall(_OP_CLUSTER + struct.pack("<I", len(body)) + body)
            self._status()
            (n,) = struct.unpack("<I", _recv_exact(sock, 4))
            _recv_exact(sock, n)
            elapsed = time.monotonic() - t0
            samples.append(len(body) / max(elapsed - rtt, 1e-6) / 1e6)
        # median of three: a token-bucket burst can fake ONE fast sample
        # (the bucket drains under the first probe), a scheduler blip
        # can fake ONE slow one — the median survives either
        return sorted(samples)[1], rtt

    def _decide_auto_codec_raw(self) -> None:
        """One-shot ``codec="auto"`` decision for THIS connection: probe
        the link, compare against the codec break-even rate, and set the
        advert the next 'Z' exchange carries. A probe the peer refuses
        (protocol error from an odd proxy) decides FOR compression —
        the bandwidth-conservative fallback — and never fails the
        transport. Caller holds ``self._lock``."""
        # guarded-by-caller: _lock
        import os

        mb_s = rtt = None
        try:
            mb_s, rtt = self._probe_link_raw()
        except (ConnectionError, socket.timeout, OSError):
            raise  # real socket death: the caller's reconnect owns it
        except Exception:  # noqa: BLE001 — a refused probe decides, not dies
            pass
        try:
            threshold = float(
                os.environ.get(
                    "PSANA_AUTO_CODEC_MB_S", self.AUTO_CODEC_THRESHOLD_MB_S
                )
            )
        except ValueError:  # a typo'd override decides at the default,
            threshold = self.AUTO_CODEC_THRESHOLD_MB_S  # never fails connect
        slow = mb_s is None or mb_s < threshold
        self._codec_names = (available_codecs() or None) if slow else None
        if self._codec_names is None:
            # decided OFF: drop any previously negotiated codec NOW —
            # with nothing to advertise no 'Z' follows, and a stale
            # codec object would keep compressing onto a fresh
            # connection that never negotiated
            self._codec = None
        FLIGHT.record(
            "codec_auto_decision",
            host=self.host, port=self.port,
            link_mb_s=round(mb_s, 1) if mb_s is not None else None,
            rtt_ms=round(rtt * 1e3, 2) if rtt is not None else None,
            threshold_mb_s=threshold,
            codec_on=bool(self._codec_names),
        )

    def _encode_for_wire(self, item):
        """codec.encode_for_wire under this connection's negotiated
        codec — every put path calls this under the client lock (the
        negotiated codec is per-connection state a racing reconnect
        may flip). See the helper for the lease/pass-through
        contract."""
        # guarded-by-caller: _lock
        return _wire_encode(item, self._codec, self._pool)

    # -- live knob surface (ISSUE 15 autotune) -----------------------------
    @property
    def put_window(self) -> int:
        with self._lock:
            return self._put_window

    def set_put_window(self, n: int) -> None:
        """Resize the windowed-PUT pipeline depth live (autotune knob).
        Purely client-side state: a shrink simply waits for more acks
        before the next send; a grow admits more in-flight puts."""
        with self._lock:
            self._put_window = max(1, int(n))

    @property
    def stream_window(self) -> int:
        with self._lock:
            st = self._stream
            return st.window if st is not None else 0

    def set_stream_window(self, n: int) -> bool:
        """Resize the stream credit window live (autotune knob): one 'M'
        with the new credit count on the streamed connection — the
        server adjusts its budget in place (no response, exactly like
        the subscribe), and the next cumulative 'K' replenishes against
        the new window. Requires an open subscription."""
        n = max(1, min(int(n), 4096))
        with self._lock:
            if self._replay_args is not None:
                # replay is pull-mode: no stream to resize (and the
                # server kills 'M' on a replay connection)
                raise RuntimeError(
                    "set_stream_window on a replay connection — replay "
                    "is pull-mode"
                )
            if self._stream is None:
                raise RuntimeError(
                    "set_stream_window needs an open stream subscription "
                    "(call stream_open first)"
                )
            st = self._stream
            if n == st.window:
                return True
            st.window = n  # before the send: a reconnect resubscribes with it
            try:
                self._sock.sendall(_OP_STREAM + struct.pack("<I", n))
            except (ConnectionError, socket.timeout, OSError) as e:
                self._reconnect(e)  # resubscribes at the NEW window
            return True

    @property
    def codec_name(self) -> Optional[str]:
        """The negotiated wire codec's name, or None when raw."""
        with self._lock:
            codec = self._codec
        return getattr(codec, "name", None) if codec is not None else None

    def renegotiate_codec(self, names=None) -> bool:
        """Flip wire compression live (autotune knob): renegotiate this
        connection's codec via a fresh 'Z' exchange — ``names`` is a
        codec list to advertise, None/empty renegotiates down to raw.
        Refused on streamed connections (a mid-push 'Z' would desync
        the push framing; the reconnect-time auto decision owns those)
        and a no-op after an old-peer refusal latched. Bounded: any
        outstanding windowed-put acks drain under the probe deadline
        first (their responses precede the 'Z' answer in the byte
        stream). Returns True when a codec is now negotiated."""
        if self._stream is not None:
            raise RuntimeError(
                "renegotiate_codec on a streamed connection — the codec "
                "there is re-decided at (re)connect, not mid-push"
            )
        if names:
            names = [str(n) for n in names]
            for n in names:
                get_codec(n)  # fail fast on unknown names
        deadline = time.monotonic() + self.PROBE_DEADLINE_S
        with self._lock:
            if self._codec_refused:
                return False
            self._codec_names = names or None
            self._retrying(self._negotiate_raw, deadline)
            return self._codec is not None

    def _reconnect(self, cause: BaseException, deadline: Optional[float] = None):
        """Re-dial with exponential backoff and replay the named binding.
        Raises TransportClosed when every attempt fails — or when
        ``deadline`` (time.monotonic()) passes, so timeout-bearing callers
        (get_wait/put_wait/get_batch) keep their latency contract instead
        of blocking through the full backoff cycle. Caller holds
        ``self._lock`` (except from __init__, where no peer exists yet
        and the windowed/stream state is still empty)."""
        # guarded-by-caller: _lock
        import time

        # flight-recorder breadcrumb: reconnect storms are the leading
        # indicator in most wedged-run postmortems
        FLIGHT.record(
            "reconnect", host=self.host, port=self.port, cause=repr(cause)
        )
        sock = getattr(self, "_sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        delay = self._reconnect_base_s
        last: BaseException = cause
        for attempt in range(self._reconnect_tries):
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                break
            if attempt:  # back off BETWEEN dials — never after the last
                # FULL JITTER (uniform over [0, envelope)): the envelope
                # doubles per attempt but the actual sleep is randomized
                # — a deterministic schedule makes every client that
                # watched the same server die redial in LOCKSTEP, and
                # after an owner death that stampede lands squarely on
                # the freshly promoted follower (ISSUE 11); the spread
                # is pinned by test_replication.py
                sleep_s = random.uniform(0.0, delay)
                if deadline is not None:
                    sleep_s = min(sleep_s, max(0.0, deadline - now))
                time.sleep(sleep_s)
                delay = min(delay * 2, 5.0)
                if deadline is not None and time.monotonic() >= deadline:
                    break
            dial_timeout = self._timeout_s
            if deadline is not None:
                dial_timeout = max(0.05, min(dial_timeout, deadline - time.monotonic()))
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=dial_timeout
                )
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if self._binding is not None:
                    self._open_raw(*self._binding)
                if (
                    self._codec_auto
                    and not self._codec_refused
                    and deadline is None
                ):
                    # "auto" is a per-CONNECTION decision: the fresh
                    # link may be a different link (failover through a
                    # tunnel, a recovered LAN) — re-probe, re-decide.
                    # NOT under a caller deadline: the ~2 MB probe
                    # cannot fit a clipped dial timeout on exactly the
                    # slow links it exists for (the previous decision
                    # carries; the next deadline-less reconnect
                    # re-decides). Reset the dial timeout first — the
                    # probe must run under the patient one.
                    self._sock.settimeout(self._timeout_s)
                    self._decide_auto_codec_raw()
                if self._codec_names or self._hello_fields:
                    # renegotiate BEFORE any payload-bearing replay: the
                    # windowed resend below must know whether this
                    # connection compresses (an old-peer refusal latches
                    # and the resend simply goes out raw), and the
                    # tenant hello must re-bind the fresh connection's
                    # fair-share identity before it carries traffic
                    self._negotiate_raw()
                if self._replay_args is not None:
                    # re-open the replay cursor at the group's committed
                    # offset: everything unconfirmed redelivers (dupes
                    # possible, holes never)
                    pos, rg = self._replay_args
                    g = rg.encode()
                    self._sock.sendall(
                        _OP_REPLAY + struct.pack("<QH", pos, len(g)) + g
                    )
                    if self._status() == _ST_OK:
                        _recv_exact(self._sock, 16)
                    else:
                        # the server came back WITHOUT a log for this
                        # queue: continuing would silently turn this
                        # non-destructive replay reader into a live
                        # consumer (popping frames live consumers own).
                        # Fail the transport loudly instead.
                        FLIGHT.record(
                            "replay_resubscribe_refused",
                            host=self.host, port=self.port,
                        )
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        raise TransportClosed(
                            f"replay re-subscription refused by "
                            f"{self.host}:{self.port} — the restarted "
                            f"server has no segment log for this queue; "
                            f"refusing to degrade into a live consumer"
                        )
                # windowed-put resend invariant: the entire unacked tail
                # goes out FIRST, in sequence order, before any new
                # request touches the fresh connection — the server may
                # see duplicates (at-least-once) but never a hole
                if self._put_unacked:
                    self._resend_put_window()
                # a streamed connection re-subscribes with its original
                # credit window; frames the dead connection had in the
                # air were re-enqueued server-side and redeliver here
                if self._stream is not None:
                    self._sock.sendall(
                        _OP_STREAM + struct.pack("<I", self._stream.window)
                    )
                    self._stream.reset_after_reconnect()
                    FLIGHT.record(
                        "stream_resubscribe", host=self.host, port=self.port
                    )
                # the clipped dial timeout bounded THIS handshake; the
                # connection it produced must run under the configured
                # timeout, or every later server-side blocking wait
                # (opcode 'D' parks up to the caller's own deadline)
                # outlives the poisoned recv timeout and reads as a
                # fresh death — reconnect storm, then TransportClosed
                # on a perfectly healthy server
                self._sock.settimeout(self._timeout_s)
                return
            except (ConnectionError, socket.timeout, OSError) as e:
                last = e
        deadline_hit = deadline is not None and time.monotonic() >= deadline
        raise TransportClosed(
            f"connection to queue server {self.host}:{self.port} died and "
            f"reconnect attempts failed (tries={self._reconnect_tries}"
            f"{', caller deadline hit' if deadline_hit else ''}): {last}"
        ) from last

    def _retrying(self, do, deadline: Optional[float] = None):
        """Run one request/response exchange; on a RAW socket failure,
        reconnect (bounded by ``deadline`` when given) and retry the
        exchange once. TransportClosed from ``_status`` (server's explicit
        refusal) passes straight through. Caller holds ``self._lock``.

        Pending windowed-put acks are fully drained FIRST: their
        responses precede this exchange's in the byte stream, so a
        request issued over an outstanding window would read a put ack
        as its own status and desync the connection."""
        # guarded-by-caller: _lock
        if self._put_unacked and not self._drain_put_acks(0, deadline):
            raise TransportClosed(
                f"windowed puts to {self.host}:{self.port} still "
                f"unacknowledged at the caller's deadline"
            )
        try:
            return do()
        except (ConnectionError, socket.timeout, OSError) as e:
            self._reconnect(e, deadline)  # raises TransportClosed when it can't
            try:
                return do()
            except (ConnectionError, socket.timeout, OSError) as e2:
                raise TransportClosed(
                    f"connection to queue server {self.host}:{self.port} "
                    f"died again right after a successful reconnect: {e2}"
                ) from e2

    # -- windowed pipelined PUT (opcode 'W') ------------------------------
    def _resend_put_window(self):
        """Resend the whole unacknowledged tail on a fresh connection, in
        sequence order (the windowed-put resend invariant — see the
        module docstring's streaming contract). Called from _reconnect
        with the new socket already dialed and the binding replayed."""
        # guarded-by-caller: _lock
        for seq, item in list(self._put_unacked):
            parts, clease = self._encode_for_wire(item)
            try:
                head = _OP_PUT_SEQ + struct.pack(
                    "<QI", seq, _parts_nbytes(parts)
                )
                _sendmsg_all(self._sock, [head, *parts])
            finally:
                if clease is not None:
                    clease.release()
        n = len(self._put_unacked)
        if n:
            STREAM.resent(n)
            FLIGHT.record(
                "put_window_resend", count=n, host=self.host, port=self.port
            )

    def _drain_put_acks(self, max_unacked: int, deadline: Optional[float]) -> bool:
        """Read windowed-put acks until at most ``max_unacked`` remain
        in flight (False when ``deadline`` expires first — nothing is
        lost; the tail stays queued for resend).

        An OVERDUE ack is BACKPRESSURE, not death: the server delays
        acks while its queue is full (the 'W' handler's blocking
        enqueue), for arbitrarily long — so a quiet wire keeps waiting
        in bounded slices instead of reconnecting (a reconnect here
        would resend the whole window into the already-full queue:
        duplicate amplification on every timeout, triggered by ordinary
        backpressure). Only a broken connection (EOF/reset) reconnects
        and resends, and that reconnect runs the FULL backoff envelope
        regardless of ``deadline`` — a supervisor restart mid-window
        must not kill the stream; the deadline bounds waiting, not
        availability recovery. An explicit 'X' raises TransportClosed.
        Caller holds ``self._lock``."""
        # guarded-by-caller: _lock
        while len(self._put_unacked) > max_unacked:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return False
            slice_s = self._timeout_s
            if remaining is not None:
                slice_s = min(slice_s, remaining)
            try:
                # the ack-wait slice applies to the status byte only;
                # once it arrives, the 8-byte seq follows at wire speed
                # under the patient timeout (a timeout mid-ack would
                # desync — that one IS treated as a raw failure)
                try:
                    self._sock.settimeout(slice_s)
                    try:
                        st = self._status()
                    except socket.timeout:
                        continue  # overdue = backpressured, keep waiting
                finally:
                    try:
                        self._sock.settimeout(self._timeout_s)
                    except OSError:
                        pass
                if st != _ST_OK:
                    raise RuntimeError(
                        f"protocol error in windowed-put ack: {st!r}"
                    )
                (seq,) = struct.unpack("<Q", _recv_exact(self._sock, 8))
            except (ConnectionError, socket.timeout, OSError) as e:
                self._reconnect(e)  # full envelope; resends the tail itself
                continue
            while self._put_unacked and self._put_unacked[0][0] <= seq:
                self._put_unacked.popleft()
            STREAM.put_depth(len(self._put_unacked))
        return True

    def put_pipelined(self, item: Any, deadline: Optional[float] = None) -> bool:
        """Windowed pipelined put: send without waiting for the status,
        keeping up to ``put_window`` sequence-numbered puts in flight
        (backpressure arrives as delayed acks from the server's blocking
        enqueue — no refusal/retry round trips). Returns False when the
        window is still full at ``deadline`` (the item was NOT sent —
        retry it); raises TransportClosed when the transport is dead
        (``deadline`` bounds the wait for window space, NOT the
        reconnect envelope — a supervisor restart mid-window rides the
        full backoff like every other op). On reconnect the unacked
        tail is resent: duplicates possible, holes never. Call
        :meth:`flush_puts` before relying on durability (EOS,
        shutdown)."""
        if self._stream is not None:
            return self._side_channel().put_pipelined(item, deadline)
        with self._lock:
            if not self._drain_put_acks(self._put_window - 1, deadline):
                return False
            # encode under the lock: the negotiated codec is per-
            # connection state a racing reconnect may flip
            parts, clease = self._encode_for_wire(item)
            try:
                n = _parts_nbytes(parts)
                if n > _MAX_PAYLOAD:  # fail fast: peer would drop the conn
                    raise ValueError(
                        f"payload of {n} bytes exceeds wire maximum "
                        f"{_MAX_PAYLOAD}"
                    )
                self._put_seq += 1
                seq = self._put_seq
                self._put_unacked.append((seq, item))
                STREAM.put_depth(len(self._put_unacked))
                head = _OP_PUT_SEQ + struct.pack("<QI", seq, n)
                try:
                    _sendmsg_all(self._sock, [head, *parts])
                except (ConnectionError, socket.timeout, OSError) as e:
                    # full-envelope reconnect (no caller deadline: see
                    # the docstring) resends the whole tail — including
                    # this item, already appended above
                    self._reconnect(e)
            finally:
                if clease is not None:
                    clease.release()
            return True

    def flush_puts(self, deadline: Optional[float] = None) -> bool:
        """Block until every windowed put is acknowledged (False when
        ``deadline`` expires first; the tail stays in flight)."""
        if self._stream is not None:
            side = self._side
            return True if side is None else side.flush_puts(deadline)
        with self._lock:
            return self._drain_put_acks(0, deadline)

    # -- streaming consumption (opcodes 'M'/'K') --------------------------
    def stream_open(self, window: int = DEFAULT_STREAM_WINDOW) -> "TcpStreamReader":
        """Subscribe this connection to server-push delivery with an
        initial credit count of ``window`` frames (idempotent — the
        first subscription wins). From here on the connection carries
        only pushes and acks: reads (get/get_wait/get_batch) drain the
        stream, while puts/probes route over a lazily opened side
        channel (see :meth:`_side_channel`)."""
        with self._lock:
            if self._stream is not None:
                return self._stream
            if self._replay_args is not None:
                # the server rejects 'M' on a replay connection (replay
                # is pull-mode by design) and kills the connection; the
                # protocol-dialogue checker pins this guard client-side
                raise RuntimeError(
                    "stream_open on a replay connection — replay is "
                    "pull-mode; use a dedicated (non-replay) client"
                )
            window = max(1, int(window))

            def _do():
                self._sock.sendall(_OP_STREAM + struct.pack("<I", window))

            self._retrying(_do)
            self._stream = TcpStreamReader(self, window)
            STREAM.ensure_registered()
            return self._stream

    def get_batch_stream(
        self, max_items: int, timeout: Optional[float] = None
    ) -> List[Any]:
        """Streamed drain (subscribing with the default credit window on
        first use): returns whatever the server has already pushed, up
        to ``max_items``, blocking at most ``timeout`` for the first
        frame. The batcher prefers this entry point over ``get_batch``
        — zero request round trips, zero empty-queue polls."""
        return self.stream_open().get_batch_stream(max_items, timeout)

    def _side_channel(self) -> "TcpQueueClient":
        """A second plain connection for the rare request/response ops a
        streamed client still needs (EOS duplicate put-backs, probes):
        any such opcode on the streamed socket itself would desync the
        push framing. Replays the named binding, shares the pool."""
        side = self._side
        if side is None:
            ns, nm, ms = self._binding or (None, None, 0)
            # "auto" inherits THIS connection's probe decision instead
            # of re-probing: the side channel shares the link
            codec_arg = self._codec_arg
            with self._lock:
                names = self._codec_names
                put_window = self._put_window
            if self._codec_auto:
                codec_arg = ",".join(names) if names else None
            side = TcpQueueClient(
                self.host,
                self.port,
                timeout_s=self._timeout_s,
                namespace=ns,
                queue_name=nm,
                maxsize=ms,
                reconnect_tries=self._reconnect_tries,
                reconnect_base_s=self._reconnect_base_s,
                pool=self._pool,
                put_window=put_window,
                codec=codec_arg,
            )
            self._side = side
        return side

    # -- contract ---------------------------------------------------------
    def put(self, item: Any, deadline: Optional[float] = None) -> bool:
        if self._stream is not None:  # streamed conn: puts use the side channel
            return self._side_channel().put(item, deadline)

        # scatter-gather: the frame payload goes to the kernel straight
        # from the record's panel memory (wire_parts memoryview) — no
        # to_bytes() serialization copy, no request-assembly concat copy.
        # A negotiated codec stages the compressed form in a pool lease,
        # released once the exchange is over. Encoding happens INSIDE
        # the retried exchange: a reconnect may renegotiate (or an
        # old-peer refusal may downgrade) the codec, and the retry must
        # send what THIS connection speaks, never stale compressed parts.
        def _do():
            parts, clease = self._encode_for_wire(item)
            try:
                n = _parts_nbytes(parts)
                if n > _MAX_PAYLOAD:  # fail fast: peer would drop the conn
                    raise ValueError(
                        f"payload of {n} bytes exceeds wire maximum "
                        f"{_MAX_PAYLOAD}"
                    )
                head = _OP_PUT + struct.pack("<I", n)
                _sendmsg_all(self._sock, [head, *parts])
                return self._status() == _ST_OK
            finally:
                if clease is not None:
                    clease.release()

        with self._lock:
            return self._retrying(_do, deadline)

    def get(self, deadline: Optional[float] = None) -> Any:
        if self._stream is not None:  # drain already-pushed frames only
            return self._stream.get_wait_stream(0.0)

        def _do():
            self._sock.sendall(_OP_GET)
            st = self._status()
            if st == _ST_NO:
                return EMPTY
            (n,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            return _recv_payload(self._sock, n, self._pool)

        with self._lock:
            return self._retrying(_do, deadline)

    # size()/stats() are observability probes (scrape threads, heartbeats,
    # the stall detector): they must fail FAST on a dead server — the full
    # reconnect backoff cycle (minutes, serialized under self._lock) would
    # stall /metrics exactly during the incident the probe exists to show.
    # Data opcodes (put/get) keep the patient default.
    PROBE_DEADLINE_S = 5.0

    def size(self, deadline: Optional[float] = None) -> int:
        import time

        if self._stream is not None:  # probes would desync the push framing
            return self._side_channel().size(deadline)

        def _do():
            self._sock.sendall(_OP_SIZE)
            self._status()
            (n,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            return n

        if deadline is None:
            deadline = time.monotonic() + self.PROBE_DEADLINE_S
        with self._lock:
            return self._retrying(_do, deadline)

    def anchor(self, deadline: Optional[float] = None) -> dict:
        """Clock ping/anchor exchange (opcode 'A', the stats RPC's tracing
        sibling): returns the server's (wall, mono) pair bracketed by this
        process's own samples, plus the measured RTT — exactly what
        :func:`psana_ray_tpu.obs.tracing.exchange_anchors` spools so the
        trace merge tool can align this host's clock to the server's."""
        if self._stream is not None:
            return self._side_channel().anchor(deadline)

        def _do():
            t0_wall, t0_mono = time.time(), time.monotonic()
            self._sock.sendall(_OP_ANCHOR + struct.pack("<dd", t0_wall, t0_mono))
            self._status()
            peer_wall, peer_mono = struct.unpack("<dd", _recv_exact(self._sock, 16))
            t1_wall, t1_mono = time.time(), time.monotonic()
            return {
                "send_wall": t0_wall,
                "send_mono": t0_mono,
                "recv_wall": t1_wall,
                "recv_mono": t1_mono,
                "peer_wall": peer_wall,
                "peer_mono": peer_mono,
                "rtt_s": t1_mono - t0_mono,
                "peer": f"{self.host}:{self.port}",
            }

        if deadline is None:
            deadline = time.monotonic() + self.PROBE_DEADLINE_S
        with self._lock:
            return self._retrying(_do, deadline)

    def stats(self, deadline: Optional[float] = None) -> dict:
        """Queue-health RPC (opcode 'T'): depth, high-water mark, put/get
        counters, liveness ages of the queue this connection is bound to —
        the cross-host half of the observability story (the stall detector
        and the Prometheus endpoint read the same dict server-side)."""
        import time

        if self._stream is not None:
            return self._side_channel().stats(deadline)

        def _do():
            self._sock.sendall(_OP_STATS)
            self._status()
            (n,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            return json.loads(_recv_exact(self._sock, n).decode())

        if deadline is None:
            deadline = time.monotonic() + self.PROBE_DEADLINE_S
        with self._lock:
            return self._retrying(_do, deadline)

    def cluster_rpc(self, payload: dict, deadline: Optional[float] = None) -> dict:
        """Consumer-group coordination RPC (opcode 'N'): send one JSON
        request to the server's :class:`psana_ray_tpu.cluster.
        coordinator.GroupRegistry` and return its JSON answer. Control
        plane, so it fails fast like the other probes (PROBE_DEADLINE_S)
        — a dead coordinator must surface as TransportClosed promptly,
        not hang a rebalance behind the full reconnect envelope."""
        import time

        if self._stream is not None:  # would desync the push framing
            return self._side_channel().cluster_rpc(payload, deadline)
        body = json.dumps(payload).encode()

        def _do():
            self._sock.sendall(_OP_CLUSTER + struct.pack("<I", len(body)) + body)
            self._status()
            (n,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            return json.loads(_recv_exact(self._sock, n).decode())

        if deadline is None:
            deadline = time.monotonic() + self.PROBE_DEADLINE_S
        with self._lock:
            return self._retrying(_do, deadline)

    # -- durable log surface (opcodes 'R'/'J', ISSUE 8) -------------------
    def replay_open(self, from_offset=None, group: str = "replay") -> dict:
        """Switch this connection's reads to a NON-DESTRUCTIVE replay
        cursor over the bound queue's retained segment-log range for
        ``group`` (durable queues only — raises RuntimeError otherwise).
        ``from_offset``: ``None``/``"resume"`` resumes at the group's
        committed offset, ``"begin"`` starts at the earliest retained
        record, an int is an explicit offset. Live consumers are
        undisturbed. Delivered records are committed for the group at
        this connection's implicit-ACK points, so a crashed replay
        consumer re-opens with ``resume`` and loses nothing (duplicates
        possible since the last commit). Returns ``{"start", "end"}``.
        On reconnect the subscription replays itself at ``resume``."""
        from psana_ray_tpu.storage.log import REPLAY_BEGIN, REPLAY_RESUME

        if self._stream is not None:
            # a streamed connection carries only pushes and acks; 'R'
            # on it is a protocol error server-side, and a side-channel
            # replay would NOT redirect THIS connection's reads — there
            # is no sane silent fallback, so refuse loudly
            raise RuntimeError(
                "replay_open on a streamed connection — replay is "
                "pull-mode; use a dedicated (non-streamed) client"
            )
        if from_offset is None or from_offset == "resume":
            pos = REPLAY_RESUME
        elif from_offset == "begin":
            pos = REPLAY_BEGIN
        else:
            pos = int(from_offset)
        g = group.encode()

        def _do():
            self._sock.sendall(
                _OP_REPLAY + struct.pack("<QH", pos, len(g)) + g
            )
            st = self._status()
            if st != _ST_OK:
                raise RuntimeError(
                    f"replay refused: queue {self._binding or 'default'} "
                    f"on {self.host}:{self.port} has no segment log "
                    f"(start the server with --durable_dir)"
                )
            start, end = struct.unpack("<QQ", _recv_exact(self._sock, 16))
            return {"start": start, "end": end}

        with self._lock:
            out = self._retrying(_do)
            # reconnects re-subscribe at the group's committed offset —
            # the server-side commit state carries the position
            self._replay_args = (REPLAY_RESUME, group)
        # client-side breadcrumb: the consumer process's own flight ring
        # (and its --status_interval `durable[...]` bracket) must show
        # the replay even when the server runs elsewhere
        FLIGHT.record(
            "replay_open", host=self.host, port=self.port, group=group,
            start=out["start"], end=out["end"],
        )
        return out

    def commit_offset(
        self, offset=None, group: str = "", deadline: Optional[float] = None
    ) -> bool:
        """Persist a committed offset for ``group`` on the bound durable
        queue ('J'). ``offset=None`` commits everything DELIVERED to
        this connection's replay cursor so far (the explicit form of the
        implicit ack). False when the queue has no log."""
        from psana_ray_tpu.storage.log import COMMIT_DELIVERED

        if self._stream is not None:
            return self._side_channel().commit_offset(offset, group, deadline)
        pos = COMMIT_DELIVERED if offset is None else int(offset)
        g = group.encode()

        def _do():
            self._sock.sendall(
                _OP_COMMIT + struct.pack("<QH", pos, len(g)) + g
            )
            return self._status() == _ST_OK

        with self._lock:
            return self._retrying(_do, deadline)

    def promote(
        self, namespace: str, queue_name: str, deadline: Optional[float] = None
    ) -> Optional[dict]:
        """Replication failover ('Y', ISSUE 11): ask this server to
        promote its replica log for ``(namespace, queue_name)`` into the
        live durable queue — sent by the cluster client against a
        partition's new owner BEFORE opening it, so the promoted backlog
        (and retained replay range) is what OPEN mounts. Returns
        ``{"start", "end"}`` (the retained range) or None when the
        server holds no replica (the partition starts empty there).
        Control plane: fails fast like the probes."""
        if self._stream is not None:  # would desync the push framing
            return self._side_channel().promote(namespace, queue_name, deadline)
        ns, nm = namespace.encode(), queue_name.encode()

        def _do():
            self._sock.sendall(
                _OP_PROMOTE
                + struct.pack("<H", len(ns)) + ns
                + struct.pack("<H", len(nm)) + nm
            )
            st = self._status()
            if st != _ST_OK:
                return None
            start, end = struct.unpack("<QQ", _recv_exact(self._sock, 16))
            return {"start": start, "end": end}

        if deadline is None:
            deadline = time.monotonic() + self.PROBE_DEADLINE_S
        with self._lock:
            return self._retrying(_do, deadline)

    def unacked_puts(self) -> List[Any]:
        """Snapshot of the windowed-put items not yet acknowledged by
        THIS server, oldest first. The cluster client reads it when a
        server dies for good (reconnects exhausted): the tail must be
        resent to the partition's NEW owner — the PR 5 resend invariant
        carried across servers (duplicates possible, holes never)."""
        with self._lock:
            return [item for (_seq, item) in self._put_unacked]

    def close_remote(self):
        """Close the remote queue (fault-injection / teardown)."""
        if self._stream is not None:
            return self._side_channel().close_remote()

        def _do():
            self._sock.sendall(_OP_CLOSE)
            self._status()

        with self._lock:
            return self._retrying(_do)

    # -- blocking helpers (same surface as RingBuffer) --------------------
    # The surviving client-side sleeps below are deadline-checked every
    # iteration and only run BETWEEN server-side bounded waits (the
    # server already blocked _SERVER_WAIT_CAP_S for the condition), so
    # total blocking is caller-bounded — the latency contract the
    # blocking-hot-path lint checker's TcpQueueClient exclusion documents.
    def get_wait(self, timeout: Optional[float] = None, poll_s: float = 0.001) -> Any:
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        if self._stream is not None:  # streamed: the push IS the wait
            while True:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return EMPTY
                item = self._stream.get_wait_stream(remaining)
                if item is not EMPTY:
                    return item
                if deadline is not None and time.monotonic() >= deadline:
                    return EMPTY
        while True:
            # server-side bounded wait ('D', max_items=1): an empty queue
            # costs one round trip per cap interval, not one per poll
            out = self._get_batch_once(1, deadline, self._server_wait(deadline))
            if out:
                return out[0]
            if deadline is not None and time.monotonic() >= deadline:
                return EMPTY
            time.sleep(poll_s)

    @staticmethod
    def _server_wait(deadline: Optional[float]) -> float:
        """How long the SERVER should block for this round trip: the full
        cap, clipped to the caller's remaining deadline."""
        if deadline is None:
            return _SERVER_WAIT_CAP_S
        return min(_SERVER_WAIT_CAP_S, max(0.0, deadline - time.monotonic()))

    def put_wait(
        self, item: Any, timeout: Optional[float] = None, poll_s: float = 0.001
    ) -> bool:
        import time

        if self._stream is not None:
            return self._side_channel().put_wait(item, timeout, poll_s)
        deadline = None if timeout is None else time.monotonic() + timeout
        # bill this thread's CPU to "enqueue" for the continuous
        # profiler until the put resolves (restored in the finally)
        prev_tag = swap_stage(TAG_ENQUEUE)
        # the compressed bytes depend only on (item, codec), so the
        # encode is CACHED across full-queue retries — paying the codec
        # once per frame, not once per bounded-wait round trip — and
        # invalidated when a reconnect mid-attempt renegotiates the
        # codec (get_codec returns per-name singletons, so identity is
        # the negotiation generation; the retry then re-encodes to what
        # this connection now speaks). The staging lease lives until
        # the put resolves.
        cached = None  # (codec, parts, staging_lease)
        try:
            while True:
                # server-side bounded wait for SPACE ('U'): a full queue
                # costs one round trip per cap interval, not one
                # rejected put per poll tick
                wait_ms = int(self._server_wait(deadline) * 1000)

                def _do():
                    nonlocal cached
                    codec = self._codec
                    if cached is None or cached[0] is not codec:
                        if cached is not None and cached[2] is not None:
                            cached[2].release()
                        cached = None
                        parts, clease = self._encode_for_wire(item)
                        cached = (codec, parts, clease)
                    parts = cached[1]
                    n = _parts_nbytes(parts)
                    if n > _MAX_PAYLOAD:  # fail fast
                        raise ValueError(
                            f"payload of {n} bytes exceeds wire maximum "
                            f"{_MAX_PAYLOAD}"
                        )
                    head = _OP_PUT_WAIT + struct.pack("<II", wait_ms, n)
                    _sendmsg_all(self._sock, [head, *parts])
                    return self._status() == _ST_OK

                with self._lock:
                    if self._retrying(_do, deadline):
                        return True
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                time.sleep(poll_s)
        finally:
            set_stage(prev_tag)
            if cached is not None and cached[2] is not None:
                cached[2].release()

    def get_batch(
        self,
        max_items: int,
        timeout: Optional[float] = None,
        poll_s: float = 0.001,
    ) -> List[Any]:
        """Drain up to ``max_items`` in ONE round trip; when the remote
        queue is momentarily empty the SERVER blocks for the first item
        (opcode 'D', bounded by ``timeout`` and the server cap), with
        ``poll_s`` pacing retries between bounded waits."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        if self._stream is not None:
            while True:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return []
                out = self._stream.get_batch_stream(max_items, remaining)
                if out:
                    return out
                if deadline is not None and time.monotonic() >= deadline:
                    return []
        while True:
            out = self._get_batch_once(
                max_items, deadline, self._server_wait(deadline)
            )
            if out:
                return out
            if deadline is not None and time.monotonic() >= deadline:
                return []
            time.sleep(poll_s)

    def _get_batch_once(
        self,
        max_items: int,
        deadline: Optional[float] = None,
        server_wait_s: float = 0.0,
    ) -> List[Any]:
        def _do():
            if server_wait_s > 0:
                self._sock.sendall(
                    _OP_GET_BATCH_WAIT
                    + struct.pack("<II", max_items, int(server_wait_s * 1000))
                )
            else:
                self._sock.sendall(_OP_GET_BATCH + struct.pack("<I", max_items))
            self._status()
            (count,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            out = []
            for _ in range(count):
                (n,) = struct.unpack("<I", _recv_exact(self._sock, 4))
                out.append(_recv_payload(self._sock, n, self._pool))
            return out

        with self._lock:
            return self._retrying(_do, deadline)

    def put_batch(self, items: List[Any]) -> int:
        """Send N items in ONE round trip (opcode 'Q'); returns how many
        the server accepted (a full queue truncates — retry the rest).
        Scatter-gather like :meth:`put`: N frames leave straight from
        their panel memory, never assembled into one request buffer."""
        if self._stream is not None:
            # a request/response opcode on the streamed socket would
            # desync the push framing (the server kills anything but
            # ack/BYE there) — route over the side channel like every
            # other non-stream op; the protocol-dialogue checker pins
            # this guard
            return self._side_channel().put_batch(items)

        # the whole request assembles INSIDE the retried exchange so a
        # post-reconnect retry re-encodes under the renegotiated codec
        def _do():
            parts = [_OP_PUT_BATCH + struct.pack("<I", len(items))]
            leases = []
            try:
                for item in items:
                    item_parts, clease = self._encode_for_wire(item)
                    if clease is not None:
                        leases.append(clease)
                    n = _parts_nbytes(item_parts)
                    if n > _MAX_PAYLOAD:  # fail fast
                        raise ValueError(
                            f"payload of {n} bytes exceeds wire maximum "
                            f"{_MAX_PAYLOAD}"
                        )
                    parts.append(struct.pack("<I", n))
                    parts.extend(item_parts)
                _sendmsg_all(self._sock, parts)
                self._status()
                (accepted,) = struct.unpack("<I", _recv_exact(self._sock, 4))
                return accepted
            finally:
                for clease in leases:
                    clease.release()

        with self._lock:
            return self._retrying(_do)

    def disconnect(self):
        side, self._side = self._side, None
        if side is not None:
            side.disconnect()
        sock = getattr(self, "_sock", None)  # absent if the first dial failed
        if sock is None:
            return
        # BYE acks the last response: without it the server would treat
        # the close as a mid-delivery death and re-enqueue (duplicate) the
        # last frame this client already consumed. A windowed-put tail is
        # drained first (bounded — this is teardown, not delivery), and a
        # streamed connection sends its final cumulative ack so consumed
        # frames are not redelivered to a sibling.
        try:
            with self._lock:
                if self._put_unacked:
                    self._drain_put_acks(
                        0, time.monotonic() + self.PROBE_DEADLINE_S
                    )
                if self._stream is not None:
                    self._stream.ack_consumed()
                sock.sendall(_OP_BYE)
        except (OSError, TransportClosed):
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _status(self) -> bytes:
        # guarded-by-caller: _lock
        st = _recv_exact(self._sock, 1)
        if st == _ST_CLOSED:
            raise TransportClosed(f"remote queue at {self.host}:{self.port} is closed")
        if st == _ST_ERR:
            raise RuntimeError("protocol error")
        return st


class TcpStreamReader:
    """Client half of stream mode: reads server-pushed frames off a
    subscribed :class:`TcpQueueClient` connection and replenishes
    credits with cumulative acks AS IT CONSUMES — a frame is acked when
    the caller comes back for the next one, the exact point the
    request/response mode took its implicit ACK, so crash-redelivery
    granularity is unchanged (frames returned-but-unacked redeliver to
    another consumer; duplicates possible, loss never).

    Deliberately a separate class from TcpQueueClient: the blocking-
    hot-path lint checker audits everything reachable from the batcher
    drain loop, and this is that path (the client class itself is
    excluded as deadline-audited). Every READ here is bounded by the
    caller's timeout or the client's socket timeout, and there are no
    sleeps. The one wait that deliberately exceeds a read timeout is a
    mid-stream RECONNECT: it runs the client's full backoff envelope
    (bounded by reconnect_tries x (backoff + dial timeout), NOT by the
    read's pacing timeout) because a streamed subscription is a
    long-lived attachment — bounding recovery by a 10 ms poll-pacing
    timeout would turn every server restart into a consumer exit. All
    methods run under the owning client's lock; probes that must not
    wait behind it use their own connections (DataReader.open_monitor)."""

    def __init__(self, client: TcpQueueClient, window: int):
        self._c = client
        self.window = window
        self.delivered_seq = 0  # last seq returned to the caller
        self.acked_seq = 0  # last seq cumulatively acked to the server
        self._dead: Optional[str] = None  # 'X' seen: the stream is over

    def reset_after_reconnect(self):
        """The server assigns sequence numbers per connection: a fresh
        subscription restarts at 1, and anything the dead connection had
        unacked was re-enqueued server-side (it redelivers here)."""
        self.delivered_seq = 0
        self.acked_seq = 0

    # -- protocol primitives (caller holds the client lock) ---------------
    def ack_consumed(self):
        """Cumulative credit replenish for everything already returned."""
        if self.delivered_seq > self.acked_seq:
            self._c._sock.sendall(
                _OP_STREAM_ACK + struct.pack("<Q", self.delivered_seq)
            )
            self.acked_seq = self.delivered_seq
            STREAM.acked_msg()

    def _read_push(self, first_timeout: Optional[float]):
        """One pushed frame, or EMPTY when no push arrives within
        ``first_timeout`` (0 = only take what is already buffered). The
        timeout applies to the leading status byte alone; once a push
        has started, the remainder is read under the client's patient
        timeout (a timeout mid-message would desync the framing)."""
        if self._dead is not None:
            raise TransportClosed(self._dead)
        sock = self._c._sock
        try:
            sock.settimeout(first_timeout)  # 0 -> non-blocking probe
            try:
                st = _recv_exact(sock, 1)
            except (BlockingIOError, socket.timeout):
                return EMPTY
        finally:
            try:
                sock.settimeout(self._c._timeout_s)
            except OSError:
                pass
        if st == _ST_CLOSED:
            self._dead = (
                f"remote queue at {self._c.host}:{self._c.port} is closed"
            )
            raise TransportClosed(self._dead)
        if st != _ST_OK:
            raise RuntimeError(
                f"protocol error on streamed connection: {st!r}"
            )
        seq, n = struct.unpack("<QI", _recv_exact(sock, 12))
        item = _recv_payload(sock, n, self._c._pool)
        self.delivered_seq = seq
        return item

    # -- drain surface -----------------------------------------------------
    def get_batch_stream(
        self, max_items: int, timeout: Optional[float] = None
    ) -> List[Any]:
        """Up to ``max_items`` pushed frames: ack everything previously
        returned (credit replenish), block up to ``timeout`` for the
        first frame, then take whatever is already buffered without
        blocking. Returns [] on timeout — and after a mid-stream
        reconnect (the fresh subscription's redeliveries arrive on the
        next call)."""
        c = self._c
        with c._lock:
            try:
                self.ack_consumed()
                first = self._read_push(timeout)
            except TransportClosed:
                raise
            except (ConnectionError, socket.timeout, OSError) as e:
                c._reconnect(e)  # re-subscribes; unacked frames redeliver
                return []
            if first is EMPTY:
                return []
            out = [first]
            while len(out) < int(max_items):
                try:
                    nxt = self._read_push(0.0)
                except TransportClosed:
                    break  # deliver what we hold; the next call raises
                except (ConnectionError, socket.timeout, OSError):
                    break  # the next call reconnects
                if nxt is EMPTY:
                    break
                out.append(nxt)
            return out

    def get_wait_stream(self, timeout: Optional[float] = None) -> Any:
        batch = self.get_batch_stream(1, timeout)
        return batch[0] if batch else EMPTY
