"""Cross-host transport: a TCP queue server + client with the transport
contract.

The reference's cross-node data plane is Ray's object store + actor RPC
(SURVEY.md §5 "Distributed communication backend"). Here the cross-host
hop is an explicit length-prefixed TCP protocol over any local queue
(RingBuffer or ShmRingBuffer): producers on ingest nodes connect and PUT,
consumers on TPU hosts connect and GET. One server per queue — the same
single-serialization-point design as the reference's actor, without the
object-store copy.

Wire protocol (all little-endian):
    request:  op:u8 ('P'|'G'|'S'|'C') + [P only] len:u32 + payload
              'B' (get-batch) + max_items:u32
              'Q' (put-batch) + count:u32 + count x (len:u32 + payload)
              'O' (open) + ns_len:u16 + ns + name_len:u16 + name
                         + maxsize:u32
    response: status:u8 ('1' ok | '0' full/empty | 'X' closed | 'E' error)
              + [G ok] len:u32 + payload   + [S] size:u32
              + [B ok] count:u32 + count x (len:u32 + payload)
              + [Q ok] accepted:u32

The batch opcodes exist so a cross-host consumer drains N records per
round trip instead of reintroducing the reference's one-RPC-per-event
bottleneck (reference ``data_reader.py:35``, SURVEY.md §3.1) over the
network hop.

The OPEN opcode makes one server a *cluster registry of named queues* —
Ray-GCS parity for the only transport that crosses hosts (reference
``shared_queue.py:33-38`` registers the actor by (namespace, name);
``data_reader.py:20`` resolves it the same way). OPEN get-or-creates the
(namespace, queue_name) queue server-side and binds this connection to
it; connections that never send OPEN use the server's default queue
(back-compat with single-queue deployments). Named queues are detached:
they live until the server process stops, regardless of which client
created them (parity: ``lifetime="detached"``, ``shared_queue.py:35``).

Payloads reuse the shm codec (records wire format / tagged pickle).

In-flight items are never dropped on a consumer crash: if the connection
dies between the queue pop and the response write, the server re-enqueues
the popped item(s).
"""

from __future__ import annotations

import contextlib
import socket
import struct
import threading
from typing import Any, List, Optional

from psana_ray_tpu.transport.registry import TransportClosed
from psana_ray_tpu.transport.ring import EMPTY, RingBuffer
from psana_ray_tpu.transport.codec import decode_payload as _decode, encode_payload as _encode

_OP_PUT = b"P"
_OP_GET = b"G"
_OP_SIZE = b"S"
_OP_CLOSE = b"C"
_OP_GET_BATCH = b"B"
_OP_PUT_BATCH = b"Q"
_OP_OPEN = b"O"
_ST_OK = b"1"
_ST_NO = b"0"
_ST_CLOSED = b"X"
_ST_ERR = b"E"



def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


class TcpQueueServer:
    """Serve queues over TCP: one default queue plus any number of named
    queues that clients OPEN by (namespace, queue_name) — see the module
    docstring. Start with ``serve_background()``."""

    def __init__(
        self,
        queue=None,
        host: str = "0.0.0.0",
        port: int = 0,
        maxsize: int = 100,
        queue_factory=None,
    ):
        self.queue = queue if queue is not None else RingBuffer(maxsize)
        self._maxsize = maxsize
        # factory for OPENed queues: (namespace, name, maxsize) -> queue.
        # Default in-process rings; a server may hand out shm-backed rings
        # instead so local clients can bypass TCP (queue_server.py --shm)
        self._queue_factory = queue_factory or (
            lambda ns, name, maxsize: RingBuffer(maxsize, name=f"{ns}__{name}")
        )
        self._queues = {}  # (namespace, name) -> queue
        self._queues_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._draining = False
        self._threads: List[threading.Thread] = []

    def open_named(self, namespace: str, queue_name: str, maxsize: Optional[int] = None):
        """Get-or-create the named queue (the OPEN opcode server-side;
        also callable in-process, e.g. for a host-local consumer of a
        queue remote producers feed over TCP)."""
        key = (namespace, queue_name)
        with self._queues_lock:
            q = self._queues.get(key)
            if q is None:
                q = self._queue_factory(namespace, queue_name, maxsize or self._maxsize)
                self._queues[key] = q
            return q

    def named_queues(self) -> List[tuple]:
        with self._queues_lock:
            return sorted(self._queues)

    def all_queues(self) -> List[Any]:
        with self._queues_lock:  # snapshot: OPENs race with shutdown
            return [self.queue, *self._queues.values()]

    def begin_drain(self):
        """Stop accepting PUTs on every queue (producers see the dead-queue
        signal and exit cleanly) while GETs keep serving — the graceful
        half of teardown: consumers drain in-flight frames instead of
        losing them to an abrupt ``close_all`` (the reference's ``ray
        stop`` kills the actor with whatever the deque still holds).
        Propagates to the backing queues themselves so producers that
        BYPASS TCP (shm-backed deployments, queue_server --shm) are
        refused too, not just the ones speaking the wire protocol."""
        self._draining = True
        for q in self.all_queues():
            drain = getattr(q, "begin_drain", None)
            if drain is not None:
                try:
                    drain()
                except Exception:
                    pass

    @property
    def draining(self) -> bool:
        return self._draining

    def depth(self) -> int:
        """Total items still queued across the default + named queues."""
        total = 0
        for q in self.all_queues():
            try:
                total += q.size()
            except Exception:
                pass
        return total

    def close_all(self):
        """Close the default + every named queue (server teardown: every
        blocked client must observe a dead transport, ``ray stop`` parity)."""
        for q in self.all_queues():
            try:
                q.close()
            except Exception:
                pass

    def serve_background(self) -> "TcpQueueServer":
        t = threading.Thread(target=self._accept_loop, daemon=True, name="tcp-queue-accept")
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # prune finished connection threads — the server is a
            # long-lived service (queue_server.py) and must not grow
            # unboundedly across client reconnects
            self._threads = [t for t in self._threads if t.is_alive()]
            t = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _requeue(self, queue, items):
        """Put back items popped but never delivered (the client connection
        died mid-response) via the shared recovery path: queue HEAD so they
        precede any EOS markers already enqueued (a tally-driven consumer
        would otherwise stop without reading them), timed tail retries with
        a logged drop for backings without ``put_front`` (shm ring)."""
        from psana_ray_tpu.transport.recovery import return_to_queue

        return_to_queue(queue, items, what="in-flight frame")

    def _serve_conn(self, conn: socket.socket):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        queue = self.queue  # rebound by OPEN; default-queue back-compat
        in_flight: List[Any] = []  # popped items whose response is pending
        try:
            while not self._stop.is_set():
                op = _recv_exact(conn, 1)
                try:
                    if op == _OP_PUT:
                        (n,) = struct.unpack("<I", _recv_exact(conn, 4))
                        payload = _recv_exact(conn, n)  # read BEFORE any
                        if self._draining:              # refusal: no desync
                            conn.sendall(_ST_CLOSED)
                            continue
                        ok = queue.put(_decode(payload))
                        conn.sendall(_ST_OK if ok else _ST_NO)
                    elif op == _OP_GET:
                        item = queue.get()
                        if item is EMPTY:
                            conn.sendall(_ST_NO)
                        else:
                            in_flight = [item]
                            payload = _encode(item)
                            conn.sendall(_ST_OK + struct.pack("<I", len(payload)) + payload)
                            in_flight = []
                    elif op == _OP_GET_BATCH:
                        (max_items,) = struct.unpack("<I", _recv_exact(conn, 4))
                        items = queue.get_batch(min(max_items, 4096), timeout=0.0)
                        in_flight = list(items)
                        parts = [_ST_OK, struct.pack("<I", len(items))]
                        for item in items:
                            payload = _encode(item)
                            parts.append(struct.pack("<I", len(payload)))
                            parts.append(payload)
                        conn.sendall(b"".join(parts))
                        in_flight = []
                    elif op == _OP_PUT_BATCH:
                        # read the WHOLE request before touching the queue:
                        # an error mid-put (closed transport) must not leave
                        # half the request unread and desync the stream
                        (count,) = struct.unpack("<I", _recv_exact(conn, 4))
                        payloads = []
                        for _ in range(count):
                            (n,) = struct.unpack("<I", _recv_exact(conn, 4))
                            payloads.append(_recv_exact(conn, n))
                        if self._draining:
                            conn.sendall(_ST_CLOSED)
                            continue
                        accepted = 0
                        for payload in payloads:
                            if not queue.put(_decode(payload)):
                                break  # full: accepted prefix only (FIFO)
                            accepted += 1
                        conn.sendall(_ST_OK + struct.pack("<I", accepted))
                    elif op == _OP_SIZE:
                        conn.sendall(_ST_OK + struct.pack("<I", queue.size()))
                    elif op == _OP_CLOSE:
                        queue.close()
                        conn.sendall(_ST_OK)
                    elif op == _OP_OPEN:
                        (ns_len,) = struct.unpack("<H", _recv_exact(conn, 2))
                        ns = _recv_exact(conn, ns_len).decode()
                        (nm_len,) = struct.unpack("<H", _recv_exact(conn, 2))
                        nm = _recv_exact(conn, nm_len).decode()
                        (maxsize,) = struct.unpack("<I", _recv_exact(conn, 4))
                        queue = self.open_named(ns, nm, maxsize or None)
                        conn.sendall(_ST_OK)
                    else:
                        conn.sendall(_ST_ERR)
                        return
                except TransportClosed:
                    conn.sendall(_ST_CLOSED)
        except (ConnectionError, OSError):
            self._requeue(queue, in_flight)
        finally:
            conn.close()

    def shutdown(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class TcpQueueClient:
    """Client with the transport contract (put/get/size/get_wait/...).

    A dead server (killed process, dropped connection) surfaces as
    :class:`TransportClosed` from every contract method — the same signal a
    gracefully closed queue sends — so consumers' dead-transport handling
    (``DataReaderError``, batcher tail-flush) works for both (parity role:
    ``RayActorError``, reference ``data_reader.py:36-37``)."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 30.0,
        namespace: Optional[str] = None,
        queue_name: Optional[str] = None,
        maxsize: int = 0,
    ):
        self.host, self.port = host, port
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        if namespace is not None or queue_name is not None:
            self.open(namespace or "default", queue_name or "default", maxsize)

    def open(self, namespace: str, queue_name: str, maxsize: int = 0):
        """Bind this connection to the server-side queue named
        ``(namespace, queue_name)``, get-or-creating it (``maxsize`` is
        used only on create; 0 = server default). Ray-GCS named-actor
        parity (reference ``shared_queue.py:33-38``, ``data_reader.py:20``)."""
        ns, nm = namespace.encode(), queue_name.encode()
        with self._lock, self._io():
            self._sock.sendall(
                _OP_OPEN
                + struct.pack("<H", len(ns)) + ns
                + struct.pack("<H", len(nm)) + nm
                + struct.pack("<I", maxsize)
            )
            self._status()

    @contextlib.contextmanager
    def _io(self):
        """Map raw socket failures to TransportClosed."""
        try:
            yield
        except (ConnectionError, socket.timeout, OSError) as e:
            raise TransportClosed(
                f"connection to queue server {self.host}:{self.port} died: {e}"
            ) from e

    # -- contract ---------------------------------------------------------
    def put(self, item: Any) -> bool:
        payload = _encode(item)
        with self._lock, self._io():
            self._sock.sendall(_OP_PUT + struct.pack("<I", len(payload)) + payload)
            return self._status() == _ST_OK

    def get(self) -> Any:
        with self._lock, self._io():
            self._sock.sendall(_OP_GET)
            st = self._status()
            if st == _ST_NO:
                return EMPTY
            (n,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            return _decode(_recv_exact(self._sock, n))

    def size(self) -> int:
        with self._lock, self._io():
            self._sock.sendall(_OP_SIZE)
            st = self._status()
            (n,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            return n

    def close_remote(self):
        """Close the remote queue (fault-injection / teardown)."""
        with self._lock, self._io():
            self._sock.sendall(_OP_CLOSE)
            self._status()

    # -- blocking helpers (same surface as RingBuffer) --------------------
    def get_wait(self, timeout: Optional[float] = None, poll_s: float = 0.001) -> Any:
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            item = self.get()
            if item is not EMPTY:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                return EMPTY
            time.sleep(poll_s)

    def put_wait(self, item: Any, timeout: Optional[float] = None, poll_s: float = 0.001) -> bool:
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.put(item):
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    def get_batch(self, max_items: int, timeout: Optional[float] = None) -> List[Any]:
        """Drain up to ``max_items`` in ONE round trip (opcode 'B'); polls
        until ``timeout`` when the remote queue is momentarily empty."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            out = self._get_batch_once(max_items)
            if out:
                return out
            if deadline is not None and time.monotonic() >= deadline:
                return []
            time.sleep(0.001)

    def _get_batch_once(self, max_items: int) -> List[Any]:
        with self._lock, self._io():
            self._sock.sendall(_OP_GET_BATCH + struct.pack("<I", max_items))
            self._status()
            (count,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            out = []
            for _ in range(count):
                (n,) = struct.unpack("<I", _recv_exact(self._sock, 4))
                out.append(_decode(_recv_exact(self._sock, n)))
            return out

    def put_batch(self, items: List[Any]) -> int:
        """Send N items in ONE round trip (opcode 'Q'); returns how many
        the server accepted (a full queue truncates — retry the rest)."""
        payloads = [_encode(i) for i in items]
        parts = [_OP_PUT_BATCH, struct.pack("<I", len(payloads))]
        for p in payloads:
            parts.append(struct.pack("<I", len(p)))
            parts.append(p)
        with self._lock, self._io():
            self._sock.sendall(b"".join(parts))
            self._status()
            (accepted,) = struct.unpack("<I", _recv_exact(self._sock, 4))
            return accepted

    def disconnect(self):
        try:
            self._sock.close()
        except OSError:
            pass

    def _status(self) -> bytes:
        st = _recv_exact(self._sock, 1)
        if st == _ST_CLOSED:
            raise TransportClosed(f"remote queue at {self.host}:{self.port} is closed")
        if st == _ST_ERR:
            raise RuntimeError("protocol error")
        return st
