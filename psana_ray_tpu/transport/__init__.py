"""Bounded, backpressured transports.

Parity surface (reference ``shared_queue.py:9-31``): ``put(item) -> bool``
(False when full, never silently drops), ``get() -> item | EMPTY``
(non-destructive failure), ``size() -> int``. Plus what the reference lacks:
a typed EOS marker distinct from "empty", blocking variants with timeouts,
and batched gets for the TPU infeed.

Variants:
- :class:`RingBuffer` — in-process, thread-safe (unit tests, single-host runs)
- cross-process shared-memory and cross-host TCP rings live in
  ``transport.shm_ring`` / ``transport.tcp`` as they land.
"""

from psana_ray_tpu.transport.ring import EMPTY, FULL, RingBuffer  # noqa: F401
from psana_ray_tpu.transport.backoff import BackoffPolicy  # noqa: F401
from psana_ray_tpu.transport.registry import (  # noqa: F401
    Registry,
    RendezvousTimeout,
    TransportClosed,
    TransportWedged,
)
