# lint: hot-path
"""Event-loop TCP queue server: one epoll loop, thousands of streamed
consumers (ISSUE 6).

The thread-per-connection server (removed in ISSUE 7 after one release
behind ``mode="threads"``) was fine at tens of consumers and dead at
thousands: a thread stack (plus an ack-reader thread per streamed
subscriber), GIL contention across serve threads, and lock convoys on
the shared queue maps. PR 5's server-push streaming already removed the
request/response coupling, so the relay is shaped like an event loop —
this module is that loop, and since ISSUE 7 it is THE server.

Design:

- ONE thread runs a ``selectors.DefaultSelector`` (epoll on Linux)
  readiness loop: non-blocking accept, non-blocking incremental reads,
  non-blocking scatter-gather writes with EPOLLOUT-driven partial-send
  resumption. Thread count is independent of connection count; memory
  is O(connections x small struct).
- Each connection is a :class:`_EvConn` state machine over all 22
  opcodes of the wire protocol (the opcode constants and
  part-gathering helpers are imported from ``transport.tcp``, so the
  wire format cannot fork). Reads land incrementally: control
  fields into a per-connection reused scratch buffer, payloads straight
  into pooled ``recv_into`` leases (the zero-copy datapath of ISSUE 2
  is unchanged — a PUT's pooled buffer is the very memory a later
  push/GET response streams from).
- Blocking waits become deferred state, not parked threads: a 'D'
  (bounded get-batch) against an empty queue, a 'U' (bounded put)
  against a full queue, a 'W' (windowed put) enqueue under
  backpressure, and a stream with an exhausted credit window all park
  the connection as a *waiter* on its queue. Waiters are served by the
  pump when queue state changes (an in-loop enqueue/dequeue, a
  RingBuffer change listener poking the loop's waker pipe, or — for
  backings without listeners, e.g. shm rings fed by other processes —
  a short poll tick), and bounded waits expire off a timer heap.
- Delivery contract parity: popped items ride ``conn.in_flight`` until
  the next opcode (implicit ACK) or BYE, and re-enqueue at queue head
  when the connection dies first; stream pushes ride the per-connection
  unacked window and redeliver the exact unacked tail on death.
  At-least-once, duplicates possible, silent loss never — the same
  words as the threaded server because it is the same contract.

While a connection has a deferred op outstanding, its reads pause (one
outstanding request per connection — anything already pipelined waits
in the kernel buffer) with a 1-byte ``MSG_PEEK`` probe keeping EOF
detection alive, mirroring the threaded server's ``_peer_hung_up``
probe during blocking enqueues.

Everything here must stay non-blocking: the ``event-loop-blocking``
lint checker roots its call graph at :meth:`EventLoop.run` and bans
``time.sleep``, the blocking send/recv helpers, bare ``acquire()``,
unbounded joins and unbounded ``Condition.wait`` from everything
reachable.
"""

from __future__ import annotations

import heapq
import json
import os
import selectors
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.obs.registry import federation_payload as _metrics_rpc_payload
from psana_ray_tpu.obs.tracing import TRACER
from psana_ray_tpu.transport.registry import TransportClosed
from psana_ray_tpu.transport.ring import EMPTY
from psana_ray_tpu.transport.codec import (
    CODEC_NONE,
    CODEC_STATS,
    decode_payload as _decode,
    encode_for_wire as _wire_encode,
    negotiate_codec,
    payload_nbytes as _parts_nbytes,
)
from psana_ray_tpu.storage.durable import SpilledRecord
from psana_ray_tpu.storage.log import COMMIT_DELIVERED
from psana_ray_tpu.transport.splice import (
    FileSpan,
    SPLICE,
    fallback_errno as _splice_fallback_errno,
    sendfile_capable as _sendfile_capable,
)
from psana_ray_tpu.transport.workers import MIGRATE_GRACE_S, MIGRATE_RETRY_S
from psana_ray_tpu.transport.tcp import (
    _MAX_PAYLOAD,
    _OP_ANCHOR,
    _OP_BYE,
    _OP_CLOSE,
    _OP_CLUSTER,
    _OP_CODEC,
    _OP_COMMIT,
    _OP_GET,
    _OP_GET_BATCH,
    _OP_GET_BATCH_WAIT,
    _OP_OPEN,
    _OP_PUT,
    _OP_PUT_BATCH,
    _OP_PROMOTE,
    _OP_PUT_SEQ,
    _OP_PUT_WAIT,
    _OP_REPLAY,
    _OP_REPL_APPEND,
    _OP_REPL_OPEN,
    _OP_SIZE,
    _OP_STATS,
    _OP_STREAM,
    _OP_STREAM_ACK,
    _SENDMSG_IOV,
    _SERVER_WAIT_CAP_S,
    _REPL_NO_FLOOR,
    _ST_CLOSED,
    _ST_ERR,
    _ST_NO,
    _ST_OK,
    _emit_relay_spans,
    _gather_parts,
    _queue_stats_payload,
    _refuse_conn,
    _stamp_relay_arrival,
    STREAM,
)

# Pump cadence for queues WITHOUT a change listener (shm rings fed by
# other processes): waiters are re-checked this often. Queues with a
# listener (RingBuffer) poke the waker pipe on every change, so their
# tick is only a safety net.
POLL_TICK_S = 0.02
LISTENED_TICK_S = 0.25
IDLE_TICK_S = 0.5
# liveness re-probe cadence for parked connections whose reads are
# paused behind pipelined bytes — the same 0.5 s dead-peer detection
# slice the threaded server's _peer_hung_up loop used
PROBE_INTERVAL_S = 0.5
# max frames popped per stream-waiter visit — fairness bound so one
# wide-window subscriber cannot monopolize a pump pass
_STREAM_POP_MAX = 64

# weighted deficit round-robin (ISSUE 12): frames of deficit each
# tenant earns per replenish round, per unit of weight. Small enough
# that weight shares converge within a few hundred frames; large
# enough that a weight-1 tenant still fills a whole max-size batch
_WDRR_QUANTUM = 8
_TENANT_DEFAULT = "default"
_TENANT_WEIGHT_MAX = 64


class _Wdrr:
    """Per-queue weighted-deficit state for the stream pump: streams
    sharing a queue are served in arrival rotation, but each pop is
    capped by the connection's TENANT deficit. A replenish round hands
    out ``_WDRR_QUANTUM`` frames PER WAITING STREAM CONNECTION, split
    across tenants in proportion to weight — so a tenant's share is
    weight-proportional no matter how many sockets or credits it
    brings (one greedy tenant cannot starve the rest), while the
    round's total volume scales with the fleet (1024 single-tenant
    subscribers keep the pre-ISSUE-12 per-pass throughput: their one
    shared budget is 1024 x quantum, not 1 x). Loop-thread-only state:
    no lock."""

    __slots__ = ("deficit",)

    def __init__(self):
        self.deficit: Dict[str, float] = {}

    def allowance(self, tenant: str) -> float:
        return self.deficit.get(tenant, 0.0)

    def charge(self, tenant: str, n: int) -> None:
        self.deficit[tenant] = self.deficit.get(tenant, 0.0) - n

    def all_dry(self, tenant_weights: Dict[str, int]) -> bool:
        """No waiting tenant can pop even one frame — time for a round."""
        return all(self.deficit.get(t, 0.0) < 1.0 for t in tenant_weights)

    def replenish(self, tenant_weights: Dict[str, int], n_conns: int) -> None:
        """A new round: ``quantum * n_conns`` total frames of deficit,
        split by weight share, capped at two rounds of credit (bursts
        must not bank unbounded catch-up); tenants that left are
        dropped."""
        if not tenant_weights:
            return
        for t in list(self.deficit):
            if t not in tenant_weights:
                del self.deficit[t]
        total = float(_WDRR_QUANTUM * max(1, n_conns))
        sum_w = sum(tenant_weights.values())
        for t, w in tenant_weights.items():
            earn = max(1.0, total * w / sum_w)
            self.deficit[t] = min(
                2.0 * earn, max(0.0, self.deficit.get(t, 0.0)) + earn
            )


def _stream_tenant_weights(get_waiters) -> Tuple[Dict[str, int], int]:
    """(tenant -> weight, live stream-conn count) over one queue's
    waiters (several connections may share a tenant; the LARGEST
    advertised weight wins — a tenant's share is per tenant, not per
    socket)."""
    out: Dict[str, int] = {}
    n = 0
    for conn in get_waiters:
        if conn.stream is None or conn.closed:
            continue
        n += 1
        w = out.get(conn.tenant, 0)
        if conn.weight > w:
            out[conn.tenant] = conn.weight
    return out, n


class EvLoopTelemetry:
    """Loop-health gauges for the event-loop server (obs source
    ``evloop``): connection counts, admission refusals, and loop lag —
    how long one dispatch pass holds the loop and how late bounded-wait
    timers fire. One process-wide instance (:data:`EVLOOP`), registered
    in the default MetricsRegistry on first loop start."""

    def __init__(self):
        self._lock = threading.Lock()
        self._registered = False  # guarded-by: _lock
        self.connections = 0  # guarded-by: _lock
        self.connections_peak = 0  # guarded-by: _lock
        self.accepted_total = 0  # guarded-by: _lock
        self.refused_total = 0  # guarded-by: _lock
        self.loops_total = 0  # guarded-by: _lock
        self.dispatch_ms_last = 0.0  # guarded-by: _lock
        self.dispatch_ms_max = 0.0  # guarded-by: _lock
        self.dispatch_ms_ewma = 0.0  # guarded-by: _lock
        self.timer_lag_ms_max = 0.0  # guarded-by: _lock
        # busy fraction = time-in-dispatch / (dispatch + select): the
        # loop-saturation signal ROADMAP item 4's elasticity controller
        # keys on — 1.0 means the loop never reaches select() idle-wait
        self.dispatch_s_total = 0.0  # guarded-by: _lock
        self.select_s_total = 0.0  # guarded-by: _lock
        self.busy_frac_ewma = 0.0  # guarded-by: _lock

    def ensure_registered(self):
        with self._lock:
            if self._registered:
                return
            self._registered = True
        try:
            from psana_ray_tpu.obs import MetricsRegistry

            MetricsRegistry.default().register("evloop", self)
        except Exception:  # obs optional: transport must work without it
            pass

    def conn_opened(self):
        with self._lock:
            self.accepted_total += 1
            self.connections += 1
            if self.connections > self.connections_peak:
                self.connections_peak = self.connections

    def conn_closed(self):
        with self._lock:
            self.connections -= 1

    def refused(self):
        with self._lock:
            self.refused_total += 1

    def loop_pass(self, dispatch_ms: float, select_ms: float = 0.0):
        with self._lock:
            self.loops_total += 1
            self.dispatch_ms_last = dispatch_ms
            if dispatch_ms > self.dispatch_ms_max:
                self.dispatch_ms_max = dispatch_ms
            self.dispatch_ms_ewma += 0.05 * (dispatch_ms - self.dispatch_ms_ewma)
            self.dispatch_s_total += dispatch_ms * 1e-3
            self.select_s_total += select_ms * 1e-3
            span_ms = dispatch_ms + select_ms
            if span_ms > 0.0:
                frac = dispatch_ms / span_ms
                self.busy_frac_ewma += 0.05 * (frac - self.busy_frac_ewma)

    def timer_lag(self, lag_ms: float):
        with self._lock:
            if lag_ms > self.timer_lag_ms_max:
                self.timer_lag_ms_max = lag_ms

    def stats(self) -> dict:
        with self._lock:
            return {
                "connections": self.connections,
                "connections_peak": self.connections_peak,
                "accepted_total": self.accepted_total,
                "refused_total": self.refused_total,
                "loops_total": self.loops_total,
                "dispatch_ms_last": round(self.dispatch_ms_last, 3),
                "dispatch_ms_max": round(self.dispatch_ms_max, 3),
                "dispatch_ms_ewma": round(self.dispatch_ms_ewma, 3),
                "timer_lag_ms_max": round(self.timer_lag_ms_max, 3),
                "busy_frac": round(
                    self.dispatch_s_total
                    / (self.dispatch_s_total + self.select_s_total)
                    if (self.dispatch_s_total + self.select_s_total) > 0.0
                    else 0.0,
                    6,
                ),
                "busy_frac_ewma": round(self.busy_frac_ewma, 6),
            }

    # obs registry source protocol
    def snapshot(self) -> dict:
        return self.stats()


EVLOOP = EvLoopTelemetry()


class _StreamState:
    """Per-connection stream-mode state ('M'): the credit window and the
    unacked redelivery tail that the threaded server kept in a dedicated
    serve thread + ack-reader thread, folded into the connection."""

    __slots__ = ("window", "seq", "acked", "unacked", "queue_closed")

    def __init__(self, window: int):
        self.window = window
        self.seq = 0
        self.acked = 0
        self.unacked: deque = deque()  # (seq, item) in push order
        self.queue_closed = False

    def budget(self) -> int:
        return self.window - (self.seq - self.acked)


class _QueueState:
    """Loop-side view of one backing queue: who is waiting on it."""

    __slots__ = (
        "queue", "get_waiters", "put_waiters", "ra_waiters", "repl",
        "listened", "unlisten", "wdrr",
    )

    def __init__(self, queue):
        self.queue = queue
        self.get_waiters: deque = deque()  # 'D' waiters + stream conns
        self.put_waiters: deque = deque()  # 'U'/'W' waiters, FIFO
        # replicated-ack-floor waiters (ISSUE 11): puts already logged
        # and enqueued whose producer ack is HELD until the follower has
        # logged them (pending kind "RA"); FIFO == offset order
        self.ra_waiters: deque = deque()
        self.repl = None  # the queue's ReplicationSender, cached
        self.listened = False
        self.unlisten = None  # callable removing the change listener
        # per-tenant weighted-deficit budgets for the stream pump
        self.wdrr = _Wdrr()


class _QueueClosedSignal(Exception):
    """Internal: the backing queue raised TransportClosed mid-pump."""


class _EvConn:
    """One connection's state machine: incremental reads, an outbound
    scatter-gather write queue, the in-flight delivery window, and
    (when subscribed) the stream credit window."""

    __slots__ = (
        "loop", "sock", "srv", "queue", "in_flight", "out", "out_bytes",
        "closing", "closed", "stream", "replay", "replica", "pending",
        "op_gen", "codec", "tenant", "weight",
        "_out_enq_total", "_out_releases",
        "_hdr", "_hdr_mv", "_target", "_need", "_got", "_cb", "_lease",
        "_want_read", "_want_write", "_mask", "_sendmsg",
        "_qb_remaining", "_qb_items", "_pw_wait_s", "_w_seq",
        "_r_from", "_v_off", "_v_floor", "_open_ns", "_open_nm",
        "_open_buf", "_no_splice", "_migration",
    )

    def __init__(self, loop: "EventLoop", sock: socket.socket, srv):
        self.loop = loop
        self.sock = sock
        self.srv = srv
        self.queue = srv.queue  # rebound by OPEN; default-queue back-compat
        # popped-but-unconfirmed deliveries: cleared at the next opcode
        # (implicit ACK), re-enqueued if the connection dies first — the
        # same delivery contract as the threaded server
        self.in_flight: List[Any] = []
        self.out: deque = deque()  # memoryview parts awaiting send
        self.out_bytes = 0
        self.closing = False  # flush remaining out bytes, then close
        self.closed = False
        self.stream: Optional[_StreamState] = None
        # negotiated wire codec ('Z', ISSUE 9): frame payloads SENT on
        # this connection compress with it (relay pass-through reuses a
        # record's cached compressed bytes when the codec matches);
        # receives are tag-driven and need no per-connection state
        self.codec = None
        # fair-share identity (ISSUE 12): set by the tenant=<name>:<w>
        # capability field on the 'Z' exchange; connections that never
        # hello share the default tenant's budget (pre-ISSUE-12 parity)
        self.tenant = _TENANT_DEFAULT
        self.weight = 1
        # compressed staging leases awaiting flush: (enqueued-bytes
        # mark, lease) released once the outbound byte counter passes
        # the mark — a lease must outlive its queued memoryview
        self._out_enq_total = 0
        self._out_releases: deque = deque()
        # durable replay cursor ('R'): when set, this connection's reads
        # serve the log non-destructively instead of popping the queue
        self.replay = None
        # replica mode ('H', ISSUE 11): when set (a _ReplicaEntry), this
        # connection is an owner's replication link — it carries only
        # 'V' appends downstream and their cumulative acks back
        self.replica = None
        self.pending: Optional[dict] = None  # deferred 'D'/'U'/'W' state
        self.op_gen = 0  # staleness guard for timer-heap entries
        self._hdr = bytearray(64)  # reused control-field scratch
        self._hdr_mv = memoryview(self._hdr)
        self._target: Optional[memoryview] = None
        self._need = 0
        self._got = 0
        self._cb = None
        self._lease = None  # pooled lease a payload is landing in
        self._want_read = False
        self._want_write = False
        self._mask = 0
        self._sendmsg = getattr(sock, "sendmsg", None)
        self._qb_remaining = 0
        self._qb_items: List[Any] = []
        self._pw_wait_s = 0.0
        self._w_seq = 0
        self._r_from = 0
        self._v_off = 0
        self._v_floor = 0
        self._open_ns = ""
        self._open_nm = ""
        self._open_buf = b""
        # set when THIS socket refused os.sendfile (TLS wrapper, exotic
        # family): spilled records materialize instead of queueing
        # spans that would each fail at the pump
        self._no_splice = False
        # multi-worker handoff in progress (ISSUE 17): {"target",
        # "ctx", "deadline"} while this connection waits to ship to the
        # queue's owning worker — reads pause, queued bytes flush first
        self._migration = None

    # -- read engine ------------------------------------------------------
    def _arm(self, mv: memoryview, cb, lease=None) -> None:
        self._lease = lease
        self._target = mv
        self._need = mv.nbytes
        self._got = 0
        self._cb = cb

    def _expect(self, n: int, cb) -> None:
        self._arm(self._hdr_mv[:n], cb)

    def _expect_payload(self, n: int, cb) -> None:
        if n > _MAX_PAYLOAD:
            raise ConnectionError(
                f"payload length {n} exceeds wire maximum {_MAX_PAYLOAD}"
            )
        lease = self.srv._pool.lease(n)
        self._arm(lease.mv, cb, lease=lease)

    def _await_op(self) -> None:
        self._expect(1, self._on_op)

    def on_readable(self) -> None:
        if self.closed or self.closing:
            return
        if self.pending is not None:
            self._probe_while_pending()
            return
        while True:
            if self._got < self._need:
                try:
                    k = self.sock.recv_into(self._target[self._got:])
                except (BlockingIOError, InterruptedError):
                    return
                if k == 0:
                    raise ConnectionError("peer closed")
                self._got += k
                if self._got < self._need:
                    continue
            cb = self._cb
            self._cb = None
            cb()
            if self.closed or self.closing or self.pending is not None:
                return
            if self._cb is None:  # handler did not arm a next read
                return

    def _probe_while_pending(self) -> None:
        """Readable while a deferred op is outstanding: either EOF (the
        peer died mid-wait — cancel the op, drop the never-enqueued
        frame, exactly like the threaded server's liveness probe) or
        pipelined bytes that must wait their turn — pause read interest
        (level-triggered epoll would spin otherwise) and schedule a
        liveness re-probe so a peer that dies AFTER pipelining is still
        detected within the probe interval, matching the threaded
        server's 0.5 s `_peer_hung_up` slices; without it a crashed
        windowed producer would pin the parked frame's lease forever
        and late-enqueue on top of its own reconnect resend."""
        try:
            k = self.sock.recv_into(self._hdr_mv[:1], 1, socket.MSG_PEEK)
        except (BlockingIOError, InterruptedError):
            return
        if k == 0:
            raise ConnectionError("peer closed while op deferred")
        self._set_interest(read=False)
        self.loop.add_liveness_probe(self)

    # -- write engine -----------------------------------------------------
    def send_parts(self, parts, release=None) -> None:
        """Queue parts for sending. ``release`` (a lease or list of
        leases backing compressed parts) is released once every byte
        queued SO FAR has left for the kernel — never while a queued
        memoryview still references the lease's buffer.

        A :class:`FileSpan` part (the kernel pass-through path) queues
        AS ITSELF — it must not pass through ``_gather_parts``, which
        would try to take a memoryview of it; byte runs between spans
        still gather/coalesce as before."""
        run: List[Any] = []
        for p in parts:
            if type(p) is FileSpan:
                if run:
                    self._enqueue_bufs(run)
                    run = []
                self.out.append(p)
                self.out_bytes += p.nbytes
                self._out_enq_total += p.nbytes
            else:
                run.append(p)
        if run:
            self._enqueue_bufs(run)
        if release is not None:
            for lease in release if isinstance(release, list) else (release,):
                self._out_releases.append((self._out_enq_total, lease))
        self.flush_out()

    def _enqueue_bufs(self, parts) -> None:
        for m in _gather_parts(parts):
            self.out.append(m)
            self.out_bytes += m.nbytes
            self._out_enq_total += m.nbytes

    def _send_control(self, b: bytes) -> None:
        self.send_parts([b])

    def flush_out(self) -> None:
        if self.closed:
            return
        try:
            while self.out:
                if type(self.out[0]) is FileSpan:
                    self._pump_span(self.out[0])
                    continue
                if self._sendmsg is not None:
                    bufs = []
                    for m in self.out:
                        if type(m) is FileSpan:
                            break  # spans splice alone, next loop pass
                        bufs.append(m)
                        if len(bufs) >= _SENDMSG_IOV:
                            break
                    sent = self._sendmsg(bufs)
                else:  # platform fallback: one part per send
                    sent = self.sock.send(self.out[0])
                if sent <= 0:
                    raise ConnectionError("peer closed during send")
                self.out_bytes -= sent
                while sent:
                    m = self.out[0]
                    if sent >= m.nbytes:
                        sent -= m.nbytes
                        self.out.popleft()
                    else:
                        self.out[0] = m[sent:]
                        sent = 0
        except (BlockingIOError, InterruptedError):
            pass
        # release compressed staging leases whose bytes have fully left
        sent_total = self._out_enq_total - self.out_bytes
        while self._out_releases and self._out_releases[0][0] <= sent_total:
            self._out_releases.popleft()[1].release()
        if not self.out and self.closing:
            self.loop.kill_conn(self, None, requeue=False)
            return
        if not self.out and self._migration is not None:
            # queued response bytes have fully left: the deferred
            # worker handoff can ship the fd now
            self.loop._try_migrate(self)
            return
        self._set_interest(write=bool(self.out))

    def _pump_span(self, span) -> None:
        """Move the head FileSpan's bytes file->socket with
        ``os.sendfile`` — the payload never enters the interpreter. On
        a non-blocking socket sendfile returns short or raises
        BlockingIOError (caught by flush_out, like a short sendmsg); a
        can't-splice-here errno downgrades THIS span (and this
        connection) to the sendmsg path by materializing the remaining
        bytes in place — degrade, never die."""
        try:
            sent = os.sendfile(
                self.sock.fileno(), span.fileno(), span.pos, span.nbytes
            )
        except (BlockingIOError, InterruptedError):
            raise
        except OSError as e:
            if _splice_fallback_errno(e):
                self._no_splice = True
                SPLICE.note_fallback(f"sendfile_errno_{e.errno}")
                self.out[0] = memoryview(span.materialize())
                return
            raise ConnectionError(f"sendfile failed: {e!r}") from e
        if sent <= 0:
            raise ConnectionError("peer closed during sendfile")
        self.out_bytes -= sent
        SPLICE.note_sendfile(sent)
        if sent >= span.nbytes:
            self.out.popleft()
            SPLICE.note_frame()
        else:
            span.advance(sent)

    # -- selector interest ------------------------------------------------
    def _set_interest(self, read: Optional[bool] = None, write: Optional[bool] = None) -> None:
        if read is not None:
            self._want_read = read
        if write is not None:
            self._want_write = write
        mask = (selectors.EVENT_READ if self._want_read else 0) | (
            selectors.EVENT_WRITE if self._want_write else 0
        )
        if mask == self._mask or self.closed:
            return
        sel = self.loop._sel
        if self._mask == 0:
            sel.register(self.sock, mask, self)
        elif mask == 0:
            sel.unregister(self.sock)
        else:
            sel.modify(self.sock, mask, self)
        self._mask = mask

    # -- deferred ops -----------------------------------------------------
    def park(self, kind: str, **state) -> None:
        self.pending = dict(state, kind=kind)
        self.op_gen += 1

    def unpark(self) -> None:
        self.pending = None
        self.op_gen += 1
        self._await_op()
        self._set_interest(read=True)

    # -- opcode dispatch --------------------------------------------------
    def _ack_in_flight(self) -> None:
        """The implicit-ACK point: a durable queue advances (and
        persists) its committed floor here; a replay cursor commits its
        group's position. Memory-only queues no-op — delivery semantics
        are unchanged where there is no log."""
        if self.in_flight:
            ack = getattr(self.queue, "ack_delivered", None)
            if ack is not None:
                ack(self.in_flight)
        if self.replay is not None:
            self.replay.commit()

    def _on_op(self) -> None:
        op = self._hdr[0]
        # previous response fully read by the peer (it can only send the
        # next request after reading the last response) — implicit ACK
        self._ack_in_flight()
        self.in_flight = []
        if self.replica is not None:
            # a replica-link connection carries only appends and BYE
            if op == _OP_REPL_APPEND[0]:
                self._expect(20, self._va_hdr)
                return
            if op == _OP_BYE[0]:
                self._begin_close()
                return
            raise ConnectionError(
                f"bad opcode {op:#04x} on replica connection"
            )
        if self.stream is not None:
            # a streamed connection carries only acks, window resizes
            # ('M' again — ISSUE 15 autotune), and BYE upstream
            if op == _OP_STREAM_ACK[0]:
                self._expect(8, self._on_stream_ack)
                return
            if op == _OP_STREAM[0]:
                self._expect(4, self._stream_resize)
                return
            if op == _OP_BYE[0]:
                self._finish_stream(clean=True)
                self._begin_close()
                return
            raise ConnectionError(
                f"bad opcode {op:#04x} on streamed connection"
            )
        wctx = self.srv.worker_ctx
        if (
            wctx is not None
            and self.queue is self.srv.queue
            and wctx.worker_id != wctx.default_owner
            and op not in _WORKER_LOCAL_OPS
        ):
            # this worker does not own the DEFAULT queue and the op
            # touches it: ship the connection to the owner. Exactly one
            # byte (the opcode) has been consumed — it rides in the
            # context; anything the client pipelined behind it is still
            # in the kernel socket buffer and travels with the fd.
            self.loop.migrate_conn(
                self, wctx.default_owner, {"kind": "op", "op": op}
            )
            return
        name = _OPS.get(op)
        if name is None:
            self._send_control(_ST_ERR)
            self._begin_close()
            return
        getattr(self, name)()

    def _begin_close(self) -> None:
        """Clean close: flush any queued response bytes, then close
        without redelivery (the peer said goodbye / protocol-erred)."""
        if self.out:
            self.closing = True
            self._set_interest(read=False, write=True)
        else:
            self.loop.kill_conn(self, None, requeue=False)

    # -- responses --------------------------------------------------------
    def _encode_item_parts(self, item):
        """codec.encode_for_wire under this connection's negotiated
        codec — the returned staging lease is handed to
        send_parts(release=...) so it outlives the queued bytes. See
        the helper for the lease/pass-through contract.

        A :class:`SpilledRecord` (lazy durable spill, ISSUE 17) short-
        circuits on an uncompressed connection: its on-disk payload IS
        the raw wire payload, so the response becomes a FileSpan the
        flush pump moves with sendfile — zero Python payload bytes.
        Compressed connections (the span can't be compressed kernel-
        side) and splice-refusing sockets materialize, which is exactly
        the pre-ISSUE-17 eager spill read."""
        if type(item) is SpilledRecord:
            if (
                self.codec is None
                and not self._no_splice
                and _sendfile_capable()
            ):
                span = item.payload_span()
                if span is not None:
                    f, pos, nbytes = span
                    return [FileSpan(f, pos, nbytes)], None
                # offset aged out of retention between unbox and send —
                # can't happen while the floor pin holds, but degrade
                # loudly rather than die if the contract ever breaks
                SPLICE.note_fallback("span_unretained")
            item = item.materialize()
        return _wire_encode(item, self.codec, self.srv._pool)

    def _respond_item(self, item) -> None:
        parts, clease = self._encode_item_parts(item)
        head = _ST_OK + struct.pack("<I", _parts_nbytes(parts))
        self.send_parts([head, *parts], release=clease)

    def _respond_batch(self, items) -> None:
        self.in_flight = list(items)
        parts: List[Any] = [_ST_OK, struct.pack("<I", len(self.in_flight))]
        leases: List[Any] = []
        try:
            for item in self.in_flight:
                item_parts, clease = self._encode_item_parts(item)
                if clease is not None:
                    leases.append(clease)
                parts.append(struct.pack("<I", _parts_nbytes(item_parts)))
                parts.extend(item_parts)
        except BaseException:
            # a mid-loop failure (allocation under pressure) must not
            # strand earlier items' staging leases: nothing was queued
            # yet, so ownership is still ours
            for clease in leases:
                clease.release()
            raise
        t_send0 = time.monotonic() if TRACER.enabled else 0.0
        self.send_parts(parts, release=leases or None)
        if TRACER.enabled:
            _emit_relay_spans(self.in_flight, t_send0)

    def _take_item(self):
        """Decode the just-received payload zero-copy off its lease.
        ``lazy=True``: a COMPRESSED frame is validated (corruption
        still dies here, where the requeue contract runs) but not
        decompressed — the relay's common case re-sends the cached
        compressed bytes verbatim and never pays codec CPU; panels
        inflate on first touch for every other destination."""
        lease = self._lease
        self._lease = None
        try:
            return _decode(lease.mv, lease=lease, lazy=True)
        except BaseException:
            lease.release()
            raise

    # -- opcode handlers --------------------------------------------------
    def _op_put(self) -> None:
        self._expect(4, self._put_hdr)

    def _put_hdr(self) -> None:
        (n,) = struct.unpack_from("<I", self._hdr)
        self._expect_payload(n, self._put_payload)

    def _try_put(self, item):
        """``queue.put`` with refusals surfaced as ANSWERS: a queue
        exception beyond TransportClosed (e.g. a durable queue rejecting
        a record larger than segment_bytes, or a disk fault) must error
        THIS request — killing the connection instead would make a
        windowed producer resend the identical poison record on every
        reconnect until its retries exhaust with a misleading
        connection-death error. Returns ``(ok, offset)`` — ``offset`` is
        the durable log offset (None for memory queues), the replicated
        ack floor's gate key — or ``(None, None)`` when a refusal was
        already answered."""
        try:
            put_offset = getattr(self.queue, "put_offset", None)
            if put_offset is not None:
                return put_offset(item)
            return self.queue.put(item), None
        except TransportClosed:
            self._send_control(_ST_CLOSED)
        except Exception:  # noqa: BLE001 — answer, don't kill the conn
            self._send_control(_ST_ERR)
        return None, None

    def _answer_put(self, parts, offset, parked: bool = False) -> None:
        """Send a successful put's reply — or HOLD it until the queue's
        replication follower has logged ``offset`` (the replicated ack
        floor, ISSUE 11: a frame is ACKed to the producer only once the
        follower has it; the sender's ack-advance pokes the loop and
        :meth:`EventLoop._pump_rack` releases the reply). ``parked``:
        the caller is resolving an existing deferred op (pump path), so
        an immediate answer must unpark instead of re-arming reads."""
        repl = self.loop.repl_sender(self.queue)
        if offset is not None and repl is not None and not repl.reached(offset):
            self.pending = {"kind": "RA", "parts": parts, "offset": offset}
            self.op_gen += 1
            self.loop.add_rack_waiter(self)
            return
        self.send_parts(parts)
        if parked:
            self.unpark()
        else:
            self._await_op()

    def _put_payload(self) -> None:
        item = self._take_item()
        if TRACER.enabled:
            _stamp_relay_arrival(item)
        if self.srv._draining:
            self._send_control(_ST_CLOSED)
        else:
            ok, offset = self._try_put(item)
            if ok:
                self.loop.queue_touched(self.queue)
                self._answer_put([_ST_OK], offset)
                return
            if ok is not None:
                self._send_control(_ST_NO)
        self._await_op()

    def _op_get(self) -> None:
        try:
            if self.replay is not None:
                items = self.replay.next_batch(1)
                item = items[0] if items else EMPTY
            else:
                item = self.queue.get()
        except TransportClosed:
            self._send_control(_ST_CLOSED)
        else:
            if item is EMPTY:
                self._send_control(_ST_NO)
            else:
                self.in_flight = [item]  # held until the next opcode
                t_send0 = time.monotonic() if TRACER.enabled else 0.0
                self._respond_item(item)
                if TRACER.enabled:
                    _emit_relay_spans(self.in_flight, t_send0)
                self.loop.queue_touched(self.queue)
        self._await_op()

    def _op_get_batch(self) -> None:
        self._expect(4, self._gb_hdr)

    def _gb_hdr(self) -> None:
        (max_items,) = struct.unpack_from("<I", self._hdr)
        try:
            items = self._read_batch(min(max_items, 4096))
        except TransportClosed:
            self._send_control(_ST_CLOSED)
        else:
            self._respond_batch(items)
            if items:
                self.loop.queue_touched(self.queue)
        self._await_op()

    def _read_batch(self, max_items: int) -> List[Any]:
        """Non-blocking read: the replay cursor when subscribed, the
        live queue otherwise."""
        if self.replay is not None:
            return self.replay.next_batch(max_items)
        return self.queue.get_batch(max_items, timeout=0.0)

    def _op_get_batch_wait(self) -> None:
        self._expect(8, self._gbw_hdr)

    def _gbw_hdr(self) -> None:
        max_items, wait_ms = struct.unpack_from("<II", self._hdr)
        max_items = min(max_items, 4096)
        wait_s = min(wait_ms / 1000.0, _SERVER_WAIT_CAP_S)
        try:
            items = self._read_batch(max_items)
        except TransportClosed:
            self._send_control(_ST_CLOSED)
            self._await_op()
            return
        if items or wait_s <= 0:
            self._respond_batch(items)
            if items:
                self.loop.queue_touched(self.queue)
            self._await_op()
            return
        # empty queue: the wait becomes timer + waiter state, not a
        # parked thread — served by the pump or expired by the timer
        self.park("D", max_items=max_items)
        self.loop.add_get_waiter(self, time.monotonic() + wait_s)

    def _op_put_wait(self) -> None:
        self._expect(8, self._pw_hdr)

    def _pw_hdr(self) -> None:
        wait_ms, n = struct.unpack_from("<II", self._hdr)
        self._pw_wait_s = min(wait_ms / 1000.0, _SERVER_WAIT_CAP_S)
        self._expect_payload(n, self._pw_payload)

    def _pw_payload(self) -> None:
        item = self._take_item()
        if TRACER.enabled:
            _stamp_relay_arrival(item)
        if self.srv._draining:
            self._send_control(_ST_CLOSED)
            self._await_op()
            return
        ok, offset = self._try_put(item)
        if ok is None:
            self._await_op()
            return
        if ok:
            self.loop.queue_touched(self.queue)
            self._answer_put([_ST_OK], offset)
            return
        if self._pw_wait_s <= 0:
            self._send_control(_ST_NO)
            self._await_op()
            return
        self.park("U", item=item)
        self.loop.add_put_waiter(self, time.monotonic() + self._pw_wait_s)

    def _op_put_seq(self) -> None:
        self._expect(12, self._ws_hdr)

    def _ws_hdr(self) -> None:
        seq, n = struct.unpack_from("<QI", self._hdr)
        self._w_seq = seq
        self._expect_payload(n, self._ws_payload)

    def _ws_payload(self) -> None:
        item = self._take_item()
        if TRACER.enabled:
            _stamp_relay_arrival(item)
        if self.srv._draining:
            self._send_control(_ST_CLOSED)
            self._await_op()
            return
        ok, offset = self._try_put(item)
        if ok is None:
            self._await_op()
            return
        if ok:
            self.loop.queue_touched(self.queue)
            self._answer_put(
                [_ST_OK + struct.pack("<Q", self._w_seq)], offset
            )
            return
        # backpressure: the ack is delayed until space frees — deferred
        # state with NO deadline (that delay IS the backpressure signal)
        self.park("W", item=item, seq=self._w_seq)
        self.loop.add_put_waiter(self, None)

    def _op_put_batch(self) -> None:
        self._expect(4, self._qb_count)

    def _qb_count(self) -> None:
        (count,) = struct.unpack_from("<I", self._hdr)
        self._qb_remaining = count
        self._qb_items = []
        self._qb_next()

    def _qb_next(self) -> None:
        if self._qb_remaining <= 0:
            self._qb_finish()
            return
        self._qb_remaining -= 1
        self._expect(4, self._qb_len)

    def _qb_len(self) -> None:
        (n,) = struct.unpack_from("<I", self._hdr)
        self._expect_payload(n, self._qb_payload)

    def _qb_payload(self) -> None:
        self._qb_items.append(self._take_item())
        self._qb_next()

    def _qb_finish(self) -> None:
        batch, self._qb_items = self._qb_items, []
        if TRACER.enabled:
            for item in batch:
                _stamp_relay_arrival(item)
        if self.srv._draining:
            self._send_control(_ST_CLOSED)
            self._await_op()
            return
        accepted = 0
        high = None  # highest durable offset (offsets are monotonic)
        for item in batch:
            ok, offset = self._try_put(item)
            if ok is None:  # refusal already answered ('X'/'E')
                self._await_op()
                return
            if not ok:
                break  # full: accepted prefix only (FIFO)
            accepted += 1
            if offset is not None:
                high = offset
        if accepted:
            self.loop.queue_touched(self.queue)
        self._answer_put([_ST_OK + struct.pack("<I", accepted)], high)

    def _op_stream(self) -> None:
        self._expect(4, self._stream_hdr)

    def _stream_hdr(self) -> None:
        (window,) = struct.unpack_from("<I", self._hdr)
        if self.replay is not None:
            # replay is pull-mode by design: stream seqs and cursor
            # offsets would need a second mapping for commit-on-ack —
            # rejected loudly rather than committed wrongly
            raise ConnectionError("stream subscribe on a replay connection")
        window = max(1, min(int(window), 4096))
        self.stream = _StreamState(window)
        STREAM.opened(window)
        FLIGHT.record("stream_open", port=self.srv.port, window=window)
        self.loop.add_stream(self)
        self._await_op()  # from here: only 'K'/'F' upstream

    def _stream_resize(self) -> None:
        """'M' on an already-streamed connection (ISSUE 15 autotune):
        resize the credit window in place — seq/acked/unacked state is
        untouched, so the budget shifts immediately and the next 'K'
        replenishes against the new window. No response, exactly like
        the subscribe."""
        (window,) = struct.unpack_from("<I", self._hdr)
        window = max(1, min(int(window), 4096))
        st = self.stream
        old, st.window = st.window, window
        if window != old:
            STREAM.resized(old, window)
            FLIGHT.record(
                "stream_resize", port=self.srv.port, old=old, window=window
            )
            if window > old:
                # new credit: the pump may have pushes waiting on budget
                self.loop.queue_touched(self.queue)
        self._await_op()

    def _on_stream_ack(self) -> None:
        (seq,) = struct.unpack_from("<Q", self._hdr)
        st = self.stream
        if seq > st.acked:
            st.acked = seq
            STREAM.acked_msg()
        acked_items = []
        while st.unacked and st.unacked[0][0] <= st.acked:
            # credit returned: lease may free
            acked_items.append(st.unacked.popleft()[1])
        if acked_items:
            STREAM.pruned(len(acked_items))
            # the stream's explicit cumulative ack is a durable queue's
            # commit point, same as the implicit next-opcode ACK
            ack = getattr(self.queue, "ack_delivered", None)
            if ack is not None:
                ack(acked_items)
        self.loop.queue_touched(self.queue)  # new credits: pump may push
        self._await_op()

    def push_stream_items(self, items) -> None:
        st = self.stream
        t_send0 = time.monotonic() if TRACER.enabled else 0.0
        parts: List[Any] = []
        leases: List[Any] = []
        try:
            for item in items:
                st.seq += 1
                st.unacked.append((st.seq, item))
                item_parts, clease = self._encode_item_parts(item)
                if clease is not None:
                    leases.append(clease)
                parts.append(
                    _ST_OK
                    + struct.pack("<QI", st.seq, _parts_nbytes(item_parts))
                )
                parts.extend(item_parts)
        except BaseException:
            for clease in leases:  # nothing queued yet: still ours
                clease.release()
            raise
        self.send_parts(parts, release=leases or None)
        STREAM.pushed(len(items))
        if TRACER.enabled:
            _emit_relay_spans(items, t_send0)

    def _finish_stream(self, clean: bool) -> None:
        """Stream teardown bookkeeping: prune what the final cumulative
        ack covered, redeliver the rest (requeue at head) unless the
        queue itself closed — exactly the threaded ``_serve_stream``
        finally-block."""
        st, self.stream = self.stream, None
        if st is None:
            return
        acked_items = []
        while st.unacked and st.unacked[0][0] <= st.acked:
            acked_items.append(st.unacked.popleft()[1])
        if acked_items:
            STREAM.pruned(len(acked_items))
            ack = getattr(self.queue, "ack_delivered", None)
            if ack is not None:  # final cumulative ack commits too
                ack(acked_items)
        lost = [item for (_s, item) in st.unacked]
        st.unacked.clear()
        if lost:
            STREAM.pruned(len(lost))
            if not st.queue_closed:
                STREAM.redelivered_n(len(lost))
                FLIGHT.record(
                    "stream_redelivery", count=len(lost), clean_bye=clean
                )
                self.loop.requeue_items(self.queue, lost)
        STREAM.closed(st.window)

    def _op_size(self) -> None:
        try:
            n = self.queue.size()
        except TransportClosed:
            self._send_control(_ST_CLOSED)
        else:
            self.send_parts([_ST_OK + struct.pack("<I", n)])
        self._await_op()

    def _op_stats(self) -> None:
        payload = json.dumps(_queue_stats_payload(self.queue)).encode()
        self.send_parts([_ST_OK + struct.pack("<I", len(payload)), payload])
        self._await_op()

    def _op_anchor(self) -> None:
        self._expect(16, self._anchor_reply)

    def _anchor_reply(self) -> None:
        # client wall+mono read for RTT symmetry; answer with our pair
        self.send_parts(
            [_ST_OK + struct.pack("<dd", time.time(), time.monotonic())]
        )
        self._await_op()

    def _op_close(self) -> None:
        try:
            self.queue.close()
        except TransportClosed:
            self._send_control(_ST_CLOSED)
        else:
            self._send_control(_ST_OK)
            self.loop.queue_touched(self.queue)
        self._await_op()

    def _op_bye(self) -> None:
        # clean goodbye: the previous response is ACKed (in_flight was
        # already cleared when this opcode arrived)
        self._begin_close()

    def _op_cluster(self) -> None:
        self._expect(4, self._cluster_len)

    def _cluster_len(self) -> None:
        (n,) = struct.unpack_from("<I", self._hdr)
        if n > 1 << 20:  # control-plane JSON: a MB is already absurd
            raise ConnectionError(f"cluster RPC payload {n} bytes")
        # dedicated exact-size buffer: group RPCs are rare control plane
        self._open_buf = bytearray(n)
        self._arm(memoryview(self._open_buf), self._cluster_finish)

    def _cluster_finish(self) -> None:
        if self._open_buf[:13] == b'{"op": "ping"':
            # link-rate probe fast path (ISSUE 15, --wire_codec auto):
            # the client times its padded REQUEST through the link, so
            # the answer must cost O(1) — parsing a 640 KB pad here
            # would bill codec-decision bandwidth for JSON decode time
            # and make every fast LAN look slow
            payload = json.dumps(
                {"ok": True, "nbytes": len(self._open_buf)}
            ).encode()
            self.send_parts(
                [_ST_OK + struct.pack("<I", len(payload)), payload]
            )
            self._await_op()
            return
        try:
            req = json.loads(self._open_buf.decode())
            if req.get("op") == "metrics":
                # federation pull (ISSUE 13): the whole metrics-registry
                # snapshot, host-tagged, over the EXISTING control
                # surface — no new opcode, and a pre-ISSUE-13 peer
                # answers {"ok": False, "error": "missing group"}, which
                # the collector surfaces as a loudly-degraded peer (the
                # 'Z' old-peer precedent)
                resp = _metrics_rpc_payload()
            elif req.get("op") == "ping":
                # non-prefix ping spellings still answer (the fast path
                # above handles the probe's canonical byte layout)
                resp = {"ok": True, "nbytes": len(self._open_buf)}
            else:
                resp = self.srv.groups.handle(req)
        except Exception as e:  # noqa: BLE001 — a bad RPC must not kill the loop
            resp = {"ok": False, "error": repr(e)}
        payload = json.dumps(resp).encode()
        self.send_parts([_ST_OK + struct.pack("<I", len(payload)), payload])
        self._await_op()

    # -- durable log opcodes ('R'/'J', ISSUE 8) ---------------------------
    def _op_replay(self) -> None:
        self._expect(10, self._replay_hdr)

    def _replay_hdr(self) -> None:
        self._r_from, glen = struct.unpack_from("<QH", self._hdr)
        self._open_buf = bytearray(glen)
        self._arm(memoryview(self._open_buf), self._replay_finish)

    def _replay_finish(self) -> None:
        group = self._open_buf.decode() or "replay"
        open_replay = getattr(self.queue, "open_replay", None)
        if open_replay is None:  # memory-only queue: no retained range
            self._send_control(_ST_NO)
            self._await_op()
            return
        self.replay = open_replay(group, self._r_from)
        self.send_parts([
            _ST_OK
            + struct.pack(
                "<QQ", self.replay.position, self.replay.log.next_offset
            )
        ])
        self._await_op()

    def _op_commit(self) -> None:
        self._expect(10, self._commit_hdr)

    def _commit_hdr(self) -> None:
        self._r_from, glen = struct.unpack_from("<QH", self._hdr)
        self._open_buf = bytearray(glen)
        self._arm(memoryview(self._open_buf), self._commit_finish)

    def _commit_finish(self) -> None:
        offset, group = self._r_from, self._open_buf.decode()
        if self.replay is not None:
            if offset == COMMIT_DELIVERED:
                self.replay.commit()
            else:
                self.replay.commit(through=offset)
            self._send_control(_ST_OK)
            self._await_op()
            return
        commit = getattr(self.queue, "commit_offset", None)
        if commit is None or not group or offset == COMMIT_DELIVERED:
            # no log / no named group / the delivered sentinel without a
            # replay cursor: nothing to commit against
            self._send_control(_ST_NO)
        else:
            commit(offset, group)
            self._send_control(_ST_OK)
        self._await_op()

    # -- wire-compression negotiation ('Z', ISSUE 9) ----------------------
    def _op_codec(self) -> None:
        self._expect(2, self._codec_len)

    def _codec_len(self) -> None:
        (n,) = struct.unpack_from("<H", self._hdr)
        if n > 4096:  # a codec-name list is tens of bytes
            raise ConnectionError(f"codec negotiation payload {n} bytes")
        self._open_buf = bytearray(n)
        self._arm(memoryview(self._open_buf), self._codec_finish)

    def _codec_finish(self) -> None:
        # the 'Z' advert mixes codec NAMES with capability FIELDS
        # (key=value, ISSUE 12); fields are peeled off here and the
        # codec picker sees only names — a field it predates is simply
        # an unknown name to an older picker, which skips it (that is
        # what makes the hello rideable on the existing exchange)
        names = []
        for entry in self._open_buf.decode().split(","):
            entry = entry.strip()
            key, sep, value = entry.partition("=")
            if not sep:
                names.append(entry)
                continue
            if key == "tenant":
                tenant, _, w = value.partition(":")
                self.tenant = tenant or _TENANT_DEFAULT
                try:
                    self.weight = max(
                        1, min(_TENANT_WEIGHT_MAX, int(w))
                    ) if w else 1
                except ValueError:
                    self.weight = 1
                FLIGHT.record(
                    "tenant_hello", port=self.srv.port,
                    tenant=self.tenant, weight=self.weight,
                )
            # unknown capability keys are ignored: a newer client must
            # degrade gracefully against this server, not die
        chosen = negotiate_codec(names)
        self.codec = chosen
        name = chosen.name if chosen is not None else CODEC_NONE
        CODEC_STATS.negotiated(name)
        FLIGHT.record(
            "codec_negotiated", port=self.srv.port, codec=name, server=True
        )
        nb = name.encode()
        self.send_parts([_ST_OK + struct.pack("<H", len(nb)) + nb])
        self._await_op()

    # -- replication opcodes ('H'/'V'/'Y', ISSUE 11) ----------------------
    def _op_repl_open(self) -> None:
        self._expect(2, self._ro_ns_len)

    def _ro_ns_len(self) -> None:
        (n,) = struct.unpack_from("<H", self._hdr)
        self._open_buf = bytearray(n)
        self._arm(memoryview(self._open_buf), self._ro_ns_done)

    def _ro_ns_done(self) -> None:
        self._open_ns = self._open_buf.decode()
        self._expect(2, self._ro_nm_len)

    def _ro_nm_len(self) -> None:
        (n,) = struct.unpack_from("<H", self._hdr)
        self._open_buf = bytearray(n)
        self._arm(memoryview(self._open_buf), self._ro_finish)

    def _ro_finish(self) -> None:
        nm = self._open_buf.decode()
        repl = self.srv.replication
        entry = (
            repl.replica_open(self._open_ns, nm) if repl is not None else None
        )
        if entry is None:
            # cannot host this replica: no replication manager, the
            # queue is mounted LIVE on this server, or the replica was
            # already promoted — the fencing answer a zombie owner must
            # treat as "stop replicating"
            self._send_control(_ST_NO)
        else:
            self.replica = entry
            FLIGHT.record(
                "replica_subscribe", port=self.srv.port,
                queue=f"{self._open_ns}/{nm}", tail=entry.log.next_offset,
            )
            self.send_parts(
                [_ST_OK + struct.pack("<Q", entry.log.next_offset)]
            )
        self._await_op()

    def _va_hdr(self) -> None:
        self._v_off, self._v_floor = struct.unpack_from("<QQ", self._hdr)
        (n,) = struct.unpack_from("<I", self._hdr, 16)
        self._expect_payload(n, self._va_payload)

    def _va_payload(self) -> None:
        item = self._take_item()
        try:
            ok = self.srv.replication.replica_append(
                self.replica, self._v_off, self._v_floor, item
            )
        except Exception:  # noqa: BLE001 — a replica disk fault answers
            ok = False  # 'E' (breadcrumbed in storage); the loop lives
        finally:
            release = getattr(item, "release", None)
            if release is not None:
                release()  # the record is in the mmap now (or refused)
        if ok:
            self.send_parts([_ST_OK + struct.pack("<Q", self._v_off)])
        else:
            self._send_control(_ST_ERR)
        self._await_op()

    def _op_promote(self) -> None:
        self._expect(2, self._pr_ns_len)

    def _pr_ns_len(self) -> None:
        (n,) = struct.unpack_from("<H", self._hdr)
        self._open_buf = bytearray(n)
        self._arm(memoryview(self._open_buf), self._pr_ns_done)

    def _pr_ns_done(self) -> None:
        self._open_ns = self._open_buf.decode()
        self._expect(2, self._pr_nm_len)

    def _pr_nm_len(self) -> None:
        (n,) = struct.unpack_from("<H", self._hdr)
        self._open_buf = bytearray(n)
        self._arm(memoryview(self._open_buf), self._pr_finish)

    def _pr_finish(self) -> None:
        nm = self._open_buf.decode()
        repl = self.srv.replication
        rng = repl.promote(self._open_ns, nm) if repl is not None else None
        if rng is None:
            self._send_control(_ST_NO)  # no replica here: queue starts empty
        else:
            self.send_parts([_ST_OK + struct.pack("<QQ", rng[0], rng[1])])
        self._await_op()

    def _op_open(self) -> None:
        self._expect(2, self._open_ns_len)

    def _open_ns_len(self) -> None:
        (ns_len,) = struct.unpack_from("<H", self._hdr)
        # name fields are u16-length control strings; a dedicated exact-
        # size buffer (OPEN runs once per connection, off the hot path)
        self._open_buf = bytearray(ns_len)
        self._arm(memoryview(self._open_buf), self._open_ns_done)

    def _open_ns_done(self) -> None:
        self._open_ns = self._open_buf.decode()
        self._expect(2, self._open_nm_len)

    def _open_nm_len(self) -> None:
        (nm_len,) = struct.unpack_from("<H", self._hdr)
        self._open_buf = bytearray(nm_len)
        self._arm(memoryview(self._open_buf), self._open_nm_done)

    def _open_nm_done(self) -> None:
        self._open_nm = self._open_buf.decode()
        self._expect(4, self._open_finish)

    def _open_finish(self) -> None:
        (maxsize,) = struct.unpack_from("<I", self._hdr)
        wctx = self.srv.worker_ctx
        if wctx is not None:
            owner = wctx.owner_of(self._open_ns, self._open_nm)
            if owner != wctx.worker_id:
                # the named queue's state lives on exactly one worker
                # (rendezvous-pinned): ship the connection there; the
                # adopter performs the open and answers the client
                self.loop.migrate_conn(self, owner, {
                    "kind": "open",
                    "ns": self._open_ns,
                    "nm": self._open_nm,
                    "maxsize": maxsize,
                })
                return
        self.queue = self.srv.open_named(
            self._open_ns, self._open_nm, maxsize or None
        )
        self._send_control(_ST_OK)
        self._await_op()


_OPS: Dict[int, str] = {
    _OP_PUT[0]: "_op_put",
    _OP_GET[0]: "_op_get",
    _OP_SIZE[0]: "_op_size",
    _OP_CLOSE[0]: "_op_close",
    _OP_GET_BATCH[0]: "_op_get_batch",
    _OP_GET_BATCH_WAIT[0]: "_op_get_batch_wait",
    _OP_PUT_BATCH[0]: "_op_put_batch",
    _OP_PUT_WAIT[0]: "_op_put_wait",
    _OP_PUT_SEQ[0]: "_op_put_seq",
    _OP_STREAM[0]: "_op_stream",
    _OP_OPEN[0]: "_op_open",
    _OP_STATS[0]: "_op_stats",
    _OP_ANCHOR[0]: "_op_anchor",
    _OP_CLUSTER[0]: "_op_cluster",
    _OP_REPLAY[0]: "_op_replay",
    _OP_COMMIT[0]: "_op_commit",
    _OP_CODEC[0]: "_op_codec",
    _OP_REPL_OPEN[0]: "_op_repl_open",
    _OP_PROMOTE[0]: "_op_promote",
    _OP_BYE[0]: "_op_bye",
}

#: ops any worker serves LOCALLY even when it does not own the default
#: queue: codec/tenant hello, cluster metadata + anchors (per-worker
#: answers by design), replica-link setup (refused with --workers at the
#: CLI), BYE. OPEN routes later, at _open_finish, once the name is read.
#: Derived from the dispatch table by handler name so this set is not a
#: second send-side reference to the opcode constants (the wire-protocol
#: lint counts those as senders).
_WORKER_LOCAL_OPS = frozenset(
    op for op, handler in _OPS.items()
    if handler in (
        "_op_open", "_op_codec", "_op_cluster", "_op_anchor",
        "_op_repl_open", "_op_promote", "_op_bye",
    )
)


class EventLoop:
    """The one loop: accepts, reads, writes, fires bounded-wait timers
    and pumps queue waiters — for a :class:`~psana_ray_tpu.transport.
    tcp.TcpQueueServer` constructed with ``mode="evloop"``."""

    def __init__(self, server):
        self._srv = server
        self._sel = selectors.DefaultSelector()
        self._conns: set = set()
        self._queues: Dict[int, _QueueState] = {}
        self._timers: List[tuple] = []  # heap: (deadline, tie, conn, gen)
        self._timer_tie = 0
        # waker: listener callbacks / shutdown poke this pipe so the
        # selector wakes immediately instead of at the next tick
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        self._waker_w.setblocking(False)
        self._waker_buf = bytearray(512)
        self._waker_mv = memoryview(self._waker_buf)
        self._ACCEPT = object()
        self._WAKER = object()
        self._ADOPT = object()
        self._loop_tid: Optional[int] = None

    # -- cross-thread pokes ----------------------------------------------
    def wake(self) -> None:
        # The loop's own queue ops fire the RingBuffer listeners too —
        # a self-poke would cost two syscalls plus a spurious zero-wait
        # select pass PER FRAME. The loop is by definition awake when it
        # is the caller, and _pump_all runs at the end of every pass, so
        # only other threads need the pipe.
        if threading.get_ident() == self._loop_tid:
            return
        try:
            self._waker_w.send(b"w")
        except (BlockingIOError, InterruptedError, OSError):
            pass  # pipe full = a wakeup is already pending; closed = exiting

    # -- queue-state plumbing --------------------------------------------
    def _qs(self, queue) -> _QueueState:
        qs = self._queues.get(id(queue))
        if qs is None:
            qs = _QueueState(queue)
            self._queues[id(queue)] = qs
            repl = getattr(self._srv, "replication", None)
            if repl is not None:
                # the queue's ReplicationSender (mounted at open_named
                # time, strictly before any connection binds) — the
                # replicated-ack-floor gate key
                qs.repl = repl.sender_for(queue)
            add = getattr(queue, "add_listener", None)
            if add is not None:
                try:
                    add(self.wake)
                    qs.listened = True
                    remove = getattr(queue, "remove_listener", None)
                    if remove is not None:
                        qs.unlisten = lambda: remove(self.wake)
                except Exception:
                    qs.listened = False
        return qs

    def queue_touched(self, queue) -> None:
        """An in-loop op changed this queue's state; the per-iteration
        pump pass will serve its waiters (this is just a cheap no-op
        hook kept for readability and future per-queue dirty tracking)."""

    def add_get_waiter(self, conn: _EvConn, deadline: Optional[float]) -> None:
        self._qs(conn.queue).get_waiters.append(conn)
        if deadline is not None:
            self._add_timer(deadline, conn)

    def add_put_waiter(self, conn: _EvConn, deadline: Optional[float]) -> None:
        self._qs(conn.queue).put_waiters.append(conn)
        if deadline is not None:
            self._add_timer(deadline, conn)

    def add_stream(self, conn: _EvConn) -> None:
        self._qs(conn.queue).get_waiters.append(conn)

    def add_rack_waiter(self, conn: _EvConn) -> None:
        """Park a producer whose reply waits on the replicated ack
        floor (pending kind "RA"); no deadline — the sender's degrade
        grace bounds the wait when the follower link is down."""
        self._qs(conn.queue).ra_waiters.append(conn)

    def repl_sender(self, queue):
        """The queue's ReplicationSender, or None when unreplicated."""
        return self._qs(queue).repl

    def add_liveness_probe(self, conn: _EvConn) -> None:
        """Re-check a parked, read-paused connection for EOF every
        PROBE_INTERVAL_S: re-arming read interest makes the next select
        pass run the MSG_PEEK probe again (which re-pauses and
        reschedules if the pipelined bytes are still waiting)."""
        self._add_timer(
            time.monotonic() + PROBE_INTERVAL_S, conn, kind="probe"
        )

    def _add_timer(self, deadline: float, conn: _EvConn, kind: str = "op") -> None:
        self._timer_tie += 1
        heapq.heappush(
            self._timers, (deadline, self._timer_tie, conn, conn.op_gen, kind)
        )

    # -- redelivery -------------------------------------------------------
    def requeue_items(self, queue, items) -> None:
        """Head-requeue via the shared recovery path. Backings without
        ``put_front`` (shm rings) take the timed-retry path, which can
        block — hand those to a short-lived helper thread so the loop
        never parks (connection death is rare; the thread is bounded by
        the recovery timeout and daemonic)."""
        if not items:
            return
        if getattr(queue, "put_front", None) is not None:
            self._srv._requeue(queue, items)  # non-blocking head placement
        else:
            threading.Thread(
                target=self._srv._requeue, args=(queue, items),
                daemon=True, name="tcp-evloop-requeue",
            ).start()

    # -- connection lifecycle --------------------------------------------
    def kill_conn(self, conn: _EvConn, cause, requeue: bool = True) -> None:
        if conn.closed:
            return
        conn.closed = True
        if conn._mask:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            conn._mask = 0
        try:
            conn.sock.close()
        except OSError:
            pass
        self._conns.discard(conn)
        EVLOOP.conn_closed()
        if conn._lease is not None:  # payload died mid-read
            conn._lease.release()
            conn._lease = None
        while conn._out_releases:  # compressed parts died queued
            conn._out_releases.popleft()[1].release()
        # a parked 'U'/'W' item was never enqueued: drop it — the client
        # is dead (its windowed-put resend redelivers on reconnect), and
        # enqueueing now would stack a duplicate on top of that resend
        conn.pending = None
        conn._qb_items = []
        if conn.replay is not None:
            # cursor-based delivery: nothing to requeue — records the
            # dead client read but never committed simply redeliver when
            # its group re-opens at RESUME
            conn.in_flight = []
            conn.replay = None
        if requeue:
            if conn.in_flight:
                self.requeue_items(conn.queue, conn.in_flight)
                conn.in_flight = []
            conn._finish_stream(clean=False)
        else:
            if conn.stream is not None:
                conn._finish_stream(clean=True)

    # -- the loop ---------------------------------------------------------
    def run(self) -> None:
        srv = self._srv
        self._loop_tid = threading.get_ident()
        EVLOOP.ensure_registered()
        SPLICE.ensure_registered()
        try:
            srv._sock.setblocking(False)
        except OSError:
            return  # shutdown() closed the socket before we got here
        self._sel.register(srv._sock, selectors.EVENT_READ, self._ACCEPT)
        self._sel.register(self._waker_r, selectors.EVENT_READ, self._WAKER)
        if srv.worker_ctx is not None:
            # the adoption socket: sibling workers ship connections
            # whose queues this worker owns (ISSUE 17)
            self._sel.register(
                srv.worker_ctx.sock, selectors.EVENT_READ, self._ADOPT
            )
        # stage-tag the dispatch half of each pass so the continuous
        # profiler bills server CPU to "dispatch" (bound once here: the
        # loop body must not pay an import)
        from psana_ray_tpu.obs.profiling.stagetag import TAG_DISPATCH, TAG_UNTAGGED, set_stage

        try:
            while not srv._stop.is_set():
                t_sel = time.monotonic()
                events = self._sel.select(self._select_timeout())
                t0 = time.monotonic()
                set_stage(TAG_DISPATCH)
                for key, mask in events:
                    data = key.data
                    if data is self._ACCEPT:
                        self._accept()
                    elif data is self._WAKER:
                        self._drain_waker()
                    elif data is self._ADOPT:
                        self._adopt_conns()
                    else:
                        self._dispatch_conn(data, mask)
                self._fire_timers()
                self._pump_all()
                set_stage(TAG_UNTAGGED)
                EVLOOP.loop_pass(
                    (time.monotonic() - t0) * 1000.0, (t0 - t_sel) * 1000.0
                )
        finally:
            self._teardown()

    def _dispatch_conn(self, conn: _EvConn, mask: int) -> None:
        try:
            if mask & selectors.EVENT_WRITE:
                conn.flush_out()
            if mask & selectors.EVENT_READ and not conn.closed:
                conn.on_readable()
        except (ConnectionError, OSError) as e:
            self.kill_conn(conn, e)
        except Exception as e:  # noqa: BLE001 — one bad conn must not kill the loop
            self.kill_conn(conn, e)

    def _accept(self) -> None:
        srv = self._srv
        while True:
            try:
                sock, _ = srv._sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            n_active = len(self._conns)
            if srv.max_conns and n_active >= srv.max_conns:
                EVLOOP.refused()
                try:
                    sock.setblocking(False)
                except OSError:
                    pass
                _refuse_conn(sock, srv.port, n_active, srv.max_conns)
                continue
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _EvConn(self, sock, srv)
            self._conns.add(conn)
            with srv._conns_lock:  # shutdown() parity sweep sees them too
                srv._conns = [c for c in srv._conns if c.fileno() != -1]
                srv._conns.append(sock)
            EVLOOP.conn_opened()
            conn._await_op()
            conn._set_interest(read=True)

    # -- multi-worker connection handoff (ISSUE 17) -----------------------
    def migrate_conn(self, conn: _EvConn, target: int, ctx: dict) -> None:
        """Begin shipping ``conn`` to worker ``target``: freeze reads,
        flush any queued response bytes, then send the fd + context
        over the adoption socket. The negotiated per-connection state
        (codec, tenant) rides in the context so the adopter rebuilds an
        indistinguishable connection."""
        ctx = dict(ctx)
        ctx["codec"] = conn.codec.name if conn.codec is not None else None
        if conn.tenant != _TENANT_DEFAULT or conn.weight != 1:
            ctx["tenant"] = conn.tenant
            ctx["weight"] = conn.weight
        conn._migration = {
            "target": int(target),
            "ctx": ctx,
            "deadline": time.monotonic() + MIGRATE_GRACE_S,
        }
        conn._set_interest(read=False)
        if conn.out:
            conn._set_interest(write=True)  # flush_out ships when drained
            return
        self._try_migrate(conn)

    def _try_migrate(self, conn: _EvConn) -> None:
        """One handoff attempt. A refusal (owner's adoption buffer full,
        owner mid-respawn) retries on a timer within the grace window;
        past it the connection dies WITH redelivery — the client's
        reconnect envelope plus durable re-expose make that lossless."""
        if conn.closed or conn._migration is None:
            return
        mig = conn._migration
        try:
            self._srv.worker_ctx.send_conn(
                mig["target"], conn.sock, mig["ctx"]
            )
        except OSError as e:
            now = time.monotonic()
            if now >= mig["deadline"]:
                FLIGHT.record(
                    "migrate_gave_up", target=mig["target"],
                    err=e.__class__.__name__,
                )
                self.kill_conn(conn, e, requeue=True)
                return
            if not mig.get("retried"):
                mig["retried"] = True
                FLIGHT.record(
                    "migrate_retry", target=mig["target"],
                    err=e.__class__.__name__,
                )
            self._add_timer(now + MIGRATE_RETRY_S, conn, kind="migrate")
            return
        FLIGHT.record(
            "conn_migrated", target=mig["target"],
            kind=mig["ctx"].get("kind"),
        )
        # the in-flight datagram holds its own reference to the fd;
        # closing our copy here is the normal no-redelivery teardown
        # (nothing is in flight at a migration point by construction)
        conn._migration = None
        self.kill_conn(conn, None, requeue=False)

    def _adopt_conns(self) -> None:
        """Drain the adoption socket: each datagram is a connection fd
        plus its context from a sibling worker. Rebuild the _EvConn
        exactly as _accept would, restore negotiated state, then either
        finish the routed OPEN or replay the consumed opcode byte."""
        srv = self._srv
        wctx = srv.worker_ctx
        for sock, ctx in wctx.recv_conns():
            conn = None
            try:
                sock.setblocking(False)
                try:
                    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    pass
                conn = _EvConn(self, sock, srv)
                name = ctx.get("codec")
                if name:
                    conn.codec = negotiate_codec([name])
                conn.tenant = ctx.get("tenant", _TENANT_DEFAULT)
                try:
                    conn.weight = max(1, int(ctx.get("weight", 1)))
                except (TypeError, ValueError):
                    conn.weight = 1
                self._conns.add(conn)
                with srv._conns_lock:  # shutdown() parity sweep
                    srv._conns = [c for c in srv._conns if c.fileno() != -1]
                    srv._conns.append(sock)
                EVLOOP.conn_opened()
                FLIGHT.record(
                    "conn_adopted", worker=wctx.worker_id,
                    kind=ctx.get("kind"),
                )
                if ctx.get("kind") == "open":
                    conn._open_ns = ctx.get("ns", "")
                    conn._open_nm = ctx.get("nm", "")
                    conn.queue = srv.open_named(
                        conn._open_ns, conn._open_nm,
                        ctx.get("maxsize") or None,
                    )
                    conn._send_control(_ST_OK)
                    conn._await_op()
                else:
                    # the migrating worker consumed exactly the opcode
                    # byte: replay it through the normal dispatcher (we
                    # own the target queue, so it cannot re-route)
                    conn._hdr[0] = int(ctx.get("op", 0))
                    conn._on_op()
                if not conn.closed:
                    conn._set_interest(read=True)
            except (ConnectionError, OSError) as e:
                if conn is not None:
                    self.kill_conn(conn, e)
                else:
                    try:
                        sock.close()
                    except OSError:
                        pass
            except Exception as e:  # noqa: BLE001 — one bad adoption must not kill the loop
                if conn is not None:
                    self.kill_conn(conn, e)

    def _drain_waker(self) -> None:
        while True:
            try:
                k = self._waker_r.recv_into(self._waker_mv)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if k == 0:
                return

    def _select_timeout(self) -> float:
        now = time.monotonic()
        t = IDLE_TICK_S
        if self._timers:
            t = min(t, max(0.0, self._timers[0][0] - now))
        waiting = unlistened = False
        for qs in self._queues.values():
            if qs.get_waiters or qs.put_waiters or qs.ra_waiters:
                waiting = True
                if not qs.listened:
                    unlistened = True
                    break
        if unlistened:
            t = min(t, POLL_TICK_S)
        elif waiting:
            t = min(t, LISTENED_TICK_S)
        return t

    def _fire_timers(self) -> None:
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            deadline, _tie, conn, gen, tkind = heapq.heappop(self._timers)
            if conn.closed:
                continue
            if tkind == "migrate":
                # worker-handoff retry: independent of pending/op_gen
                # (a migrating connection has neither) — must be
                # checked BEFORE the pending-is-None guard below
                if conn._migration is not None and not conn.out:
                    self._try_migrate(conn)
                continue
            if conn.pending is None or gen != conn.op_gen:
                continue  # already served / superseded
            if tkind == "probe":
                # parked with reads paused: re-arm read interest so the
                # next select pass re-runs the EOF probe
                conn._set_interest(read=True)
                continue
            EVLOOP.timer_lag((now - deadline) * 1000.0)
            kind = conn.pending["kind"]
            try:
                if kind == "D":
                    # one last non-blocking look, then the empty answer
                    try:
                        items = conn._read_batch(conn.pending["max_items"])
                    except TransportClosed:
                        conn._send_control(_ST_CLOSED)
                        conn.unpark()
                        continue
                    conn._respond_batch(items)
                    conn.unpark()
                elif kind == "U":
                    conn._send_control(_ST_NO)
                    conn.unpark()
                # "W" carries no deadline: backpressure, not timeout
            except (ConnectionError, OSError) as e:
                self.kill_conn(conn, e)

    # -- the pump: serve waiters when queue state may have changed --------
    def _pump_all(self) -> None:
        for qs in list(self._queues.values()):
            if not (qs.get_waiters or qs.put_waiters or qs.ra_waiters):
                continue
            try:
                progressed = True
                while progressed:
                    progressed = (
                        self._pump_get(qs)
                        | self._pump_put(qs)
                        | self._pump_rack(qs)
                    )
            except _QueueClosedSignal:
                self._queue_closed(qs)

    def _pump_get(self, qs: _QueueState) -> bool:
        did = False
        gw = qs.get_waiters
        if gw:
            # cheap emptiness probe first: the pump runs on every loop
            # pass, and an idle queue must cost a depth check, not a
            # get_batch per waiter per tick (round-trip-economy parity
            # with the threaded server's single blocking get_batch).
            # size() alone is not a liveness probe — RingBuffer.size()
            # answers 0 on a CLOSED queue — so check closed explicitly
            # (waiting streams must see 'X' promptly). Replay waiters
            # read the LOG cursor, not the queue, so an empty live
            # queue must not short-circuit past them.
            try:
                if getattr(qs.queue, "closed", False):
                    raise _QueueClosedSignal
                if not qs.queue.size() and not any(
                    c.replay is not None for c in gw if not c.closed
                ):
                    return False
            except TransportClosed:
                raise _QueueClosedSignal from None
        # WDRR round bookkeeping (ISSUE 12): when EVERY waiting stream
        # tenant's deficit is dry, start a new round up front (the
        # common single-tenant case replenishes once and serves a full
        # pass, pre-ISSUE-12 throughput)
        weights, n_stream = _stream_tenant_weights(gw)
        if weights and qs.wdrr.all_dry(weights):
            qs.wdrr.replenish(weights, n_stream)
        visits = len(gw)
        # streams skipped ONLY because their tenant's WDRR deficit ran
        # dry this round (credit-blocked or empty-queue skips don't
        # count): when that is the only reason nothing moved, a new
        # round replenishes every waiting tenant and the pump re-runs
        blocked_on_allowance = False
        while visits and gw:
            visits -= 1
            conn = gw[0]
            if conn.closed:
                gw.popleft()
                continue
            if conn.replay is not None:
                # replay waiter ('D' park): serve from the cursor
                if conn.pending is None or conn.pending.get("kind") != "D":
                    gw.popleft()
                    continue
                try:
                    items = conn.replay.next_batch(conn.pending["max_items"])
                except TransportClosed:
                    raise _QueueClosedSignal from None
                if not items:
                    gw.rotate(-1)  # caught up: the timer answers empty
                    continue
                try:
                    conn._respond_batch(items)
                    gw.popleft()
                    conn.unpark()
                except (ConnectionError, OSError) as e:
                    self.kill_conn(conn, e)
                did = True
                continue
            if conn.stream is not None:
                allow = qs.wdrr.allowance(conn.tenant)
                if allow < 1.0:
                    # tenant budget exhausted this WDRR round: other
                    # tenants' streams go first (weighted fair-share)
                    blocked_on_allowance = True
                    gw.rotate(-1)
                    continue
                # per-VISIT cap at quantum * weight: within a shared
                # tenant budget, rotation (serve-rotate + blocked-rotate
                # is a full cycle with two conns) would otherwise hand
                # the whole round to whichever conn sits first — each
                # visit takes one quantum so same-tenant conns split
                # their tenant's round evenly
                want = min(
                    conn.stream.budget(), _STREAM_POP_MAX, int(allow),
                    _WDRR_QUANTUM * conn.weight,
                )
                if want <= 0:
                    gw.rotate(-1)  # window full: wait for credits
                    continue
            elif conn.pending is not None and conn.pending.get("kind") == "D":
                want = conn.pending["max_items"]
            else:
                gw.popleft()  # served by a timer / superseded
                continue
            try:
                items = qs.queue.get_batch(min(want, 4096), timeout=0.0)
            except TransportClosed:
                raise _QueueClosedSignal from None
            except Exception as e:  # noqa: BLE001 — a corrupt spill read
                # must cost this waiter an error answer, not the loop
                gw.popleft()
                try:
                    conn._send_control(_ST_ERR)
                    if conn.stream is None:
                        conn.unpark()
                except (ConnectionError, OSError):
                    self.kill_conn(conn, e)
                did = True
                continue
            if not items:
                if any(c.replay is not None for c in gw if not c.closed):
                    gw.rotate(-1)  # let replay waiters behind us run
                    continue
                break  # queue empty: every remaining get-waiter waits
            try:
                if conn.stream is not None:
                    qs.wdrr.charge(conn.tenant, len(items))
                    conn.push_stream_items(items)
                    gw.rotate(-1)  # round-robin fairness across streams
                else:
                    conn._respond_batch(items)
                    gw.popleft()
                    conn.unpark()
            except (ConnectionError, OSError) as e:
                # the waiter died with items popped: standard redelivery
                self.kill_conn(conn, e)
            did = True
        if not did and blocked_on_allowance:
            # frames exist but every stream that could still serve was
            # allowance-blocked (a credit-stalled tenant may be sitting
            # on unspent deficit, which all_dry above would wait on
            # forever): force a new round. Reporting progress makes
            # _pump_all re-run this pump with fresh budgets — the next
            # pass either serves frames or finds nothing but
            # credit/emptiness blocks (allowances now >= 1, so the
            # blocked flag stays down and the loop ends)
            weights, n_stream = _stream_tenant_weights(gw)
            qs.wdrr.replenish(weights, n_stream)
            did = bool(weights)
        return did

    def _pump_put(self, qs: _QueueState) -> bool:
        did = False
        pw = qs.put_waiters
        while pw:
            conn = pw[0]
            if conn.closed or conn.pending is None or conn.pending.get(
                "kind"
            ) not in ("U", "W"):
                pw.popleft()
                continue
            try:
                put_offset = getattr(qs.queue, "put_offset", None)
                if put_offset is not None:
                    ok, offset = put_offset(conn.pending["item"])
                else:
                    ok, offset = qs.queue.put(conn.pending["item"]), None
            except TransportClosed:
                raise _QueueClosedSignal from None
            except Exception as e:  # noqa: BLE001 — e.g. a durable queue
                # refusing an oversized record (ValueError from the
                # segment log): answer THIS conn with a protocol error
                # instead of letting the exception escape _pump_all and
                # take the whole loop (and every connection) down
                pw.popleft()
                try:
                    conn._send_control(_ST_ERR)
                    conn.unpark()
                except (ConnectionError, OSError):
                    self.kill_conn(conn, e)
                did = True
                continue
            if not ok:
                break  # still full: FIFO — nobody behind may jump the line
            pw.popleft()
            if conn.pending["kind"] == "W":
                parts = [_ST_OK + struct.pack("<Q", conn.pending["seq"])]
            else:
                parts = [_ST_OK]
            try:
                # the reply may re-park on the replicated ack floor
                # (pending flips U/W -> RA); parked=True resumes reads
                # on the immediate-answer path
                conn._answer_put(parts, offset, parked=True)
            except (ConnectionError, OSError) as e:
                self.kill_conn(conn, e)
            did = True
        return did

    def _pump_rack(self, qs: _QueueState) -> bool:
        """Release producer replies whose offsets the follower has
        logged — or all of them once the sender degraded (follower link
        down past the grace window). FIFO is offset order, so an
        unreached head means nobody behind is reachable either."""
        did = False
        rw = qs.ra_waiters
        while rw:
            conn = rw[0]
            if conn.closed or conn.pending is None or conn.pending.get(
                "kind"
            ) != "RA":
                rw.popleft()
                continue
            if qs.repl is not None and not qs.repl.reached(
                conn.pending["offset"]
            ):
                break
            rw.popleft()
            parts = conn.pending["parts"]
            try:
                conn.send_parts(parts)
                conn.unpark()
            except (ConnectionError, OSError) as e:
                self.kill_conn(conn, e)
            did = True
        return did

    def _queue_closed(self, qs: _QueueState) -> None:
        """The backing queue raised TransportClosed mid-pump: answer
        every waiter with 'X' (bounded waits resume the connection;
        streams end — the threaded server's stream loop did the same)."""
        while qs.get_waiters:
            conn = qs.get_waiters.popleft()
            if conn.closed:
                continue
            try:
                if conn.stream is not None:
                    conn.stream.queue_closed = True
                    conn._send_control(_ST_CLOSED)  # the stream is over
                    conn._finish_stream(clean=False)
                    conn._begin_close()
                else:
                    conn._send_control(_ST_CLOSED)
                    conn.unpark()
            except (ConnectionError, OSError) as e:
                self.kill_conn(conn, e)
        while qs.put_waiters:
            conn = qs.put_waiters.popleft()
            if conn.closed or conn.pending is None:
                continue
            try:
                conn._send_control(_ST_CLOSED)
                conn.unpark()
            except (ConnectionError, OSError) as e:
                self.kill_conn(conn, e)
        while qs.ra_waiters:
            # replicated-ack waiters: their frames WERE accepted and
            # logged before the close — release the truthful OK reply
            # rather than holding it against a floor that may never
            # advance on a closed queue
            conn = qs.ra_waiters.popleft()
            if conn.closed or conn.pending is None or conn.pending.get(
                "kind"
            ) != "RA":
                continue
            try:
                conn.send_parts(conn.pending["parts"])
                conn.unpark()
            except (ConnectionError, OSError) as e:
                self.kill_conn(conn, e)

    def _teardown(self) -> None:
        for conn in list(self._conns):
            # server stopping: redeliver in-flight/unacked to the queues
            # (parity with the threaded server, whose dying serve
            # threads requeue on the forced disconnect)
            self.kill_conn(conn, None, requeue=True)
        for qs in self._queues.values():
            if qs.unlisten is not None:
                try:
                    qs.unlisten()
                except Exception:
                    pass
        for s in (self._waker_r, self._waker_w):
            try:
                self._sel.unregister(s)
            except (KeyError, ValueError, OSError):
                pass
            try:
                s.close()
            except OSError:
                pass
        try:
            self._sel.unregister(self._srv._sock)
        except (KeyError, ValueError, OSError):
            pass
        self._sel.close()
