"""Multi-process data plane: N forked evloop workers behind ONE port.

The evloop broke the thread-per-connection ceiling (ISSUE 6) but left
the hard one: a single Python process is a single core, and the cost
model (ISSUE 16) shows the brokered path is CPU-bound in exactly that
process. ``queue_server --workers N`` forks N full evloop server
processes that share the listening port via ``SO_REUSEPORT`` — the
kernel shards incoming CONNECTIONS across them, tf.data-style (Murray
et al.: the host data plane should scale with cores, not be a fixed
tax).

The kernel shards *connections*, not *queues* — and a named queue's
state (ring, durable log, stream subscribers) must live in exactly ONE
process or ordering and the delivery contract shatter. Three pieces
close that gap:

- **partition pinning** — :func:`queue_owner` maps ``(ns, name)`` to a
  worker by the existing rendezvous ranking
  (:mod:`psana_ray_tpu.cluster.hashring`): deterministic across
  processes, runs, and respawns, so every worker computes the same map
  with zero coordination. The default queue is pinned to worker 0.
- **connection adoption** — each worker binds an ``AF_UNIX`` datagram
  socket (``worker-<i>.sock``); when a connection's first
  queue-touching opcode names a queue another worker owns, the serving
  worker ships the connection FD over ``SCM_RIGHTS`` plus a small JSON
  context (negotiated codec, tenant, the pending op) and forgets it.
  The evloop's exact-size reads make this safe: the server never
  over-reads, so any pipelined request bytes are still in the KERNEL
  socket buffer and travel with the fd. Clients cannot tell one worker
  from many.
- **a tiny supervisor** — the parent process forks, reaps, and
  respawns. A respawned worker keeps its worker id, so the partition
  map never moves; its durable queues re-expose ``(floor, tail]`` on
  the next OPEN and the in-flight-requeue / stream redelivery
  contracts hold across the death (at-least-once, as ever).

Scope: ``--workers`` composes with durable/named queues, streams,
codec negotiation, and per-worker telemetry ('G' metrics answers are
per-worker, tagged with the worker id). It does NOT compose with chain
replication (``--replicate_peers``) — replica links bind queues
directly and the CLI refuses the combination loudly.
"""

from __future__ import annotations

import array
import json
import os
import signal
import socket
import struct
import threading
from typing import Callable, Dict, List, Optional

from psana_ray_tpu.cluster.hashring import partition_owner
from psana_ray_tpu.obs.flight import FLIGHT

__all__ = [
    "queue_owner",
    "current_worker_id",
    "WorkerContext",
    "WorkerSupervisor",
    "resolve_port",
]

#: worker that owns the default (un-OPENed) queue
DEFAULT_QUEUE_WORKER = 0

#: how long a migration retries against a dead/respawning owner before
#: the connection is killed (the client's reconnect envelope takes over;
#: durable re-expose makes the handoff lossless)
MIGRATE_GRACE_S = 2.0
MIGRATE_RETRY_S = 0.25

#: adoption datagram: u32 json length + json (fds ride the ancillary data)
_ADOPT_HDR = struct.Struct("<I")
_ADOPT_MAX = 16 * 1024

# the forked worker's identity, set once by WorkerContext in the child —
# telemetry (federation payload, prof spools) reads it to tag this
# process's numbers with the worker they came from
_CURRENT_WORKER_ID: Optional[int] = None


def current_worker_id() -> Optional[int]:
    """This process's worker id (None outside ``--workers`` children)."""
    return _CURRENT_WORKER_ID


def queue_owner(namespace: str, name: str, n_workers: int) -> int:
    """The worker pinned to ``(namespace, name)`` — rendezvous over the
    synthetic member set ``w0..w{N-1}`` (the cluster partition-placement
    primitive reused process-locally, so the map is deterministic and
    respawn-stable). The default queue lives on worker 0."""
    if n_workers <= 1:
        return 0
    members = [f"w{i}" for i in range(n_workers)]
    return int(partition_owner(members, f"{namespace}/{name}", 0)[1:])


def resolve_port(host: str, port: int) -> int:
    """A concrete port every worker can SO_REUSEPORT-bind: ``port`` if
    nonzero, else one the kernel assigns to a throwaway reuseport bind
    (closed before any worker binds — a client hitting the gap gets a
    clean refusal and its reconnect envelope)."""
    if port:
        return int(port)
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


class WorkerContext:
    """One worker's half of the adoption plane (created in the CHILD,
    after fork): its own bound datagram socket, the peer address map,
    and the send/receive primitives the evloop calls."""

    def __init__(self, worker_id: int, n_workers: int, sock_dir: str):
        global _CURRENT_WORKER_ID
        self.worker_id = int(worker_id)
        self.n_workers = int(n_workers)
        self.sock_dir = sock_dir
        self.default_owner = DEFAULT_QUEUE_WORKER
        path = self._peer_path(self.worker_id)
        try:  # a respawned worker reclaims its predecessor's address
            os.unlink(path)
        except FileNotFoundError:
            pass
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self.sock.bind(path)
        self.sock.setblocking(False)
        self._send_sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._send_sock.setblocking(False)
        _CURRENT_WORKER_ID = self.worker_id

    def _peer_path(self, wid: int) -> str:
        return os.path.join(self.sock_dir, f"worker-{wid}.sock")

    def owner_of(self, namespace: str, name: str) -> int:
        return queue_owner(namespace, name, self.n_workers)

    # -- fd migration ------------------------------------------------------
    def send_conn(self, target: int, sock: socket.socket, ctx: dict) -> None:
        """Ship ``sock`` + its context to ``target``'s adoption socket.
        Raises OSError (ENOENT/ECONNREFUSED while the target respawns,
        EAGAIN when its buffer is full) — the caller's retry timer owns
        the grace period. On return the fd is referenced by the
        in-flight datagram and the caller closes its copy."""
        blob = json.dumps(ctx).encode()
        if len(blob) > _ADOPT_MAX:
            raise ValueError(f"adoption context too large: {len(blob)}")
        # sendmsg directly, NOT socket.send_fds: the stdlib helper drops
        # its address argument on the floor (cpython 3.10), which turns
        # every send on this unconnected datagram socket into ENOTCONN
        self._send_sock.sendmsg(
            [_ADOPT_HDR.pack(len(blob)) + blob],
            [(
                socket.SOL_SOCKET,
                socket.SCM_RIGHTS,
                array.array("i", [sock.fileno()]),
            )],
            0,
            self._peer_path(target),
        )

    def recv_conns(self) -> List:
        """Drain every pending adoption: ``[(socket, ctx), ...]``. Runs
        on the evloop thread; non-blocking by construction."""
        out = []
        while True:
            try:
                data, fds, _flags, _addr = socket.recv_fds(
                    self.sock, _ADOPT_HDR.size + _ADOPT_MAX, 4
                )
            except (BlockingIOError, InterruptedError):
                return out
            except OSError:
                return out
            if not data:
                return out
            try:
                (n,) = _ADOPT_HDR.unpack_from(data)
                ctx = json.loads(data[_ADOPT_HDR.size:_ADOPT_HDR.size + n])
            except (struct.error, ValueError):
                for fd in fds:
                    os.close(fd)
                FLIGHT.record("adopt_bad_datagram", worker=self.worker_id)
                continue
            if len(fds) != 1:
                for fd in fds:
                    os.close(fd)
                FLIGHT.record(
                    "adopt_bad_fd_count", worker=self.worker_id, fds=len(fds)
                )
                continue
            out.append((socket.socket(fileno=fds[0]), ctx))

    def close(self) -> None:
        try:
            self.sock.close()
        finally:
            self._send_sock.close()


class WorkerSupervisor:
    """The parent process: fork N workers, reap, respawn with the SAME
    worker id (partition-map stability), forward shutdown signals.

    ``worker_fn(worker_id)`` runs in each CHILD and must serve until
    its process exits; the child never returns to the caller's code
    (``os._exit`` fences it). The supervise loop is on the
    event-loop-blocking checker's audited graph: it parks in
    ``os.waitpid`` (reaping, not sleeping) and every wait it takes is
    deadline-bounded."""

    def __init__(self, n_workers: int, worker_fn: Callable[[int], None]):
        if n_workers < 1:
            raise ValueError("need at least one worker")
        self.n_workers = int(n_workers)
        # callback attr deliberately NOT named like any def in the tree:
        # the lint call-graph is name-based and must not pull the whole
        # server into the supervisor's audited set
        self._child_entry = worker_fn
        self._pids: Dict[int, int] = {}  # pid -> worker_id  # guarded-by: _lock
        self._spawn_mono: Dict[int, float] = {}  # worker_id -> last spawn  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.respawns = 0  # guarded-by: _lock

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "WorkerSupervisor":
        for wid in range(self.n_workers):
            self._spawn(wid)
        self._thread = threading.Thread(
            target=self._supervise, daemon=True, name="worker-supervisor"
        )
        self._thread.start()
        return self

    def _spawn(self, worker_id: int) -> None:
        import time

        with self._lock:
            last = self._spawn_mono.get(worker_id, 0.0)
            now = time.monotonic()
            self._spawn_mono[worker_id] = now
        crash_loop = (now - last) < 1.0
        pid = os.fork()
        if pid == 0:
            # THE CHILD: a fresh worker. Restore default signal
            # dispositions (the parent's handlers must not leak in),
            # then serve forever; _exit fences the parent's stack.
            try:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                signal.signal(signal.SIGINT, signal.SIG_DFL)
                if crash_loop:
                    # a worker that died <1s after spawn is crash-
                    # looping: pause before rebuilding so the loop
                    # burns seconds, not CPU. Runs in the CHILD (the
                    # supervisor loop itself never waits unbounded);
                    # the forked _stop copy is never set here, so this
                    # is a plain bounded delay
                    self._stop.wait(0.5)
                self._child_entry(worker_id)
            except BaseException:
                os._exit(1)
            os._exit(0)
        with self._lock:
            self._pids[pid] = worker_id
        FLIGHT.record("worker_spawned", worker=worker_id, pid=pid)

    def _supervise(self) -> None:
        """Reap + respawn until told to stop. Parking in ``waitpid`` is
        the loop's idle state (event-driven, like the selector); every
        other wait is deadline-bounded."""
        while True:
            try:
                pid, status = os.waitpid(-1, 0)
            except ChildProcessError:
                if self._stop.wait(0.2):
                    return
                continue
            except InterruptedError:
                continue
            with self._lock:
                wid = self._pids.pop(pid, None)
            if wid is None:
                continue
            if self._stop.is_set():
                with self._lock:
                    done = not self._pids
                if done:
                    return
                continue
            FLIGHT.record(
                "worker_died", worker=wid, pid=pid,
                status=os.waitstatus_to_exitcode(status)
                if hasattr(os, "waitstatus_to_exitcode") else status,
            )
            with self._lock:
                self.respawns += 1
            self._spawn(wid)

    def pids(self) -> Dict[int, int]:
        """``{worker_id: pid}`` of the live fleet (tests kill -9 by it)."""
        with self._lock:
            return {wid: pid for pid, wid in self._pids.items()}

    def stop(self, sig: int = signal.SIGTERM, timeout_s: float = 10.0) -> None:
        """Forward ``sig`` to every worker and reap them (bounded: a
        worker ignoring SIGTERM past the deadline gets SIGKILL)."""
        import time

        self._stop.set()
        with self._lock:
            pids = list(self._pids)
        for pid in pids:
            try:
                os.kill(pid, sig)
            except ProcessLookupError:
                pass
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pids:
                    break
            # reap directly (the supervise thread may be mid-respawn)
            try:
                pid, _status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                with self._lock:
                    self._pids.clear()
                break
            if pid:
                with self._lock:
                    self._pids.pop(pid, None)
            elif self._stop.wait(0.05):
                continue
        with self._lock:
            leftover = list(self._pids)
        for pid in leftover:
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- obs source --------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "workers": self.n_workers,
                "alive": len(self._pids),
                "respawns_total": self.respawns,
            }
