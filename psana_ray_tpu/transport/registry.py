"""Named-queue rendezvous: the role Ray's GCS actor registry plays in the
reference.

Reference behavior being reproduced (``producer.py:35-71``):
- rank 0 get-or-creates the named queue, tolerating the create-vs-get race
  (``producer.py:42-48``);
- every participant then resolves the queue by (namespace, name) with a
  retry loop — 10 retries x 1 s, raising ``TimeoutError`` on exhaustion
  (``producer.py:56-67``);
- "detached" lifetime (``shared_queue.py:35``): the queue outlives its
  creator until explicitly destroyed.

Here the registry is an in-process singleton keyed by (namespace, name); the
cross-process/cross-host realizations (shm ring files, TCP endpoints) reuse
the same resolve-with-retry semantics via :func:`Registry.resolve`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


class TransportClosed(RuntimeError):
    """The transport (queue) is dead. Parity role: ``RayActorError`` at the
    producer (``producer.py:112``) / ``DataReaderError`` at the consumer
    (``data_reader.py:46-48``)."""


class TransportWedged(TransportClosed):
    """A peer process died mid-operation (claimed a queue slot and never
    committed/released it), permanently blocking the queue at that slot.
    Subclasses :class:`TransportClosed` so it is never mistaken for
    starvation — the silent-stall failure mode the reference's
    error-swallowing queue exhibits (SURVEY.md §3 quirk 5). Handlers that
    treat *closure* as a clean end of stream (batcher tail-flush, producer
    clean exit, EOS delivery) explicitly re-raise this subclass: a wedge
    means data loss, never normal completion. Recovery: destroy and
    recreate the ring; in-flight items in the wedged region are lost."""


class RendezvousTimeout(TimeoutError):
    """Queue never appeared. Parity: ``producer.py:67``."""


class Registry:
    """Process-wide named-object registry with detached lifetimes."""

    _global: Optional["Registry"] = None
    _global_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[Tuple[str, str], Any] = {}
        self._cond = threading.Condition(self._lock)

    @classmethod
    def default(cls) -> "Registry":
        with cls._global_lock:
            if cls._global is None:
                cls._global = Registry()
            return cls._global

    @classmethod
    def reset_default(cls):
        with cls._global_lock:
            cls._global = None

    def get_or_create(self, namespace: str, name: str, factory: Callable[[], Any]) -> Any:
        """Atomic get-or-create — closes the create-vs-get race the reference
        handles with try-get-first (``producer.py:42-48``)."""
        with self._lock:
            key = (namespace, name)
            if key not in self._objects:
                self._objects[key] = factory()
                self._cond.notify_all()
            return self._objects[key]

    def resolve(
        self,
        namespace: str,
        name: str,
        retries: int = 10,
        interval_s: float = 1.0,
    ) -> Any:
        """Resolve by name, retrying. Parity: ``producer.py:56-67``.

        Uses a condition wait rather than sleep-loop so in-process resolution
        is immediate; total timeout is ``retries * interval_s``."""
        deadline = time.monotonic() + retries * interval_s
        with self._lock:
            key = (namespace, name)
            while key not in self._objects:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RendezvousTimeout(
                        f"queue {name!r} in namespace {namespace!r} not found "
                        f"after {retries} x {interval_s}s"
                    )
                self._cond.wait(timeout=min(remaining, interval_s))
            return self._objects[key]

    def destroy(self, namespace: str, name: str):
        """Explicit teardown — the ``ray stop`` of this world
        (reference ``README.md:37-40``)."""
        with self._lock:
            obj = self._objects.pop((namespace, name), None)
        if obj is not None and hasattr(obj, "close"):
            obj.close()

    def list(self, namespace: Optional[str] = None):
        with self._lock:
            return [k for k in self._objects if namespace is None or k[0] == namespace]
