"""Exponential backoff with jitter — producer backpressure policy.

Parity with the reference's envelope (``producer.py:85-86,108-110``):
base 0.1 s, cap 2.0 s, uniform jitter [0, 0.5) s, retry counter frozen once
the cap is reached (``producer.py:111``). Parameterized and testable here
(the reference inlined it in the hot loop)."""

from __future__ import annotations

import random
import time
from typing import Callable, Optional


class BackoffPolicy:
    def __init__(
        self,
        base_s: float = 0.1,
        cap_s: float = 2.0,
        jitter_s: float = 0.5,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ):
        self.base_s = base_s
        self.cap_s = cap_s
        self.jitter_s = jitter_s
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._retries = 0

    def delay(self) -> float:
        """Next delay without sleeping (pure; unit-testable)."""
        d = min(self.cap_s, self.base_s * (2**self._retries))
        return d + self._rng.uniform(0, self.jitter_s)

    def wait(self) -> float:
        """Sleep the next delay and advance the counter. Returns the delay."""
        d = self.delay()
        self._sleep(d)
        # stop growing once capped — parity with producer.py:111
        if self.base_s * (2**self._retries) < self.cap_s:
            self._retries += 1
        return d

    def reset(self):
        self._retries = 0

    @property
    def retries(self) -> int:
        return self._retries
