"""Cross-process shared-memory ring: ctypes bindings over the C++ MPMC ring.

Same contract as :class:`psana_ray_tpu.transport.ring.RingBuffer` — put ->
bool / get -> item|EMPTY / size / close-with-TransportClosed — but the
queue lives in POSIX shared memory, so independent producer and consumer
*processes* on one host exchange frames with a single memcpy each way (the
reference needed two cross-node object-store hops through a Ray actor,
SURVEY.md §3.3).

Payloads are the wire format of :mod:`psana_ray_tpu.records` (FrameRecord /
EndOfStream); arbitrary Python objects are supported via pickle with a
1-byte tag.

The C library builds on demand with ``make`` (g++); see
``psana_ray_tpu/native/``.
"""

from __future__ import annotations

import ctypes
import logging
import os
import pickle
import subprocess
import threading
import time
from typing import Any, List, Optional

from psana_ray_tpu.obs.flight import FLIGHT
from psana_ray_tpu.records import EndOfStream, FrameRecord, encode_into, encoded_size
from psana_ray_tpu.transport.codec import TAG_PICKLE as _TAG_PICKLE
from psana_ray_tpu.transport.codec import TAG_RECORD as _TAG_RECORD
from psana_ray_tpu.transport.codec import TAG_VOID as _TAG_VOID
from psana_ray_tpu.transport.codec import decode_payload
from psana_ray_tpu.transport.registry import TransportClosed, TransportWedged
from psana_ray_tpu.transport.ring import EMPTY

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libshmring.so")

_lib = None
_lib_lock = threading.Lock()


def _lib_is_stale() -> bool:
    """True when the .so is missing or older than any native source —
    a stale binary must never shadow an edited shmring.cpp."""
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for fname in os.listdir(_NATIVE_DIR):
        if fname.endswith((".cpp", ".h", ".hpp")) or fname == "Makefile":
            if os.path.getmtime(os.path.join(_NATIVE_DIR, fname)) > lib_mtime:
                return True
    return False


def _load_lib() -> ctypes.CDLL:
    """Load (building/rebuilding if needed) the native library. Raises
    RuntimeError with guidance when no toolchain is available or the
    binary does not load on this platform.

    Build + load run under an inter-process file lock: the runbook starts
    producer and consumers near-simultaneously, and without the lock each
    process would race its own ``make`` while another dlopens the
    half-written .so."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        import fcntl

        lock_path = os.path.join(_NATIVE_DIR, ".build.lock")
        with open(lock_path, "w") as lock_f:
            fcntl.flock(lock_f, fcntl.LOCK_EX)
            try:
                if _lib_is_stale():  # re-check under the lock: a sibling
                    try:             # process may have just built it
                        subprocess.run(
                            ["make", "-C", _NATIVE_DIR, "-s", "-B"],
                            check=True,
                            capture_output=True,
                            timeout=120,
                        )
                    except (
                        subprocess.CalledProcessError,
                        FileNotFoundError,
                        subprocess.TimeoutExpired,
                    ) as e:
                        detail = getattr(e, "stderr", b"")
                        if not os.path.exists(_LIB_PATH):
                            raise RuntimeError(
                                "could not build native shm ring (needs g++/make); "
                                "use the in-process RingBuffer or TCP transport "
                                f"instead: {detail!r}"
                            ) from e
                        # stale-but-present binary + no toolchain: load as-is
                try:
                    lib = ctypes.CDLL(_LIB_PATH)
                except OSError as e:  # wrong arch/glibc for a prebuilt binary
                    raise RuntimeError(
                        f"native shm ring library failed to load on this platform "
                        f"({e}); use the in-process RingBuffer or TCP transport instead"
                    ) from e
            finally:
                fcntl.flock(lock_f, fcntl.LOCK_UN)
        lib.shmring_create.restype = ctypes.c_void_p
        lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.shmring_attach.restype = ctypes.c_void_p
        lib.shmring_attach.argtypes = [ctypes.c_char_p]
        lib.shmring_put.restype = ctypes.c_int
        lib.shmring_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.shmring_get.restype = ctypes.c_int64
        lib.shmring_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        for fn in ("shmring_size", "shmring_capacity", "shmring_slot_bytes"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.shmring_reserve.restype = ctypes.c_int
        lib.shmring_reserve.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.shmring_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.shmring_acquire.restype = ctypes.c_int64
        lib.shmring_acquire.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.shmring_release.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shmring_is_closed.restype = ctypes.c_int
        lib.shmring_is_closed.argtypes = [ctypes.c_void_p]
        lib.shmring_set_stall_timeout.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.shmring_begin_drain.argtypes = [ctypes.c_void_p]
        lib.shmring_close.argtypes = [ctypes.c_void_p]
        lib.shmring_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64 * 4)]
        lib.shmring_free.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _lib = lib
        return _lib


def native_available() -> bool:
    try:
        _load_lib()
        return True
    except RuntimeError:
        return False


class _SlotLease:
    """A consumed-but-unreleased ring slot backing a zero-copy record.

    ``get_batch_view`` hands out records whose panels view slot memory
    directly; this lease keeps the slot out of producers' hands until
    the payload has been copied onward (``FrameBatcher.push_view``
    releases right after the batch-arena copy). Idempotent; also fires
    on GC, so a dropped record frees its slot instead of wedging the
    ring. Holds the ring object itself — the mapping cannot be detached
    by GC while any slot lease is alive, and release after an explicit
    disconnect/destroy degrades to a no-op instead of touching a freed
    C handle."""

    __slots__ = ("_ring", "_ticket", "_released")

    def __init__(self, ring: "ShmRingBuffer", ticket: int):
        self._ring = ring
        self._ticket = ticket
        self._released = False

    def release(self):
        if self._released:
            return
        self._released = True
        ring = self._ring
        self._ring = None
        with ring._handle_lock:
            ring._slot_leases -= 1
            if ring._h:
                ring._lib.shmring_release(ring._h, self._ticket)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


class ShmRingBuffer:
    """MPMC shared-memory queue; create on one process, attach on others."""

    # epix10k2M f32 frame = 8.6 MB; default slot fits it + header slack
    DEFAULT_SLOT_BYTES = 9 * 1024 * 1024

    def __init__(self, handle, name: str, owner: bool):
        self._h = handle  # guarded-by: _handle_lock
        self.name = name
        self._owner = owner
        self._lib = _load_lib()
        # immutable after creation; cached so put()/put_wait spins skip
        # the FFI round trip
        self._slot_bytes = int(self._lib.shmring_slot_bytes(handle))
        self._voids_skipped = 0  # guarded-by: _handle_lock
        # outstanding zero-copy gets (see _SlotLease)
        self._slot_leases = 0  # guarded-by: _handle_lock
        # serializes EVERY use of the C handle — the read surface
        # (stats/size — scraped from metrics HTTP threads), the data ops
        # (put/get: held across the FFI call, so disconnect() can never
        # free the handle mid-memcpy), and teardown itself — against
        # disconnect()/destroy() freeing it: a check-then-use on _h alone
        # can still pass a freed pointer to C when any of them races
        # teardown (the PR 1 segfault class). REENTRANT because a
        # _SlotLease can release from __del__ — cyclic GC may run it on
        # the very thread that already holds this lock
        self._handle_lock = threading.RLock()

    def set_stall_timeout(self, seconds: float):
        """Wedge-detection window for THIS handle (0 disables): a slot
        claimed by a peer but left uncommitted/unreleased longer than this
        raises :class:`TransportWedged` instead of stalling forever."""
        with self._handle_lock:
            self._lib.shmring_set_stall_timeout(self._live_handle(), int(seconds * 1000))

    def _wedged_msg(self, peer: str, verb: str) -> str:
        # breadcrumb for the flight recorder: a wedged ring is the exact
        # postmortem case the black box exists for
        FLIGHT.record("shm_wedged", ring=self.name, peer=peer)
        return (
            f"shm ring {self.name!r} is wedged: a {peer} process claimed a "
            f"slot and never {verb} it (likely crashed mid-operation). "
            f"Destroy and recreate the ring to recover; in-flight items in "
            f"the wedged region are lost."
        )

    # -- construction -----------------------------------------------------
    @classmethod
    def create(
        cls, name: str, maxsize: int = 64, slot_bytes: int = DEFAULT_SLOT_BYTES
    ) -> "ShmRingBuffer":
        lib = _load_lib()
        h = lib.shmring_create(cls._shm_name(name), maxsize, slot_bytes)
        if not h:
            raise RuntimeError(f"shmring_create({name!r}) failed")
        return cls(h, name, owner=True)

    @classmethod
    def attach(cls, name: str, retries: int = 10, interval_s: float = 1.0) -> "ShmRingBuffer":
        """Attach with the rendezvous retry semantics (producer.py:56-67)."""
        lib = _load_lib()
        deadline = time.monotonic() + retries * interval_s
        while True:
            h = lib.shmring_attach(cls._shm_name(name))
            if h:
                return cls(h, name, owner=False)
            if time.monotonic() >= deadline:
                from psana_ray_tpu.transport.registry import RendezvousTimeout

                raise RendezvousTimeout(
                    f"shm ring {name!r} not found after {retries} x {interval_s}s"
                )
            time.sleep(interval_s)

    @staticmethod
    def _shm_name(name: str) -> bytes:
        clean = "".join(c if c.isalnum() or c in "-_." else "_" for c in name)
        return f"/psana_ray_tpu_{clean}".encode()

    # -- transport contract ----------------------------------------------
    # put/get serialize straight into / out of the claimed slot memory
    # (shmring_reserve/commit + acquire/release): a FrameRecord costs ONE
    # numpy memcpy each way instead of the bytes-assembly + ctypes-buffer
    # + decode-copy chain (measured 38 -> ~300 fps on 8.6 MB epix frames).
    def put(self, item: Any) -> bool:
        wire = isinstance(item, (FrameRecord, EndOfStream))
        slot_bytes = self._slot_bytes
        if wire:
            n = 1 + encoded_size(item)
            payload = None
        else:
            payload = _TAG_PICKLE + pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)
            n = len(payload)
        if n > slot_bytes:
            raise ValueError(f"message of {n} bytes exceeds slot size {slot_bytes}")
        ptr = ctypes.c_void_p()
        ticket = ctypes.c_uint64()
        # the lock is held across reserve -> encode -> commit: disconnect/
        # destroy must not munmap the slot while the memcpy into it runs
        # (reserve and commit are non-blocking C calls, and in-process
        # producers sharing one handle were already serialized by the GIL
        # around the FFI boundary, so this costs no real concurrency)
        with self._handle_lock:
            h = self._live_handle()
            rc = self._lib.shmring_reserve(h, ctypes.byref(ptr), ctypes.byref(ticket))
            if rc == 0:
                return False
            if rc == -2:
                raise TransportClosed(f"shm ring {self.name!r} is closed")
            if rc == -4:
                raise TransportWedged(self._wedged_msg("consumer", "released"))
            mv = memoryview((ctypes.c_ubyte * slot_bytes).from_address(ptr.value)).cast("B")
            ok = False
            try:
                if wire:
                    mv[0:1] = _TAG_RECORD
                    encode_into(item, mv[1:n])
                else:
                    mv[:n] = payload
                ok = True
            finally:
                # always publish the claimed slot — an unreleased claim
                # would wedge every consumer at this position forever. A
                # failed encode publishes a 1-byte void marker consumers
                # skip.
                if not ok:
                    mv[0:1] = _TAG_VOID
                self._lib.shmring_commit(h, ticket, n if ok else 1)
        return True

    def get(self) -> Any:
        return self._get(view=False)

    def get_view(self) -> Any:
        """Zero-copy get: a FrameRecord's panels VIEW the ring slot, the
        slot stays claimed, and the record carries a :class:`_SlotLease`
        — release it (``rec.release()`` / ``FrameBatcher.push_view``)
        right after copying the payload onward. Each outstanding lease
        keeps one slot from producers, so never hold many across
        blocking waits. Non-frame payloads decode as owned objects with
        the slot released immediately (same as :meth:`get`)."""
        return self._get(view=True)

    def _get(self, view: bool) -> Any:
        # loops past void slots (producer-side encode failures): a void is
        # consumed-and-skipped, NOT "empty" — real items may sit right
        # behind it, and reporting EMPTY here could convince a get_wait
        # caller at its deadline that the queue starved
        while True:
            ptr = ctypes.c_void_p()
            ticket = ctypes.c_uint64()
            # held across acquire -> decode -> release: teardown must not
            # munmap the slot while the decode copy (or the zero-copy view
            # hand-off) reads it — the same UAF class as the PR 1 scrape
            # segfault, on the data path. RLock: _SlotLease.release (e.g.
            # via GC inside decode's allocations) re-enters safely.
            with self._handle_lock:
                h = self._live_handle()
                n = self._lib.shmring_acquire(h, ctypes.byref(ptr), ctypes.byref(ticket))
                if n == -1:
                    return EMPTY
                if n == -2:
                    raise TransportClosed(f"shm ring {self.name!r} is closed")
                if n == -4:
                    raise TransportWedged(self._wedged_msg("producer", "committed"))
                mv = memoryview((ctypes.c_ubyte * int(n)).from_address(ptr.value)).cast("B")
                if bytes(mv[:1]) == _TAG_VOID:
                    self._voids_skipped += 1
                    self._lib.shmring_release(h, ticket)
                    continue
                if not view:
                    try:
                        return self._decode(mv)  # copies panels out of the slot
                    finally:
                        self._lib.shmring_release(h, ticket)
                self._slot_leases += 1
                lease = _SlotLease(self, int(ticket.value))
                try:
                    return decode_payload(mv, lease=lease)
                except BaseException:
                    lease.release()
                    raise

    def get_wait(self, timeout: Optional[float] = None, poll_s: float = 0.0002) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            item = self.get()
            if item is not EMPTY:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                return EMPTY
            time.sleep(poll_s)

    def put_wait(self, item: Any, timeout: Optional[float] = None, poll_s: float = 0.0002) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.put(item):
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    def get_batch(self, max_items: int, timeout: Optional[float] = None) -> List[Any]:
        return self._get_batch(max_items, timeout, view=False)

    def get_batch_view(self, max_items: int, timeout: Optional[float] = None) -> List[Any]:
        """Batch drain with ZERO-COPY records (see :meth:`get_view`):
        the one-memcpy consumer path ``batches_from_queue`` prefers when
        the transport offers it. Blocks only for the first item; every
        returned frame holds its slot until released, so consume the
        batch promptly (the batcher copies + releases per record)."""
        return self._get_batch(max_items, timeout, view=True)

    def _get_batch(self, max_items: int, timeout: Optional[float], view: bool) -> List[Any]:
        out = []
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:  # blocking first-get, matching get_wait's poll loop
            first = self._get(view)
            if first is not EMPTY:
                break
            if deadline is not None and time.monotonic() >= deadline:
                return out
            time.sleep(0.0002)
        out.append(first)
        while len(out) < max_items:
            item = self._get(view)
            if item is EMPTY:
                break
            out.append(item)
        return out

    def _live_handle(self):
        """The C handle, or TransportClosed after disconnect()/destroy().
        Every surface that hands the handle to C (data ops, stats/size
        scrapes — possibly after teardown) must fail as a catchable
        dead-transport error, never hand NULL to C (a segfault)."""
        # guarded-by-caller: _handle_lock
        h = self._h
        if not h:
            raise TransportClosed(f"shm ring {self.name!r} is detached")
        return h

    def size(self) -> int:
        with self._handle_lock:
            return int(self._lib.shmring_size(self._live_handle()))

    @property
    def maxsize(self) -> int:
        with self._handle_lock:
            return int(self._lib.shmring_capacity(self._live_handle()))

    @property
    def closed(self) -> bool:
        with self._handle_lock:
            return bool(self._lib.shmring_is_closed(self._live_handle()))

    def close(self):
        # no-op after disconnect()/destroy(): there is nothing left to
        # close, and the C side dereferences the handle without a NULL
        # check (same segfault class _live_handle guards the read surface
        # against; teardown paths may close and detach in either order —
        # the lock makes the check-then-use atomic vs a concurrent free)
        with self._handle_lock:
            if self._h:
                self._lib.shmring_close(self._h)

    def begin_drain(self):
        """Half-close for graceful teardown: producer puts/reserves are
        refused (they see the closed signal, a clean exit) while gets keep
        serving. Cross-process: every attached producer observes it."""
        with self._handle_lock:
            if self._h:
                self._lib.shmring_begin_drain(self._h)

    def stats(self) -> dict:
        buf = (ctypes.c_uint64 * 4)()
        with self._handle_lock:
            h = self._live_handle()
            self._lib.shmring_stats(h, ctypes.byref(buf))
            maxsize = int(self._lib.shmring_capacity(h))
            voids = self._voids_skipped
        return {
            "depth": int(buf[0]),
            "maxsize": maxsize,
            "puts": int(buf[1]),
            "gets": int(buf[2]),
            "puts_rejected": int(buf[3]),
            "voids_skipped": voids,
        }

    def disconnect(self):
        """Detach this handle (the ring survives for other processes)."""
        with self._handle_lock:
            self._warn_live_leases("disconnect")
            if self._h:
                self._lib.shmring_free(self._h, 0)
                self._h = None

    def destroy(self):
        """Detach AND unlink the shared memory object."""
        with self._handle_lock:
            self._warn_live_leases("destroy")
            if self._h:
                self._lib.shmring_free(self._h, 1)
                self._h = None

    def _warn_live_leases(self, what: str):
        # guarded-by-caller: _handle_lock. Unmapping under a zero-copy record's
        # panels view is use-after-munmap; surface it loudly — the fix is
        # to release (push_view/materialize) before teardown.
        if self._h and self._slot_leases > 0:
            logger.warning(
                "%s(%s) with %d zero-copy slot lease(s) outstanding — "
                "views into this ring become invalid",
                what, self.name, self._slot_leases,
            )

    def __del__(self):
        try:
            self.disconnect()
        except Exception:
            pass

    # -- payload codec ----------------------------------------------------
    @staticmethod
    def _decode(buf) -> Any:
        return decode_payload(buf)  # copies panels out of the slot view
