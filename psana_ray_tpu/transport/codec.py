"""Tagged payload codec shared by the byte-oriented transports (shm, TCP).

One leading tag byte selects the codec: ``R`` = records wire format
(:mod:`psana_ray_tpu.records` — FrameRecord/EndOfStream), ``P`` = pickle
(arbitrary Python objects), ``V`` = void (a slot committed by a producer
whose encode failed mid-write; consumers skip it). The zero-copy shm path
writes tag + record directly into slot memory (`shm_ring.put`); TCP
framing uses the scatter-gather form (:func:`encode_payload_parts` +
``socket.sendmsg``) so a frame is never materialized as a contiguous
bytes object; :func:`encode_payload` remains for callers that genuinely
need one buffer. The shared decoder accepts an optional buffer lease for
zero-copy records (see :func:`psana_ray_tpu.records.decode`).

Distributed-tracing contract (ISSUE 4): a sampled frame's
:class:`~psana_ray_tpu.obs.tracing.TraceContext` is part of the record
wire format itself (schema v3, records.py), so every path through this
codec — contiguous, scatter-gather, or encode-into-slot — preserves it
across transports with no codec-level branches; untraced frames encode
as v2, byte-identical to pre-tracing wire.

Wire compression (ISSUE 9): a fourth tag, ``C``, carries a COMPRESSED
frame payload on TCP connections that negotiated a codec (opcode 'Z',
transport/tcp.py — uncompressed stays the default, so wire bytes are
byte-identical for peers that never negotiate). The layout keeps the
record header readable without decompressing anything it doesn't have
to: ``C + codec_id:u8 + raw_len:u32 + head_len:u16`` followed by the
original tagged payload's first ``head_len`` bytes RAW (the record tag
+ frame header + shape) and then the codec's encoding of the panel
bytes. Compression is an ENCODING of the existing at-least-once
delivery contract, never a semantic change: a payload that expands
under its codec is sent raw (ordinary ``R`` framing), and decode is
tag-driven, so mixed-codec connections share one server. Both
directions stage through :class:`~psana_ray_tpu.utils.bufpool.
BufferPool` leases — compress into a lease that is released once the
bytes hit the socket, decompress into a lease that rides the decoded
record exactly like a plain pooled receive — so the zero-copy
discipline (copies/frame 1.00, steady-state pool allocs 0) holds on
the compressed path too. The codec registry lives at the bottom of
this module: ``none``, a pure-numpy chunk-min-offset + byte-shuffle +
RLE/bit-pack u16-class codec (``shuffle-rle``), and optional ``lz4`` /
``bitshuffle-lz4`` backends when those packages are importable.
"""

from __future__ import annotations

import pickle
import struct
import threading
import time
from typing import Any, List, Optional

import numpy as np

from psana_ray_tpu.records import EndOfStream, FrameRecord, decode

TAG_RECORD = b"R"
TAG_PICKLE = b"P"
TAG_VOID = b"V"
# compressed wire payload (ISSUE 9): tag + codec_id + raw_len + head_len
TAG_COMPRESSED = b"C"
_CPREFIX = struct.Struct("<BIH")  # codec_id:u8, raw_len:u32, head_len:u16
# payloads below this never compress: the codec header + plane metadata
# would eat the win and tiny control records dominate latency, not wire
WIRE_COMPRESS_MIN = 4096
# hostile-length guard for the DECOMPRESSED size a compressed prefix
# claims (mirrors transport _MAX_PAYLOAD: largest real frame ~67 MB)
_MAX_RAW_PAYLOAD = 256 * 1024 * 1024


def encode_payload_parts(item: Any) -> List[Any]:
    """``[tag+header bytes, payload buffer...]`` for scatter-gather send.

    For a FrameRecord the panel payload is the record's own memory
    (``wire_parts`` memoryview — zero copies here); everything else is a
    single small bytes part. ``b"".join(map(bytes, parts))`` equals
    :func:`encode_payload` for every item."""
    if isinstance(item, FrameRecord):
        header, payload = item.wire_parts()
        return [TAG_RECORD + header, payload]
    if isinstance(item, EndOfStream):
        return [TAG_RECORD + item.to_bytes()]
    return [TAG_PICKLE + pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)]


def payload_nbytes(parts: List[Any]) -> int:
    """Total wire length of :func:`encode_payload_parts` output. Any
    part exposing ``.nbytes`` counts by it (memoryviews, and the splice
    path's FileSpan — which has no ``len()`` because its bytes never
    enter the interpreter); plain bytes count by ``len``."""
    return sum(p.nbytes if hasattr(p, "nbytes") else len(p) for p in parts)


def encode_payload(item: Any) -> bytes:
    if isinstance(item, (FrameRecord, EndOfStream)):
        return TAG_RECORD + item.to_bytes()
    return TAG_PICKLE + pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)


def decode_payload(buf, lease=None, pool=None, lazy=False) -> Any:
    """Decode a tagged payload; accepts bytes or memoryview.

    Without ``lease`` the returned records own their data (panels copied
    out of ``buf``). With ``lease`` (a checked-out pool buffer that
    ``buf`` views), frame records are returned zero-copy with the lease
    attached — see :func:`psana_ray_tpu.records.decode` for the
    ownership contract; non-record payloads release the lease here.

    Compressed payloads (``TAG_COMPRESSED``, ISSUE 9) are transparent:
    the payload decompresses into a fresh lease from ``pool`` (default:
    the incoming lease's own pool; a plain ``bytearray`` when neither
    is given), the compressed staging lease is released, and decoding
    proceeds on the recovered bytes — so every receive path (client
    GET/stream, server PUT, cluster merge drain) handles any codec the
    peer negotiated with no call-site changes. Corruption in the
    compressed framing raises ``ConnectionError``: the byte stream is
    untrustworthy past this payload, so the connection must die (and
    the server's in-flight requeue path runs).

    ``lazy=True`` (the relay's receive path) skips the decompression
    when the codec can cheaply VALIDATE the stream instead: the frame
    comes back as a :class:`~psana_ray_tpu.records.LazyFrameRecord`
    whose panels inflate on first touch — a broker that re-sends the
    cached compressed bytes verbatim never pays codec CPU. Corruption
    still fails HERE (validate raises ConnectionError) exactly like
    the eager path, so delivery semantics do not change."""
    tag = bytes(buf[:1])
    if tag == TAG_COMPRESSED:
        return _decode_compressed(buf, lease, pool, lazy)
    body = buf[1:]
    if tag == TAG_RECORD:
        return decode(body, lease=lease)
    try:
        if tag == TAG_PICKLE:
            return pickle.loads(body)
        raise ValueError(f"unknown payload tag {tag!r}")
    finally:
        # after the parse, not before: a released buffer may be re-leased
        # by another thread while ``body`` is still being read
        if lease is not None:
            lease.release()


# ---------------------------------------------------------------------------
# Wire compression (ISSUE 9): negotiated per-connection payload codecs.
#
# A codec object exposes ``name``/``codec_id`` and two methods that work
# ENTIRELY in caller-owned buffers (pool leases on the hot path):
#
#   compress(src: memoryview, itemsize: int, dst: memoryview)
#       -> Optional[int]  — encode ``src`` (the frame's panel bytes;
#       ``itemsize`` is the panel dtype's element width for the shuffle)
#       into ``dst``; returns bytes written, or None when the encoding
#       would not fit ``dst`` (the caller's expansion-fallback budget —
#       the frame then ships raw under ordinary ``R`` framing).
#   decompress(src: memoryview, dst: memoryview) -> None — exact
#       inverse; ``len(dst)`` is the known original size. Raises
#       ValueError on any corruption (wrapped into ConnectionError by
#       decode_payload: a desynced stream must kill the connection).
# ---------------------------------------------------------------------------

CODEC_NONE = "none"
_SHUFFLE_HDR = struct.Struct("<BBII")  # flags, itemsize, n_body, n_tail
_PLANE_HDR = struct.Struct("<BI")  # mode, encoded length
_PLANE_RAW, _PLANE_RLE, _PLANE_PACKED = 0, 1, 2
_RLE_MAX_RUN = 65535  # u16 run counts; longer runs split
# chunk-min-offset transform (flags bit 0, u8/u16 elements): elements
# per chunk. Chosen so a chunk's pedestal drift stays small against
# readout noise while the offsets array stays negligible (2 bytes per
# 4096 elements)
_OFFSET_CHUNK = 4096


def _chunk_min_offsets(v):
    """Per-chunk minima of ``v`` (any unsigned dtype): ONE reduction
    pass. Subtracting them re-centers smooth detector payloads
    (pedestal + noise) near zero so the shuffled high planes collapse
    and the low planes bit-pack — the role delta coding plays in
    classic schemes, at a third of the memory passes and with no
    serial carry chain on decode."""
    n = v.size
    c = n // _OFFSET_CHUNK
    mins = np.empty(c + (1 if n % _OFFSET_CHUNK else 0), v.dtype)
    if c:
        mins[:c] = v[: c * _OFFSET_CHUNK].reshape(c, _OFFSET_CHUNK).min(axis=1)
    if n % _OFFSET_CHUNK:
        mins[c] = v[c * _OFFSET_CHUNK :].min()
    return mins


def _apply_offsets(src, mins, out, subtract: bool) -> None:
    """Modular per-chunk ``out = src -/+ mins``: one broadcast pass
    (``src`` may BE ``out`` for the in-place decode direction)."""
    n = src.size
    c = n // _OFFSET_CHUNK
    op = np.subtract if subtract else np.add
    if c:
        op(
            src[: c * _OFFSET_CHUNK].reshape(c, _OFFSET_CHUNK),
            mins[:c, None],
            out=out[: c * _OFFSET_CHUNK].reshape(c, _OFFSET_CHUNK),
        )
    if n % _OFFSET_CHUNK:
        op(src[c * _OFFSET_CHUNK :], mins[c], out=out[c * _OFFSET_CHUNK :])


def _pack_kbits(p, k: int):
    """Pack u8 values (< 2^k) at ``k`` bits each: a big-endian k*8-bit
    stream per 8-value group, built with ~8+k vectorized u8 column ops
    (value bits land in at most two adjacent output bytes; uint8 shift
    wrap IS the byte-boundary mask). Output: ceil(n/8)*k bytes."""
    n = p.size
    g = -(-n // 8)
    v = np.zeros((g, 8), np.uint8)
    v.reshape(-1)[:n] = p
    out = np.zeros((g, k), np.uint8)
    for i in range(8):
        hi = k * i + k  # value i occupies stream bits [k*i, hi)
        for j in range((k * i) // 8, (hi - 1) // 8 + 1):
            sh = (8 * j + 8) - hi
            if sh >= 0:
                out[:, j] |= v[:, i] << sh  # u8 wrap drops carried bits
            else:
                out[:, j] |= v[:, i] >> (-sh)
    return out.reshape(-1)


def _unpack_kbits(buf, n: int, k: int):
    g = -(-n // 8)
    if buf.size != g * k:
        raise ValueError(f"packed plane size {buf.size} != {g * k}")
    b = buf.reshape(g, k)
    v = np.zeros((g, 8), np.uint8)
    for i in range(8):
        hi = k * i + k
        for j in range((k * i) // 8, (hi - 1) // 8 + 1):
            sh = (8 * j + 8) - hi
            if sh >= 0:
                v[:, i] |= b[:, j] >> sh
            else:
                v[:, i] |= b[:, j] << (-sh)  # u8 wrap; mask clears strays
    if k < 8:
        v &= np.uint8((1 << k) - 1)
    return v.reshape(-1)[:n]


def _build_rle(p, n: int):
    change = np.flatnonzero(p[1:] != p[:-1])
    starts = np.empty(change.size + 1, np.int64)
    starts[0] = 0
    starts[1:] = change + 1
    lengths = np.diff(starts, append=n)
    reps = (lengths + (_RLE_MAX_RUN - 1)) // _RLE_MAX_RUN
    n_runs = int(reps.sum())
    values = np.repeat(p[starts], reps).astype(np.uint8)
    counts = np.full(n_runs, _RLE_MAX_RUN, np.uint16)
    last = np.cumsum(reps) - 1
    counts[last] = (lengths - (reps - 1) * _RLE_MAX_RUN).astype(np.uint16)
    return (
        4 + 3 * n_runs,
        [np.array([n_runs], np.uint32), values, counts],
    )


def _encode_plane(p):
    """Best encoding for one shuffled byte plane, sized EXACTLY from one
    histogram + one boundary count before anything is built:

    - raw — incompressible noise planes;
    - run-length — near-constant planes (the high bytes of shuffled
      detector u16);
    - k-bit packing WITH an exception list — planes that are small
      values plus rare outliers (offset-centered residuals around
      sparse photon peaks: one bright pixel must not force the whole
      plane to 8 bits). ``k == 0`` degenerates to a pure sparse
      encoding.

    Returns ``(mode, encoded_len, pieces)``; pieces are contiguous
    arrays written verbatim after the plane header. Mode choice runs on
    a 1/16 SAMPLE of large planes (estimates pick the candidate; the
    build's exact length is what lands in the stream, and raw wins
    whenever the built encoding disappoints)."""
    n = int(p.size)
    if not n:
        return (_PLANE_RAW, n, [p])
    g8 = -(-n // 8)
    step = 16 if n >= (1 << 16) else 1
    sample = p[::step]
    scale = n / sample.size
    hist = np.bincount(sample, minlength=256)
    cum = np.cumsum(hist)
    pk_k, pk_est = 0, None
    for k in range(8):
        n_exc = (sample.size - int(cum[(1 << k) - 1])) * scale
        cost = 5 + 5 * n_exc + (g8 * k if k else 0)
        if pk_est is None or cost < pk_est:
            pk_k, pk_est = k, cost
    # sampled boundary count UNDERESTIMATES runs shorter than the
    # stride; trusted only as a coarse "is this plane near-constant"
    nc_est = int(np.count_nonzero(sample[1:] != sample[:-1]) * scale)
    rle_est = 4 + 3 * (nc_est + 1)
    best_len, pieces = n, [p]  # raw baseline
    mode = _PLANE_RAW
    if rle_est < min(best_len, pk_est):
        blen, built = _build_rle(p, n)
        if blen < best_len:
            mode, best_len, pieces = _PLANE_RLE, blen, built
    if mode == _PLANE_RAW and pk_est < best_len:
        k = pk_k
        exc = p >= (1 << k) if k else p != 0
        pos = np.flatnonzero(exc).astype(np.uint32)
        blen = 5 + 5 * pos.size + (g8 * k if k else 0)
        if blen < best_len:
            built = [
                np.array([k], np.uint8),
                np.array([pos.size], np.uint32),
                pos,
                p[exc],
            ]
            if k:
                masked = p.copy()
                masked[pos] = 0
                built.append(_pack_kbits(masked, k))
            mode, best_len, pieces = _PLANE_PACKED, blen, built
    return (mode, best_len, pieces)


def _decode_plane(mv, off: int, mode: int, blen: int, n: int):
    if mode == _PLANE_RAW:
        if blen != n:
            raise ValueError(f"raw plane length {blen} != {n}")
        return np.frombuffer(mv, np.uint8, n, off)
    if mode == _PLANE_RLE:
        (n_runs,) = struct.unpack_from("<I", mv, off)
        if blen != 4 + 3 * n_runs:
            raise ValueError(f"rle plane length {blen} != 4+3*{n_runs}")
        values = np.frombuffer(mv, np.uint8, n_runs, off + 4)
        counts = np.frombuffer(mv, np.uint16, n_runs, off + 4 + n_runs)
        total = int(counts.sum(dtype=np.int64))
        if total != n:
            raise ValueError(f"rle plane expands to {total} != {n}")
        return np.repeat(values, counts)
    if mode == _PLANE_PACKED:
        k = mv[off]
        (n_exc,) = struct.unpack_from("<I", mv, off + 1)
        g8 = -(-n // 8)
        if k >= 8 or blen != 5 + 5 * n_exc + (g8 * k if k else 0):
            raise ValueError(
                f"packed plane k={k} n_exc={n_exc} length {blen} mismatch"
            )
        pos = np.frombuffer(mv, np.uint32, n_exc, off + 5)
        vals = np.frombuffer(mv, np.uint8, n_exc, off + 5 + 4 * n_exc)
        if k:
            plane = _unpack_kbits(
                np.frombuffer(mv, np.uint8, g8 * k, off + 5 + 5 * n_exc), n, k
            )
        else:
            plane = np.zeros(n, np.uint8)
        if n_exc:
            if int(pos.max()) >= n:
                raise ValueError("exception position out of range")
            plane[pos] = vals
        return plane
    raise ValueError(f"unknown plane mode {mode}")


class _ShuffleRle:
    """Pure-numpy chunk-min-offset + byte-shuffle + RLE/bit-pack codec
    for detector payloads — the stdlib-only default every deployment
    has.

    u16/u8 payloads are re-centered first by subtracting per-chunk
    minima (``_chunk_min_offsets``: pedestal + readout noise become
    small magnitudes, with no decode carry chain the way delta coding
    would have); then bytes shuffle into per-significance planes (SIMD
    via strided numpy views), and each plane ships as the smallest of
    raw / run-length / k-bit-packed. High planes of shuffled detector
    u16 are near-constant (RLE collapses them); low planes of the
    offset-centered residuals fit in a few bits (packing wins).
    Uniform-noise payloads refuse to shrink — compress() returns None
    and the frame ships raw (the expansion-fallback contract)."""

    name = "shuffle-rle"
    codec_id = 1

    def compress(self, src, itemsize: int, dst):
        data = np.frombuffer(src, dtype=np.uint8)
        n = data.size
        if itemsize not in (1, 2, 4, 8):
            itemsize = 1
        n_elems = n // itemsize
        n_body = n_elems * itemsize
        n_tail = n - n_body
        budget = len(dst)
        total = _SHUFFLE_HDR.size + n_tail
        if n_body == 0 or total >= budget:
            return None
        flags = 0
        body = data[:n_body]
        mins = None
        if itemsize <= 2:
            flags |= 1
            dt = np.uint16 if itemsize == 2 else np.uint8
            v = body.view(dt)
            if itemsize == 2:
                # sign-bias: two's-complement -> offset-binary, so the
                # chunk minima re-center i16 payloads too (a pure shift
                # for u16 — the subtracted minimum absorbs it)
                v = v ^ dt(0x8000)
            mins = _chunk_min_offsets(v)
            z = np.empty_like(v)
            _apply_offsets(v, mins, z, subtract=True)
            body = z.view(np.uint8)
            total += mins.nbytes
            if total >= budget:
                return None
        if itemsize == 2:
            # contiguous shift/mask split beats two strided byte
            # gathers (the hot epix/jungfrau u16 case)
            z16 = body.view(np.uint16)
            plane_arrays = [
                z16.astype(np.uint8),  # low bytes (widening truncate)
                (z16 >> 8).astype(np.uint8),  # high bytes
            ]
        else:
            planes = body.reshape(n_elems, itemsize)
            plane_arrays = [
                np.ascontiguousarray(planes[:, i]) for i in range(itemsize)
            ]
        encs = []
        for p in plane_arrays:
            enc = _encode_plane(p)
            total += _PLANE_HDR.size + enc[1]
            if total >= budget:
                return None  # expansion: caller falls back to raw
            encs.append(enc)
        _SHUFFLE_HDR.pack_into(dst, 0, flags, itemsize, n_body, n_tail)
        off = _SHUFFLE_HDR.size
        if mins is not None:
            end = off + mins.nbytes
            dst[off:end] = mins.data.cast("B")
            off = end
        for mode, blen, pieces in encs:
            _PLANE_HDR.pack_into(dst, off, mode, blen)
            off += _PLANE_HDR.size
            for arr in pieces:
                a = np.ascontiguousarray(arr)
                end = off + a.nbytes
                dst[off:end] = a.data.cast("B")
                off = end
        if n_tail:
            end = off + n_tail
            dst[off:end] = data[n_body:].data
            off = end
        return off

    def validate(self, src, out_len: int) -> None:
        """Structural proof that ``decompress(src, dst)`` with
        ``len(dst) == out_len`` CANNOT raise — every length relation,
        RLE count sum, and exception position is checked, and packed /
        raw plane CONTENT needs no checking (any bit pattern decodes).
        Cost: header arithmetic plus tiny metadata passes, no
        frame-sized work — this is what lets the relay accept a
        compressed frame lazily (LazyFrameRecord) while still failing
        corrupt payloads AT RECEIVE, where the in-flight requeue
        contract runs. Raises ValueError exactly when decompress
        would."""
        mv = src if isinstance(src, memoryview) else memoryview(src)
        try:
            flags, itemsize, n_body, n_tail = _SHUFFLE_HDR.unpack_from(mv, 0)
        except struct.error as e:
            raise ValueError(f"short shuffle header: {e}") from e
        if (
            itemsize not in (1, 2, 4, 8)
            or n_body % itemsize
            or n_body + n_tail != out_len
        ):
            raise ValueError(
                f"shuffle geometry body={n_body} tail={n_tail} "
                f"itemsize={itemsize} vs dst={out_len}"
            )
        n_elems = n_body // itemsize
        off = _SHUFFLE_HDR.size
        if flags & 1:
            if itemsize > 2:
                raise ValueError("offset coding on wide elements")
            n_chunks = -(-n_elems // _OFFSET_CHUNK)
            off += n_chunks * itemsize  # offsets content cannot fail
            if off > len(mv):
                raise ValueError("truncated offset table")
        for _ in range(itemsize):
            if off + _PLANE_HDR.size > len(mv):
                raise ValueError("truncated plane header")
            mode, blen = _PLANE_HDR.unpack_from(mv, off)
            off += _PLANE_HDR.size
            if off + blen > len(mv):
                raise ValueError("truncated plane body")
            if mode == _PLANE_RAW:
                if blen != n_elems:
                    raise ValueError(f"raw plane length {blen} != {n_elems}")
            elif mode == _PLANE_RLE:
                (n_runs,) = struct.unpack_from("<I", mv, off)
                if blen != 4 + 3 * n_runs:
                    raise ValueError(f"rle plane length {blen} mismatch")
                counts = np.frombuffer(mv, np.uint16, n_runs, off + 4 + n_runs)
                if int(counts.sum(dtype=np.int64)) != n_elems:
                    raise ValueError("rle counts do not cover the plane")
            elif mode == _PLANE_PACKED:
                k = mv[off]
                (n_exc,) = struct.unpack_from("<I", mv, off + 1)
                g8 = -(-n_elems // 8)
                if k >= 8 or blen != 5 + 5 * n_exc + (g8 * k if k else 0):
                    raise ValueError(f"packed plane k={k} length mismatch")
                if n_exc:
                    pos = np.frombuffer(mv, np.uint32, n_exc, off + 5)
                    if int(pos.max()) >= n_elems:
                        raise ValueError("exception position out of range")
            else:
                raise ValueError(f"unknown plane mode {mode}")
            off += blen
        if off + n_tail != len(mv):
            raise ValueError("shuffle stream length mismatch")

    def decompress(self, src, dst) -> None:
        mv = src if isinstance(src, memoryview) else memoryview(src)
        try:
            flags, itemsize, n_body, n_tail = _SHUFFLE_HDR.unpack_from(mv, 0)
        except struct.error as e:
            raise ValueError(f"short shuffle header: {e}") from e
        if (
            itemsize not in (1, 2, 4, 8)
            or n_body % itemsize
            or n_body + n_tail != len(dst)
        ):
            raise ValueError(
                f"shuffle geometry body={n_body} tail={n_tail} "
                f"itemsize={itemsize} vs dst={len(dst)}"
            )
        n_elems = n_body // itemsize
        out = np.frombuffer(dst, dtype=np.uint8)
        off = _SHUFFLE_HDR.size
        mins = None
        if flags & 1:
            if itemsize > 2:
                raise ValueError("offset coding on wide elements")
            dt = np.uint16 if itemsize == 2 else np.uint8
            n_chunks = -(-n_elems // _OFFSET_CHUNK)
            if off + n_chunks * itemsize > len(mv):
                raise ValueError("truncated offset table")
            mins = np.frombuffer(mv, dt, n_chunks, off)
            off += n_chunks * itemsize
        plane_arrays = []
        for _ in range(itemsize):
            if off + _PLANE_HDR.size > len(mv):
                raise ValueError("truncated plane header")
            mode, blen = _PLANE_HDR.unpack_from(mv, off)
            off += _PLANE_HDR.size
            if off + blen > len(mv):
                raise ValueError("truncated plane body")
            plane_arrays.append(_decode_plane(mv, off, mode, blen, n_elems))
            off += blen
        if itemsize == 2:
            # contiguous widen + shift-or beats two strided byte
            # scatters; the (typical) all-zero high plane skips its
            # passes entirely
            out16 = out[:n_body].view(np.uint16)
            out16[:] = plane_arrays[0]  # widening assign: low bytes
            hi = plane_arrays[1]
            if hi.any():
                np.bitwise_or(
                    out16, hi.astype(np.uint16) << np.uint16(8), out=out16
                )
        else:
            shuf = out[:n_body].reshape(n_elems, itemsize)
            for i, p in enumerate(plane_arrays):
                shuf[:, i] = p
        if mins is not None:
            v = out[:n_body].view(mins.dtype)
            _apply_offsets(v, mins, v, subtract=False)
            if itemsize == 2:
                np.bitwise_xor(v, mins.dtype.type(0x8000), out=v)
        if n_tail:
            if off + n_tail > len(mv):
                raise ValueError("truncated shuffle tail")
            out[n_body:] = np.frombuffer(mv, np.uint8, n_tail, off)
            off += n_tail
        if off != len(mv):
            raise ValueError(
                f"{len(mv) - off} trailing bytes after shuffle stream"
            )


# -- optional native backends (never required; register when importable) ----
try:  # pragma: no cover - depends on the environment
    import lz4.block as _lz4block
except Exception:  # ImportError or a broken install
    _lz4block = None

try:  # pragma: no cover - depends on the environment
    import bitshuffle as _bitshuffle
except Exception:
    _bitshuffle = None


class _Lz4Block:  # pragma: no cover - exercised only where lz4 exists
    """Raw-byte LZ4 block backend (no shuffle): the backend allocates
    its output internally — still correct, one staging copy into the
    lease; documented as the trade for native match-finding speed."""

    name = "lz4"
    codec_id = 2

    def compress(self, src, itemsize: int, dst):
        comp = _lz4block.compress(src, store_size=False)
        if len(comp) >= len(dst):
            return None
        dst[: len(comp)] = comp
        return len(comp)

    def decompress(self, src, dst) -> None:
        try:
            raw = _lz4block.decompress(src, uncompressed_size=len(dst))
        except Exception as e:
            raise ValueError(f"lz4 decompress failed: {e}") from e
        if len(raw) != len(dst):
            raise ValueError(f"lz4 length {len(raw)} != {len(dst)}")
        dst[:] = raw


class _BitshuffleLz4:  # pragma: no cover - exercised only where bitshuffle exists
    """bitshuffle + LZ4 (the HDF5 detector-data workhorse). The element
    width rides as one leading byte so decompress can rebuild the
    typed view."""

    name = "bitshuffle-lz4"
    codec_id = 3
    _DTYPES = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}

    def compress(self, src, itemsize: int, dst):
        dt = self._DTYPES.get(itemsize, np.uint8)
        arr = np.frombuffer(src, dtype=np.uint8)
        if arr.size % np.dtype(dt).itemsize:
            return None
        try:
            comp = _bitshuffle.compress_lz4(arr.view(dt))
        except Exception:
            return None
        if 1 + comp.nbytes >= len(dst):
            return None
        dst[0] = np.dtype(dt).itemsize
        dst[1 : 1 + comp.nbytes] = comp.data
        return 1 + comp.nbytes

    def decompress(self, src, dst) -> None:
        mv = src if isinstance(src, memoryview) else memoryview(src)
        dt = self._DTYPES.get(mv[0] if len(mv) else 0)
        if dt is None or len(dst) % np.dtype(dt).itemsize:
            raise ValueError("bitshuffle stream geometry")
        n = len(dst) // np.dtype(dt).itemsize
        try:
            raw = _bitshuffle.decompress_lz4(
                np.frombuffer(mv, np.uint8, len(mv) - 1, 1), (n,), np.dtype(dt)
            )
        except Exception as e:
            raise ValueError(f"bitshuffle decompress failed: {e}") from e
        np.frombuffer(dst, dtype=np.uint8)[:] = raw.view(np.uint8)


_CODECS: dict = {}  # name -> codec object
_CODECS_BY_ID: dict = {}


def _register_codec(codec) -> None:
    _CODECS[codec.name] = codec
    _CODECS_BY_ID[codec.codec_id] = codec


_register_codec(_ShuffleRle())
if _lz4block is not None:  # pragma: no cover - environment-dependent
    _register_codec(_Lz4Block())
if _bitshuffle is not None:  # pragma: no cover - environment-dependent
    _register_codec(_BitshuffleLz4())


def available_codecs():
    """Codec names this process can ENCODE AND DECODE, preference order
    (fast native backends first, the stdlib-only fallback last) — what a
    client advertises under ``codec="auto"``."""
    order = ("bitshuffle-lz4", "lz4", "shuffle-rle")
    return [n for n in order if n in _CODECS]


def get_codec(name: Optional[str]):
    """Resolve a codec name: None/"none" -> None (uncompressed), "auto"
    -> this process's preferred codec, a registered name -> its codec
    object; unknown names raise."""
    if name is None or name == CODEC_NONE:
        return None
    if name == "auto":
        avail = available_codecs()
        return _CODECS[avail[0]] if avail else None
    codec = _CODECS.get(name)
    if codec is None:
        raise ValueError(
            f"unknown wire codec {name!r} (available: "
            f"{[CODEC_NONE, *available_codecs()]})"
        )
    return codec


def negotiate_codec(client_names):
    """Server side of the 'Z' capability exchange: the first codec the
    client advertises that this process also implements wins; no
    overlap (or an explicit "none") means uncompressed."""
    for name in client_names:
        name = name.strip()
        if name == CODEC_NONE:
            return None
        codec = _CODECS.get(name)
        if codec is not None:
            return codec
    return None


class CodecTelemetry:
    """Wire-compression accounting (obs source ``wire_codec``):
    negotiations by codec, raw-vs-wire byte volumes both directions
    (their quotient IS the live compression ratio), codec latency
    EWMAs, and expansion fallbacks. One process-wide instance
    (:data:`CODEC_STATS`), registered on first negotiation."""

    _EWMA = 0.05
    EXPANSION_STORM_RUN = 32  # consecutive fallbacks per breadcrumb

    def __init__(self):
        self._lock = threading.Lock()
        self._registered = False  # guarded-by: _lock
        self.negotiations: dict = {}  # codec name -> count  # guarded-by: _lock
        self.frames_compressed = 0  # guarded-by: _lock
        self.frames_decompressed = 0  # guarded-by: _lock
        self.bytes_raw_out = 0  # pre-compression payload bytes  # guarded-by: _lock
        self.bytes_wire_out = 0  # post-compression wire bytes  # guarded-by: _lock
        self.bytes_wire_in = 0  # compressed bytes received  # guarded-by: _lock
        self.bytes_raw_in = 0  # decompressed payload bytes  # guarded-by: _lock
        self.expansions = 0  # frames that fell back to raw  # guarded-by: _lock
        self._expansion_run = 0  # consecutive, for the storm breadcrumb  # guarded-by: _lock
        self.cache_hits = 0  # relay pass-through re-sends  # guarded-by: _lock
        self.cache_hit_bytes = 0  # guarded-by: _lock
        self.lazy_frames = 0  # validated-not-decompressed receives  # guarded-by: _lock
        self.compress_ms_ewma = 0.0  # guarded-by: _lock
        self.decompress_ms_ewma = 0.0  # guarded-by: _lock

    def ensure_registered(self):
        with self._lock:
            if self._registered:
                return
            self._registered = True
        try:
            from psana_ray_tpu.obs import MetricsRegistry

            MetricsRegistry.default().register("wire_codec", self)
        except Exception:  # obs optional: transport must work without it
            pass

    def negotiated(self, name: str):
        self.ensure_registered()
        with self._lock:
            self.negotiations[name] = self.negotiations.get(name, 0) + 1

    def compressed(self, raw: int, wire: int, ms: float):
        with self._lock:
            self.frames_compressed += 1
            self.bytes_raw_out += raw
            self.bytes_wire_out += wire
            self.compress_ms_ewma += self._EWMA * (ms - self.compress_ms_ewma)
            self._expansion_run = 0

    def expanded(self, codec_name: str):
        with self._lock:
            self.expansions += 1
            self._expansion_run += 1
            storm = self._expansion_run == self.EXPANSION_STORM_RUN
            if storm:
                self._expansion_run = 0
        if storm:
            # every frame is refusing to shrink: the negotiated codec is
            # wasting CPU on this stream — worth a postmortem breadcrumb
            try:
                from psana_ray_tpu.obs.flight import FLIGHT

                FLIGHT.record(
                    "codec_expansion_storm",
                    codec=codec_name,
                    consecutive=self.EXPANSION_STORM_RUN,
                )
            except Exception:
                pass

    def cache_hit(self, nbytes: int):
        with self._lock:
            self.cache_hits += 1
            self.cache_hit_bytes += nbytes

    def lazy_frame(self):
        with self._lock:
            self.lazy_frames += 1

    def decompressed(self, wire: int, raw: int, ms: float):
        with self._lock:
            self.frames_decompressed += 1
            self.bytes_wire_in += wire
            self.bytes_raw_in += raw
            self.decompress_ms_ewma += self._EWMA * (
                ms - self.decompress_ms_ewma
            )

    def stats(self) -> dict:
        with self._lock:
            ratio_out = (
                self.bytes_raw_out / self.bytes_wire_out
                if self.bytes_wire_out
                else 0.0
            )
            ratio_in = (
                self.bytes_raw_in / self.bytes_wire_in
                if self.bytes_wire_in
                else 0.0
            )
            return {
                "negotiations": dict(self.negotiations),
                "frames_compressed_total": self.frames_compressed,
                "frames_decompressed_total": self.frames_decompressed,
                "bytes_raw_out_total": self.bytes_raw_out,
                "bytes_wire_out_total": self.bytes_wire_out,
                "bytes_wire_in_total": self.bytes_wire_in,
                "bytes_raw_in_total": self.bytes_raw_in,
                "ratio_out": round(ratio_out, 3),
                "ratio_in": round(ratio_in, 3),
                "expansions_total": self.expansions,
                "cache_hits_total": self.cache_hits,
                "cache_hit_bytes_total": self.cache_hit_bytes,
                "lazy_frames_total": self.lazy_frames,
                "compress_ms_ewma": round(self.compress_ms_ewma, 3),
                "decompress_ms_ewma": round(self.decompress_ms_ewma, 3),
            }

    # obs registry source protocol
    def snapshot(self) -> dict:
        return self.stats()


CODEC_STATS = CodecTelemetry()


def cached_wire_parts(item, codec):
    """Relay pass-through entry: when ``item`` carries compressed bytes
    for exactly ``codec`` (records.wire_cache), return them as a
    single-part payload — WITHOUT touching ``item.panels`` (a
    LazyFrameRecord must not inflate just to be re-sent verbatim).
    None means encode normally. Call BEFORE building raw parts."""
    cache = getattr(item, "wire_cache", None)
    if codec is not None and cache is not None and cache[0] == codec.codec_id:
        CODEC_STATS.cache_hit(cache[2].nbytes)
        return [cache[2]]
    return None


def encode_for_wire(item, codec, pool):
    """THE send-side dispatch both transports share (client put paths
    under the client lock, evloop response/push paths): scatter-gather
    parts for ``item`` under the connection's negotiated ``codec``,
    returned as ``(parts, staging_lease)``. The lease (None on the
    uncompressed / cached / too-small / expansion-fallback paths) backs
    the compressed part — release it only AFTER the parts have fully
    hit the socket. The relay pass-through cache (records.wire_cache)
    is consulted BEFORE building raw parts: a same-codec compressed
    record re-sends its exact received bytes without ever touching
    ``item.panels`` (building raw parts first would inflate every
    LazyFrameRecord and pay the decompression the lazy receive exists
    to avoid)."""
    if codec is None:
        return encode_payload_parts(item), None
    cached = cached_wire_parts(item, codec)
    if cached is not None:
        return cached, None
    return compress_encoded_parts(item, encode_payload_parts(item), codec, pool)


def compress_encoded_parts(item, parts, codec, pool):
    """Compress :func:`encode_payload_parts` output for a connection
    that negotiated ``codec``. Returns ``(wire_parts, staging_lease)``;
    the caller MUST release the lease only after the parts have fully
    hit the socket (it backs the compressed memoryview part). Frames
    that are too small, non-frame payloads, and frames the codec cannot
    shrink pass through UNCHANGED with a None lease — the expansion
    fallback that keeps compression an encoding, never a requirement."""
    if codec is None or not isinstance(item, FrameRecord) or len(parts) != 2:
        return parts, None
    cached = cached_wire_parts(item, codec)
    if cached is not None:
        # relay pass-through backstop for DIRECT callers (bench, tests):
        # this record arrived COMPRESSED with the same codec — re-send
        # the exact bytes, zero codec CPU. The cached lease rides the
        # record (released with it), so no staging lease changes hands.
        # The transports route through encode_for_wire, which consults
        # the cache before building raw parts (never inflating a
        # LazyFrameRecord) and so never reaches this arm.
        return cached, None
    head, body = parts
    nbody = body.nbytes
    raw_len = len(head) + nbody
    if raw_len > _MAX_RAW_PAYLOAD:
        # fail-fast parity with the raw path's send-side cap: an
        # oversized frame that COMPRESSES under the cap would pass the
        # transport's wire-length check, then die at the receiver's
        # raw_len guard — a poison record in a windowed-resend loop
        raise ValueError(
            f"payload length {raw_len} exceeds wire maximum {_MAX_RAW_PAYLOAD}"
        )
    if nbody < WIRE_COMPRESS_MIN:
        return parts, None
    out = pool.lease(nbody)
    t0 = time.monotonic()
    try:
        # budget strictly under the raw body: any accepted encoding is
        # a real win even after the compressed prefix rides along
        clen = codec.compress(
            body, item.panels.dtype.itemsize, out.mv[: nbody - 16]
        )
        if clen is None:
            CODEC_STATS.expanded(codec.name)
            out.release()
            return parts, None
        prefix = (
            TAG_COMPRESSED
            + _CPREFIX.pack(codec.codec_id, raw_len, len(head))
            + head
        )
        CODEC_STATS.compressed(
            raw_len, len(prefix) + clen, (time.monotonic() - t0) * 1000.0
        )
    except BaseException:
        # the except arm covers prefix assembly and the stats hooks
        # too, not just the compress call — a raise anywhere between
        # the lease and the hand-off below must not strand the staging
        # buffer (the resource-flow checker walks exactly this window)
        out.release()
        raise
    return [prefix, out.mv[:clen]], out


def _decode_compressed(buf, lease, pool, lazy=False):
    """Decompress a TAG_COMPRESSED payload into a fresh lease (or a
    bytearray off the pooled path) and decode the recovered bytes —
    or, with ``lazy`` and a validatable codec, return a
    LazyFrameRecord over the still-compressed bytes. Framing
    corruption becomes ConnectionError — see decode_payload."""
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    out = None
    try:
        try:
            codec_id, raw_len, head_len = _CPREFIX.unpack_from(mv, 1)
        except struct.error as e:
            raise ValueError(f"short compressed prefix: {e}") from e
        codec = _CODECS_BY_ID.get(codec_id)
        if codec is None:
            raise ValueError(f"unknown wire codec id {codec_id}")
        off = 1 + _CPREFIX.size
        if raw_len > _MAX_RAW_PAYLOAD or head_len > raw_len:
            raise ValueError(
                f"implausible geometry raw_len={raw_len} head_len={head_len}"
            )
        if len(mv) < off + head_len:
            raise ValueError("truncated compressed head")
        if pool is None and lease is not None:
            pool = lease.pool
        body = mv[off + head_len :]
        body_len = raw_len - head_len
        if lazy and lease is not None and hasattr(codec, "validate"):
            rec = _decode_lazy(
                codec, codec_id, mv, lease, pool, off, head_len, body, body_len
            )
            if rec is not None:
                return rec
        if pool is not None:
            out = pool.lease(raw_len)
            dst = out.mv
        else:
            dst = memoryview(bytearray(raw_len))
        t0 = time.monotonic()
        dst[:head_len] = mv[off : off + head_len]
        codec.decompress(body, dst[head_len:])
        CODEC_STATS.decompressed(
            len(mv), raw_len, (time.monotonic() - t0) * 1000.0
        )
    except ValueError as e:
        if out is not None:
            out.release()
        if lease is not None:
            lease.release()
        raise ConnectionError(f"corrupt compressed wire payload: {e}") from e
    except BaseException:
        if out is not None:
            out.release()
        if lease is not None:
            lease.release()
        raise
    try:
        if raw_len and dst[0] == TAG_COMPRESSED[0]:
            # no encoder ever nests 'C' in 'C' — a stream that
            # decompresses to another compressed payload is a crafted
            # recursion/amplification bomb, not desync noise
            raise ValueError("nested compressed framing")
        rec = decode_payload(dst, lease=out)
    except (ValueError, struct.error) as e:
        # a stream that decompresses cleanly but whose RAW bytes do not
        # parse (bad dtype code, lying shape) is corruption all the
        # same: same contract as the framing guards above — release
        # both leases (idempotent; decode_payload's pickle arm may have
        # released ``out`` already) and kill the connection
        if out is not None:
            out.release()
        if lease is not None:
            lease.release()
        raise ConnectionError(f"corrupt compressed wire payload: {e}") from e
    except BaseException:
        if out is not None:
            out.release()
        if lease is not None:
            lease.release()
        raise
    if lease is not None:
        if lazy and isinstance(rec, FrameRecord):
            # relay receive whose codec cannot validate lazily: keep the
            # COMPRESSED bytes checked out alongside the decompressed
            # panels so a push to a same-codec peer re-sends them
            # verbatim (records.py wire_cache — released with the
            # record). Plain consumers (lazy=False) never relay: caching
            # for them would pin a second pool buffer per in-flight
            # frame for nothing, so the staging lease goes back now.
            object.__setattr__(rec, "wire_cache", (codec_id, lease, mv))
        else:
            lease.release()
    return rec


def _decode_lazy(codec, codec_id, mv, lease, pool, off, head_len, body, body_len):
    """The relay's zero-codec-CPU receive: VALIDATE the compressed
    stream (so a corrupt payload still dies here, at receive), parse
    the raw head, and return a LazyFrameRecord whose panels inflate on
    first touch. Returns None when the payload is not a frame (the
    caller decompresses eagerly). Raises ValueError (wrapped by the
    caller) on corruption."""
    from psana_ray_tpu.records import make_lazy_frame, parse_frame_header

    head = mv[off : off + head_len]
    if not head_len or head[0] != TAG_RECORD[0]:
        return None  # compressed pickle/EOS: rare, eager path handles it
    try:
        rank, idx, shape, dtype, energy, ts, version, trace, hdr_len = (
            parse_frame_header(head[1:])
        )
    except (ValueError, struct.error) as e:
        raise ValueError(f"corrupt compressed frame head: {e}") from e
    panel_nbytes = int(np.prod(shape)) * dtype.itemsize if shape else 0
    if hdr_len + 1 != head_len or panel_nbytes != body_len:
        raise ValueError(
            f"compressed head geometry lies: header {hdr_len + 1} vs "
            f"{head_len}, panels {panel_nbytes} vs {body_len}"
        )
    codec.validate(body, body_len)
    CODEC_STATS.lazy_frame()
    # telemetry mirrors the eager path: wire = the whole 'C' payload,
    # raw = head + panels — so ratio_in reads the same for a relay and
    # a plain consumer of identical traffic (plain ints: the closure
    # must stay cycle-free)
    wire_len = mv.nbytes
    raw_len = head_len + body_len

    def inflate():
        # returns (panels, lease); MUST NOT capture the record — that
        # cycle would defer every pool lease to a gc pass (see
        # records.LazyFrameRecord.panels)
        dst_lease = pool.lease(panel_nbytes) if pool is not None else None
        try:
            dst = (
                dst_lease.mv
                if dst_lease is not None
                else memoryview(bytearray(panel_nbytes))
            )
            t0 = time.monotonic()
            codec.decompress(body, dst)  # validated: cannot raise
            CODEC_STATS.decompressed(
                wire_len, raw_len, (time.monotonic() - t0) * 1000.0
            )
        except BaseException:
            if dst_lease is not None:
                dst_lease.release()
            raise
        return np.frombuffer(dst, dtype=dtype).reshape(shape), dst_lease

    return make_lazy_frame(
        rank, idx, energy, ts, version, trace, panel_nbytes,
        inflate, (codec_id, lease, mv),
    )
