"""Tagged payload codec shared by the byte-oriented transports (shm, TCP).

One leading tag byte selects the codec: ``R`` = records wire format
(:mod:`psana_ray_tpu.records` — FrameRecord/EndOfStream), ``P`` = pickle
(arbitrary Python objects), ``V`` = void (a slot committed by a producer
whose encode failed mid-write; consumers skip it). The zero-copy shm path
writes tag + record directly into slot memory (`shm_ring.put`); this
module provides the bytes-building variant for transports that need a
contiguous payload (TCP framing) and the shared decoder.
"""

from __future__ import annotations

import pickle
from typing import Any

from psana_ray_tpu.records import EndOfStream, FrameRecord, decode

TAG_RECORD = b"R"
TAG_PICKLE = b"P"
TAG_VOID = b"V"


def encode_payload(item: Any) -> bytes:
    if isinstance(item, (FrameRecord, EndOfStream)):
        return TAG_RECORD + item.to_bytes()
    return TAG_PICKLE + pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)


def decode_payload(buf) -> Any:
    """Decode a tagged payload; accepts bytes or memoryview. Returned
    records own their data (panels copied out of ``buf``)."""
    tag = bytes(buf[:1])
    body = buf[1:]
    if tag == TAG_RECORD:
        return decode(body)
    if tag == TAG_PICKLE:
        return pickle.loads(body)
    raise ValueError(f"unknown payload tag {tag!r}")
