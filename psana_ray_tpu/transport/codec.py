"""Tagged payload codec shared by the byte-oriented transports (shm, TCP).

One leading tag byte selects the codec: ``R`` = records wire format
(:mod:`psana_ray_tpu.records` — FrameRecord/EndOfStream), ``P`` = pickle
(arbitrary Python objects), ``V`` = void (a slot committed by a producer
whose encode failed mid-write; consumers skip it). The zero-copy shm path
writes tag + record directly into slot memory (`shm_ring.put`); TCP
framing uses the scatter-gather form (:func:`encode_payload_parts` +
``socket.sendmsg``) so a frame is never materialized as a contiguous
bytes object; :func:`encode_payload` remains for callers that genuinely
need one buffer. The shared decoder accepts an optional buffer lease for
zero-copy records (see :func:`psana_ray_tpu.records.decode`).

Distributed-tracing contract (ISSUE 4): a sampled frame's
:class:`~psana_ray_tpu.obs.tracing.TraceContext` is part of the record
wire format itself (schema v3, records.py), so every path through this
codec — contiguous, scatter-gather, or encode-into-slot — preserves it
across transports with no codec-level branches; untraced frames encode
as v2, byte-identical to pre-tracing wire.
"""

from __future__ import annotations

import pickle
from typing import Any, List

from psana_ray_tpu.records import EndOfStream, FrameRecord, decode

TAG_RECORD = b"R"
TAG_PICKLE = b"P"
TAG_VOID = b"V"


def encode_payload_parts(item: Any) -> List[Any]:
    """``[tag+header bytes, payload buffer...]`` for scatter-gather send.

    For a FrameRecord the panel payload is the record's own memory
    (``wire_parts`` memoryview — zero copies here); everything else is a
    single small bytes part. ``b"".join(map(bytes, parts))`` equals
    :func:`encode_payload` for every item."""
    if isinstance(item, FrameRecord):
        header, payload = item.wire_parts()
        return [TAG_RECORD + header, payload]
    if isinstance(item, EndOfStream):
        return [TAG_RECORD + item.to_bytes()]
    return [TAG_PICKLE + pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)]


def payload_nbytes(parts: List[Any]) -> int:
    """Total wire length of :func:`encode_payload_parts` output."""
    return sum(p.nbytes if isinstance(p, memoryview) else len(p) for p in parts)


def encode_payload(item: Any) -> bytes:
    if isinstance(item, (FrameRecord, EndOfStream)):
        return TAG_RECORD + item.to_bytes()
    return TAG_PICKLE + pickle.dumps(item, protocol=pickle.HIGHEST_PROTOCOL)


def decode_payload(buf, lease=None) -> Any:
    """Decode a tagged payload; accepts bytes or memoryview.

    Without ``lease`` the returned records own their data (panels copied
    out of ``buf``). With ``lease`` (a checked-out pool buffer that
    ``buf`` views), frame records are returned zero-copy with the lease
    attached — see :func:`psana_ray_tpu.records.decode` for the
    ownership contract; non-record payloads release the lease here."""
    tag = bytes(buf[:1])
    body = buf[1:]
    if tag == TAG_RECORD:
        return decode(body, lease=lease)
    try:
        if tag == TAG_PICKLE:
            return pickle.loads(body)
        raise ValueError(f"unknown payload tag {tag!r}")
    finally:
        # after the parse, not before: a released buffer may be re-leased
        # by another thread while ``body`` is still being read
        if lease is not None:
            lease.release()
