"""In-process bounded ring buffer with the reference queue's semantics.

Reference: ``shared_queue.py`` — a Ray actor wrapping ``collections.deque``
with non-blocking ``put -> False`` when full (``:11-14``), ``get -> None``
when empty (``:19-24``), and ``size`` (``:26-31``). Here the same contract is
an in-process object: the Ray actor serialized all access through one
process; we serialize through one lock, which is the same guarantee without
the two cross-node object-store hops of SURVEY.md §3.3.

Improvements over the reference (explicitly, per SURVEY.md §3 quirks):
- ``get`` returns the typed :data:`EMPTY` sentinel, never a ``None`` that
  could be confused with data or EOS;
- blocking ``put``/``get`` with condition variables and timeouts, so callers
  need not spin-sleep (the reference consumer polls at 1 Hz,
  ``psana_consumer.py:40``);
- ``get_batch`` drains up to N items in one lock acquisition — the infeed's
  building block;
- ``close()`` wakes all waiters and makes further ops raise
  :class:`TransportClosed`, giving dead-transport detection parity with the
  reference's ``RayActorError`` paths (``producer.py:112-114``,
  ``data_reader.py:36-37``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional

from psana_ray_tpu.transport.registry import TransportClosed


class _Sentinel:
    __slots__ = ("_name",)

    def __init__(self, name: str):
        self._name = name

    def __repr__(self):
        return f"<{self._name}>"


EMPTY = _Sentinel("EMPTY")  # queue momentarily empty — try again
FULL = _Sentinel("FULL")  # queue full — backpressure


class RingBuffer:
    """Thread-safe bounded FIFO with non-blocking and blocking interfaces."""

    def __init__(self, maxsize: int = 100, name: str = "shared_queue"):
        if maxsize <= 0:
            raise ValueError("maxsize must be positive")
        self.maxsize = int(maxsize)
        self.name = name
        self._q: deque = deque()  # guarded-by: _lock
        self._lock = threading.Lock()
        # Condition(self._lock): holding either condition IS holding _lock
        # (the lint lock-discipline checker understands the aliasing)
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False  # guarded-by: _lock
        self._draining = False  # guarded-by: _lock
        # change listeners: non-blocking callbacks poked after any state
        # change a waiter could care about (item added, space freed,
        # close/drain) — the event-loop TCP server registers its waker
        # here so an in-process put wakes the selector immediately
        # instead of at the next poll tick
        self._listeners: list = []  # guarded-by: _lock
        # lifetime counters (observability the reference lacks, SURVEY.md §5)
        self._n_put = 0  # guarded-by: _lock
        self._n_get = 0  # guarded-by: _lock
        self._n_put_rejected = 0  # guarded-by: _lock
        self._high_water = 0  # guarded-by: _lock
        self._last_put_t: float = -1.0  # monotonic; -1 = never  # guarded-by: _lock
        self._last_get_t: float = -1.0  # guarded-by: _lock

    # -- storage hooks ----------------------------------------------------
    # The log-backed variant (psana_ray_tpu.storage.durable.
    # DurableRingBuffer) reuses ALL of this class's locking, condition,
    # listener and lifecycle machinery by overriding just these two
    # boxing hooks: ``_box`` maps an incoming item to its stored form
    # (durable: append to the segment log, possibly spilling the RAM
    # copy), ``_unbox`` maps the stored form back to the delivered item
    # (durable: re-read a spilled record from the log). The base class
    # stores items as themselves.
    def _box(self, item: Any) -> Any:
        # guarded-by-caller: _lock
        return item

    def _box_front(self, item: Any) -> Any:
        """Boxing for HEAD re-insertion (the put_front recovery path);
        durable reinstates the item's original log offset instead of
        assigning a new one."""
        # guarded-by-caller: _lock
        return self._box(item)

    def _unbox(self, stored: Any) -> Any:
        # guarded-by-caller: _lock
        return stored

    # -- reference-parity non-blocking surface ---------------------------
    def put(self, item: Any) -> bool:
        """Append if not full. Returns False when full (never drops).
        Parity: ``shared_queue.py:11-14``."""
        with self._lock:
            self._check_open()
            self._check_accepting()
            if len(self._q) >= self.maxsize:
                self._n_put_rejected += 1
                return False
            self._q.append(self._box(item))
            self._note_put()
            self._not_empty.notify()
            return True

    def get(self) -> Any:
        """Pop the oldest item, or :data:`EMPTY` when none available.
        Parity: ``shared_queue.py:19-24`` (which returned an ambiguous None)."""
        with self._lock:
            self._check_open()
            if not self._q:
                return EMPTY
            # unbox BEFORE popping: a failing unbox (durable spill
            # re-read) must leave the entry queued, not strand it
            item = self._unbox(self._q[0])
            self._q.popleft()
            self._note_get()
            self._not_full.notify()
            return item

    def size(self) -> int:
        """Current depth. Parity: ``shared_queue.py:26-31``."""
        with self._lock:
            return len(self._q)

    def put_front(self, item: Any) -> bool:
        """Return an item to the HEAD of the queue (recovery path: an item
        popped but never delivered must come back *before* anything behind
        it — especially EOS markers, or a tally-driven consumer stops
        without ever seeing it). Exceeding maxsize by the returned item is
        allowed: it was counted when first enqueued."""
        with self._lock:
            self._check_open()
            self._q.appendleft(self._box_front(item))
            if len(self._q) > self._high_water:
                self._high_water = len(self._q)
            self._not_empty.notify()
            self._notify_listeners()
            return True

    # -- change listeners -------------------------------------------------
    def add_listener(self, cb) -> None:
        """Register a NON-BLOCKING callback invoked (with the queue lock
        held — keep it to a self-pipe write or a flag set) after any
        put/get/close/drain state change. Used by the event-loop TCP
        server's waker so waiters are served the instant an in-process
        producer enqueues."""
        with self._lock:
            self._listeners.append(cb)

    def remove_listener(self, cb) -> None:
        with self._lock:
            try:
                self._listeners.remove(cb)
            except ValueError:
                pass

    def _notify_listeners(self):
        # guarded-by-caller: _lock
        for cb in self._listeners:
            try:
                cb()
            except Exception:  # a broken listener must not break the queue
                pass

    # -- blocking variants (new capability) ------------------------------
    def put_wait(self, item: Any, timeout: Optional[float] = None) -> bool:
        """Block until space is available (or timeout). Returns success."""
        with self._not_full:
            ok = self._not_full.wait_for(
                lambda: self._closed or self._draining or len(self._q) < self.maxsize,
                timeout=timeout,
            )
            self._check_open()
            self._check_accepting()
            if not ok:
                return False
            self._q.append(self._box(item))
            self._note_put()
            self._not_empty.notify()
            return True

    def get_wait(self, timeout: Optional[float] = None) -> Any:
        """Block until an item is available (or timeout -> :data:`EMPTY`)."""
        with self._not_empty:
            ok = self._not_empty.wait_for(lambda: self._closed or bool(self._q), timeout=timeout)
            self._check_open()
            if not ok or not self._q:
                return EMPTY
            item = self._unbox(self._q[0])  # peek-unbox-pop: see get()
            self._q.popleft()
            self._note_get()
            self._not_full.notify()
            return item

    def get_batch(self, max_items: int, timeout: Optional[float] = None) -> List[Any]:
        """Drain up to ``max_items`` in one lock acquisition. Blocks for the
        first item up to ``timeout``; never blocks for subsequent items.
        The infeed batcher's building block — amortizes synchronization the
        way the reference's per-event RPC (``data_reader.py:35``) cannot."""
        with self._not_empty:
            ok = self._not_empty.wait_for(lambda: self._closed or bool(self._q), timeout=timeout)
            self._check_open()
            if not ok:
                return []
            n = min(max_items, len(self._q))
            out: List[Any] = []
            try:
                for _ in range(n):
                    # unbox BEFORE popping so a failure leaves the
                    # failing entry queued...
                    out.append(self._unbox(self._q[0]))
                    self._q.popleft()
            except BaseException:
                # ...and REINSTATES the prefix already popped: without
                # this, those entries would sit delivered-to-nobody (a
                # durable queue would pin its committed floor under
                # them until restart — an in-process hole)
                for item in reversed(out):
                    self._q.appendleft(self._box_front(item))
                raise
            if out:
                self._note_get(len(out))
                self._not_full.notify_all()
            return out

    # -- lifecycle -------------------------------------------------------
    def close(self):
        """Mark dead: wake all waiters; further ops raise TransportClosed.
        Gives consumers/producers the reference's dead-actor detection
        (``RayActorError`` -> exit, producer.py:112-114) without Ray."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
            self._notify_listeners()

    def begin_drain(self):
        """Half-close for graceful teardown: producers are refused (they
        see the dead-queue signal and exit cleanly) while consumers keep
        reading what is already queued."""
        with self._lock:
            self._draining = True
            self._not_full.notify_all()
            self._notify_listeners()

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def _check_open(self):
        # guarded-by-caller: _lock
        if self._closed:
            raise TransportClosed(f"queue {self.name!r} is closed")

    def _check_accepting(self):
        # guarded-by-caller: _lock
        if self._draining:
            raise TransportClosed(f"queue {self.name!r} is draining (shutdown)")

    # -- observability ---------------------------------------------------
    def _note_put(self):
        # guarded-by-caller: _lock
        self._n_put += 1
        depth = len(self._q)
        if depth > self._high_water:
            self._high_water = depth
        self._last_put_t = time.monotonic()
        self._notify_listeners()

    def _note_get(self, n: int = 1):
        # guarded-by-caller: _lock
        self._n_get += n
        self._last_get_t = time.monotonic()
        self._notify_listeners()

    def stats(self) -> dict:
        """Depth + lifetime counters + the health fields the stall
        detector and stats RPC read: ``high_water`` (max depth ever seen)
        and ``last_put_age_s``/``last_get_age_s`` (seconds since the last
        producer/consumer touch; -1 = never) for liveness."""
        with self._lock:
            # sampled under the lock: outside it a concurrent put/get
            # could advance _last_put_t past `now` -> negative age
            now = time.monotonic()
            return {
                "depth": len(self._q),
                "maxsize": self.maxsize,
                "puts": self._n_put,
                "gets": self._n_get,
                "puts_rejected": self._n_put_rejected,
                "high_water": self._high_water,
                "last_put_age_s": round(now - self._last_put_t, 3) if self._last_put_t >= 0 else -1.0,
                "last_get_age_s": round(now - self._last_get_t, 3) if self._last_get_t >= 0 else -1.0,
                "closed": self._closed,
                "draining": self._draining,
            }
