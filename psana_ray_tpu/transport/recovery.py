"""The one audited put-back path for already-popped items.

Three situations return items a process popped (or held) to a shared
queue: a TCP client dying mid-response (``tcp.TcpQueueServer._requeue``),
a get-batch straddling the tally-completing EOS (``infeed.batcher``), and
a consumer exiting while holding sibling EOS markers
(``records.EosTally.flush_duplicates``). They all route here so recovery
semantics — head placement when the transport supports it, bounded timed
retries otherwise, and a logged (never silent) drop — stay consistent.
"""

from __future__ import annotations

import logging
import time
from typing import Any, List, Sequence

from psana_ray_tpu.transport.registry import TransportClosed

logger = logging.getLogger(__name__)


def return_to_queue(
    queue,
    items: Sequence[Any],
    *,
    timeout_s: float = 30.0,
    what: str = "in-flight item",
) -> List[Any]:
    """Return ``items`` (FIFO order preserved) to ``queue``.

    Prefers ``put_front`` — head placement keeps recovered items ahead of
    any EOS markers behind them (a tally-driven consumer would otherwise
    stop before reading them), and is allowed past maxsize so it cannot
    fail. Transports without it get tail appends with timed retries up to
    ``timeout_s`` total.

    Returns the items that could NOT be returned (always logged, never a
    silent drop); empty on success or when the queue is closed (a dead
    transport has no sibling left to starve).
    """
    items = list(items)
    if not items:
        return []
    put_front = getattr(queue, "put_front", None)
    if put_front is not None:
        # appendleft in reverse so items[0] ends up at the head
        for item in reversed(items):
            try:
                put_front(item)
            except TransportClosed:
                return []
        return []
    deadline = time.monotonic() + timeout_s
    for i, item in enumerate(items):
        returned = False
        while time.monotonic() < deadline:
            wait = min(5.0, max(0.1, deadline - time.monotonic()))
            try:
                if queue.put_wait(item, timeout=wait):
                    returned = True
                    break
            except TransportClosed:
                return []
        if not returned:
            rest = items[i:]
            logger.warning(
                "dropping %d %s(s): queue stayed full for %.0f s",
                len(rest), what, timeout_s,
            )
            return rest
    return []
