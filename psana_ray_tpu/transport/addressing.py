"""Address-scheme resolution: one queue-opening surface for every transport.

The reference rendezvouses through Ray's GCS: producers and consumers name
a queue and namespace, and the cluster resolves it (``shared_queue.py:35``,
``producer.py:56-67``, ``data_reader.py:20``). Here the address string
selects the transport and the (namespace, queue_name) pair still names the
queue within it:

- ``auto`` / ``local`` — in-process :class:`Registry` (tests, single-process
  pipelines, threads);
- ``shm://`` or ``shm://<name>`` — cross-process POSIX shared-memory ring on
  one host. With no explicit ``<name>``, the ring is named from
  ``<namespace>__<queue_name>`` so the producer CLI and DataReader
  rendezvous from config alone, exactly like the reference's named actors.
  The ring is *detached* (parity: ``shared_queue.py:35``): it outlives its
  creator until destroyed;
- ``tcp://host:port`` — cross-host queue server (see
  :mod:`psana_ray_tpu.queue_server`). The (namespace, queue_name) pair
  selects a *named queue on that server* (OPEN opcode): one server per
  cluster hosts every detector's queue, exactly like Ray's GCS hosts many
  named actors.
- ``cluster://host:port,host:port,...`` — a SHARDED queue service over N
  queue servers (:mod:`psana_ray_tpu.cluster`): the logical queue splits
  into ``config.cluster_partitions`` partitions placed by rendezvous
  hashing over the server list; the returned :class:`~psana_ray_tpu.
  cluster.client.ClusterClient` speaks the same transport contract, so
  everything downstream is unchanged. ``config.group`` enrolls a
  consumer in a named consumer group (disjoint partition assignment,
  rebalance on membership change, one aggregated EOS per group).

Producers open with ``role='producer'`` (get-or-create semantics, parity
``producer.py:42-48``); consumers with ``role='consumer'`` (resolve with
retry, parity ``producer.py:56-67``).
"""

from __future__ import annotations

from typing import Optional

from psana_ray_tpu.config import TransportConfig
from psana_ray_tpu.transport.registry import Registry, RendezvousTimeout


def shm_ring_name(config: TransportConfig, address: Optional[str] = None) -> str:
    """The shm object name for a config: explicit ``shm://<name>`` wins,
    else derived from (namespace, queue_name)."""
    address = address or config.address
    explicit = address[len("shm://"):] if address.startswith("shm://") else ""
    return explicit or f"{config.namespace}__{config.queue_name}"


def add_cluster_args(parser, consumer: bool = False) -> None:
    """The shared ``--cluster`` CLI surface (producer / consumer / sfx):
    pointing a CLI at a sharded queue service is an address-list change,
    nothing else."""
    parser.add_argument(
        "--cluster", default=None, metavar="HOST:PORT,HOST:PORT",
        help="queue-server cluster: shard the logical queue over these "
        "servers (overrides --address with cluster://...). The FIRST "
        "server doubles as the consumer-group coordinator. Every "
        "producer and consumer of one stream must pass the same list "
        "and --partitions",
    )
    parser.add_argument(
        "--partitions", type=int, default=8,
        help="partitions the logical queue shards into across the "
        "cluster (fixed for the life of a stream)",
    )
    if consumer:
        parser.add_argument(
            "--group", default="",
            help="consumer-group name: members share the stream with "
            "disjoint partition assignments, rebalancing on "
            "join/leave/death; empty = compete on all partitions",
        )
        parser.add_argument(
            "--member_id", default="",
            help="stable member id within --group (default: random per "
            "process — fine unless you want sticky assignment)",
        )


def apply_cluster_args(config: TransportConfig, args) -> TransportConfig:
    """Fold the ``--cluster`` flags into a TransportConfig (no-op when
    the flag is absent)."""
    import dataclasses

    if not getattr(args, "cluster", None):
        return config
    return dataclasses.replace(
        config,
        address=f"cluster://{args.cluster}",
        cluster_partitions=args.partitions,
        group=getattr(args, "group", "") or "",
        member_id=getattr(args, "member_id", "") or "",
    )


def add_wire_args(parser, producer: bool = False) -> None:
    """The shared wire-compression CLI surface (ISSUE 9)."""
    parser.add_argument(
        "--wire_codec", default="", metavar="auto|none|NAME[,NAME]",
        help="negotiate per-connection wire compression with the queue "
        "server (tcp:// and cluster:// transports): 'auto' DECIDES per "
        "connection from a brief link-rate probe at connect — "
        "compression on through slow links (tunnels), off on fast LANs "
        "where the codec only burns CPU — re-decided on every "
        "reconnect (codec_auto_decision flight breadcrumb either way; "
        "works with --autotune off). A name advertises exactly that "
        "codec (pure-numpy shuffle-rle always; lz4/bitshuffle when "
        "installed). The server picks; old servers degrade the "
        "connection to uncompressed. Default: off (wire bytes "
        "byte-identical to pre-codec builds)",
    )
    if producer:
        parser.add_argument(
            "--wire_dtype", default="", metavar="DTYPE",
            help="LOSSY opt-in: narrow panels to this dtype before "
            "encode (e.g. uint16 halves f32 wire bytes; integer "
            "targets round + clip). Off by default",
        )


def add_tenant_args(parser) -> None:
    """The serving fair-share CLI surface (ISSUE 12)."""
    parser.add_argument(
        "--tenant", default="", metavar="NAME",
        help="fair-share tenant identity for this endpoint's queue "
        "connections (tcp:// and cluster:// transports): the event "
        "loop's stream pump serves tenants by weighted deficit "
        "round-robin, so one greedy tenant cannot starve the rest. "
        "Rides the existing capability exchange — zero new wire "
        "surface; old servers ignore it. Default: the shared default "
        "tenant",
    )
    parser.add_argument(
        "--tenant_weight", type=int, default=1, metavar="1-64",
        help="this tenant's fair-share weight (goodput under "
        "contention converges to the weight shares)",
    )


def apply_tenant_args(config: TransportConfig, args) -> TransportConfig:
    """Fold the tenant flags into a TransportConfig."""
    import dataclasses

    tenant = getattr(args, "tenant", "") or ""
    weight = int(getattr(args, "tenant_weight", 1) or 1)
    if not 1 <= weight <= 64:
        raise ValueError(f"--tenant_weight must be in [1, 64], got {weight}")
    if not tenant:
        if weight != 1:
            # refusing loudly beats silently serving at default weight:
            # the weight only means something under a tenant identity
            raise ValueError("--tenant_weight requires --tenant")
        return config
    return dataclasses.replace(config, tenant=tenant, tenant_weight=weight)


def apply_wire_args(config: TransportConfig, args) -> TransportConfig:
    """Fold the wire-compression flags into a TransportConfig."""
    import dataclasses

    codec = getattr(args, "wire_codec", "") or ""
    dtype = getattr(args, "wire_dtype", "") or ""
    if not codec and not dtype:
        return config
    if codec and codec != "none":
        from psana_ray_tpu.transport.codec import get_codec

        if codec != "auto":
            for name in codec.split(","):
                get_codec(name.strip())  # fail fast on unknown names
    if dtype:
        from psana_ray_tpu.records import validate_wire_dtype

        validate_wire_dtype(dtype)  # fail fast, one shared rule
    return dataclasses.replace(config, wire_codec=codec, wire_dtype=dtype)


def open_queue(
    config: TransportConfig,
    role: str = "consumer",
    address: Optional[str] = None,
    registry: Optional[Registry] = None,
):
    """Open the queue named by ``config`` over the transport its address
    selects. Returns an object with the transport contract (put/get/size/
    put_wait/get_wait/get_batch/close)."""
    if role not in ("producer", "consumer"):
        raise ValueError(f"role must be producer|consumer, got {role!r}")
    address = address or config.address
    # one normalization of the codec knob for every TCP-family branch:
    # ""/"none" -> no negotiation; likewise the tenant hello ("" = the
    # shared default tenant, no capability field on the wire)
    wire_codec = config.wire_codec if config.wire_codec not in ("", "none") else None
    tenant = config.tenant or None

    if address in ("auto", "local"):
        reg = registry or Registry.default()
        from psana_ray_tpu.transport.ring import RingBuffer

        if role == "producer":
            return reg.get_or_create(
                config.namespace,
                config.queue_name,
                lambda: RingBuffer(config.queue_size, name=config.queue_name),
            )
        return reg.resolve(
            config.namespace,
            config.queue_name,
            retries=config.rendezvous_retries,
            interval_s=config.rendezvous_interval_s,
        )

    if address.startswith("shm://"):
        from psana_ray_tpu.transport.shm_ring import ShmRingBuffer

        name = shm_ring_name(config, address)
        if role == "consumer":
            return ShmRingBuffer.attach(
                name,
                retries=config.rendezvous_retries,
                interval_s=config.rendezvous_interval_s,
            )
        # producer: get-or-create, tolerating the create-vs-attach race the
        # reference handles with try-get-first (producer.py:42-48). The
        # native create is O_EXCL, so exactly one creator wins.
        try:
            return ShmRingBuffer.attach(name, retries=0, interval_s=0.01)
        except RendezvousTimeout:
            pass
        try:
            return ShmRingBuffer.create(name, maxsize=config.queue_size)
        except RuntimeError:
            # lost the race — another producer created it just now
            return ShmRingBuffer.attach(
                name,
                retries=config.rendezvous_retries,
                interval_s=config.rendezvous_interval_s,
            )

    if address.startswith("cluster://"):
        from psana_ray_tpu.cluster.client import ClusterClient

        # producers never join consumer groups — a group is a consumer-
        # side partition-ownership construct; a producer in the member
        # list would hold (and starve) partitions it never reads
        group = config.group if role == "consumer" else ""
        return ClusterClient(
            address,
            namespace=config.namespace,
            queue_name=config.queue_name,
            n_partitions=config.cluster_partitions,
            maxsize=config.queue_size,
            group=group or None,
            member_id=config.member_id or None,
            codec=wire_codec,
            tenant=tenant,
            tenant_weight=config.tenant_weight,
        )

    if address.startswith("tcp://"):
        from psana_ray_tpu.transport.tcp import TcpQueueClient

        host, _, port = address[len("tcp://"):].partition(":")
        if not port:
            raise ValueError(f"tcp address needs host:port, got {address!r}")
        # (namespace, queue_name) select a named queue on the server —
        # one queue server per cluster hosts every detector's queue, the
        # role Ray's GCS plays for the reference's named actors
        return TcpQueueClient(
            host,
            int(port),
            namespace=config.namespace,
            queue_name=config.queue_name,
            maxsize=config.queue_size,
            codec=wire_codec,
            tenant=tenant,
            tenant_weight=config.tenant_weight,
        )

    raise ValueError(
        f"unknown address scheme {address!r} (want auto | shm://[name] | "
        f"tcp://host:port | cluster://host:port,host:port,...)"
    )
