"""DataSource protocol + detector geometry registry + shard assignment.

The reference delegates event sharding across MPI ranks to psana's
Smd (smalldata) reader — each rank's ``iter_events`` yields a disjoint shard
(``producer.py:150``, SURVEY.md §2 parallelism table). Here sharding is an
explicit, testable policy: strided assignment by (shard_rank, num_shards),
so rank r sees events r, r+N, r+2N, ... Deterministic and order-stable.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Optional, Protocol, Tuple, runtime_checkable

import numpy as np

from psana_ray_tpu.config import RetrievalMode


@dataclasses.dataclass(frozen=True)
class DetectorSpec:
    """Geometry + signal statistics of a detector family."""

    name: str
    panels: int
    height: int
    width: int
    # assembled-image shape for mode='image' (approximate mosaic)
    adu_offset: float = 100.0  # pedestal level in raw ADUs
    adu_gain: float = 35.0  # ADUs per photon
    bad_pixel_fraction: float = 0.003

    @property
    def frame_shape(self) -> Tuple[int, int, int]:
        return (self.panels, self.height, self.width)

    @property
    def pixels(self) -> int:
        return self.panels * self.height * self.width


# Real LCLS detector geometries (domain facts; epix10k2M geometry cited in
# SURVEY.md §3.3/§6: 16 panels of 352x384; Jungfrau4M: 8 panels of 512x1024).
DETECTORS = {
    "epix10k2M": DetectorSpec("epix10k2M", panels=16, height=352, width=384),
    "jungfrau4M": DetectorSpec("jungfrau4M", panels=8, height=512, width=1024),
    "cspad": DetectorSpec("cspad", panels=32, height=185, width=388),
    "epix100": DetectorSpec("epix100", panels=1, height=704, width=768),
    # tiny lane-aligned geometries for off-TPU smoke runs (bench BENCH_SMOKE=1)
    "smoke_a": DetectorSpec("smoke_a", panels=2, height=16, width=128),
    "smoke_b": DetectorSpec("smoke_b", panels=1, height=32, width=128),
}


@runtime_checkable
class DataSource(Protocol):
    """The surface the producer consumes (reference ``producer.py:81,88,
    150-154``), plus indexed iteration so the producer can stamp global
    event ids without a parallel index stream (the reference counts a local
    ``idx`` per rank, ``producer.py:88,101``)."""

    def iter_events(self, mode: str = RetrievalMode.CALIB) -> Iterator[Tuple[np.ndarray, float]]:
        ...

    def iter_indexed_events(
        self, mode: str = RetrievalMode.CALIB
    ) -> Iterator[Tuple[int, np.ndarray, float]]:
        ...

    def create_bad_pixel_mask(self) -> np.ndarray:
        ...


def shard_indices(num_events: int, shard_rank: int, num_shards: int) -> np.ndarray:
    """Strided shard: rank r gets events r, r+N, ... Disjoint + exhaustive."""
    if not (0 <= shard_rank < num_shards):
        raise ValueError(f"shard_rank {shard_rank} not in [0, {num_shards})")
    return np.arange(shard_rank, num_events, num_shards)


def open_source(
    exp: str,
    run: int,
    detector_name: str,
    shard_rank: int = 0,
    num_shards: int = 1,
    **kwargs,
):
    """Dispatch to a backend by experiment name.

    - ``synthetic`` / ``synthetic-*`` -> :class:`SyntheticSource`
    - ``replay:<path>`` -> :class:`ReplaySource`
    - anything else: try a real psana wrapper (only on LCLS hosts), else
      raise with guidance.
    """
    from psana_ray_tpu.sources.synthetic import SyntheticSource
    from psana_ray_tpu.sources.replay import ReplaySource

    if exp.startswith("synthetic"):
        return SyntheticSource(
            exp, run, detector_name, shard_rank=shard_rank, num_shards=num_shards, **kwargs
        )
    if exp.startswith("replay:"):
        return ReplaySource(
            exp.split(":", 1)[1],
            detector_name=detector_name,
            shard_rank=shard_rank,
            num_shards=num_shards,
            **kwargs,
        )
    try:  # real LCLS host with psana installed
        from psana_ray_tpu.sources.psana_compat import PsanaSource  # noqa: PLC0415
    except ImportError as e:
        raise RuntimeError(
            f"experiment {exp!r} requires psana (LCLS host). For local runs use "
            f"exp='synthetic' or exp='replay:<path.npz>'."
        ) from e
    return PsanaSource(
        exp, run, detector_name, shard_rank=shard_rank, num_shards=num_shards, **kwargs
    )
