"""Experiment data sources.

Protocol parity with the reference's external ``PsanaWrapperSmd`` surface
(``producer.py:81,88,150-154``): construct with (exp, run, detector_name),
``iter_events(mode)`` yielding ``(data, photon_energy)``, and
``create_bad_pixel_mask()``. Backends:

- :class:`SyntheticSource` — deterministic synthetic detector frames
  (epix10k2M, Jungfrau4M, ...) for tests and benchmarks;
- :class:`ReplaySource` — replay frames from ``.npz`` / ``.npy`` files;
- :func:`open_source` — dispatch by experiment name, falling through to a
  real psana wrapper when one is importable on an LCLS host.
"""

from psana_ray_tpu.sources.base import DataSource, DetectorSpec, DETECTORS  # noqa: F401
from psana_ray_tpu.sources.synthetic import SyntheticSource  # noqa: F401
from psana_ray_tpu.sources.replay import ReplaySource  # noqa: F401
from psana_ray_tpu.sources.base import open_source  # noqa: F401
