"""Synthetic detector source: deterministic, shardable, physically plausible.

Stands in for the reference's external ``PsanaWrapperSmd`` (``producer.py:
150-154``) so every protocol in the framework is testable without LCLS data
(the reference has no such fake and therefore no tests — SURVEY.md §4).

Frames model an area detector in ADUs: pedestal + Gaussian noise + Poisson
photon signal with a handful of bright Bragg-like peaks, per-panel common
mode offset (so the common-mode calibration op has something to remove),
and a deterministic bad-pixel set. Determinism: every event is generated
from ``seed ^ hash(exp, run, event_idx)`` so any rank can regenerate any
event — this also powers checkpoint/resume tests.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from psana_ray_tpu.config import RetrievalMode
from psana_ray_tpu.sources.base import DETECTORS, DetectorSpec, shard_indices


def _stable_seed(exp: str, run: int, base_seed: int) -> int:
    h = 2166136261
    for b in f"{exp}/{run}/{base_seed}".encode():
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


class SyntheticSource:
    """Deterministic synthetic frames for one (exp, run, detector) shard."""

    def __init__(
        self,
        exp: str = "synthetic",
        run: int = 1,
        detector_name: str = "epix10k2M",
        num_events: int = 1024,
        seed: int = 0,
        shard_rank: int = 0,
        num_shards: int = 1,
        dtype: str = "float32",
        peak_count: int = 24,
        start_event: int = 0,
        hit_fraction: Optional[float] = None,
    ):
        if detector_name not in DETECTORS:
            raise ValueError(f"unknown detector {detector_name!r}; have {sorted(DETECTORS)}")
        self.exp = exp
        self.run = run
        self.spec: DetectorSpec = DETECTORS[detector_name]
        self.num_events = num_events
        self.shard_rank = shard_rank
        self.num_shards = num_shards
        self.dtype = np.dtype(dtype)
        self.peak_count = peak_count
        self.start_event = start_event  # resume cursor (reference has none, SURVEY.md §5)
        # hit_fraction: when set, each event is independently a "hit"
        # (Bragg peaks planted, probability hit_fraction) or a "miss"
        # (background only, zero truth rows) — the labeled hit-finding
        # corpus the classifier workloads train/score on (label := any
        # truth rows). None (default) keeps every event a hit AND keeps
        # frames bit-identical to pre-knob sources (no extra rng draw).
        if hit_fraction is not None and not (0.0 <= hit_fraction <= 1.0):
            raise ValueError(f"hit_fraction must be in [0, 1], got {hit_fraction}")
        self.hit_fraction = hit_fraction
        self._seed = _stable_seed(exp, run, seed)

        self._pedestal: Optional[np.ndarray] = None
        self._gain_map: Optional[np.ndarray] = None

    # -- protocol surface (parity: producer.py:81,88) ---------------------
    def create_bad_pixel_mask(self) -> np.ndarray:
        """1 = good pixel, 0 = bad. Deterministic per (exp, run, detector)."""
        rng = np.random.default_rng(self._seed ^ 0xBAD)
        mask = rng.random(self.spec.frame_shape) >= self.spec.bad_pixel_fraction
        return mask.astype(np.uint8)

    def pedestal(self) -> np.ndarray:
        """Per-pixel pedestal (dark level), for the calibration ops.
        Constant per source — computed once, cached."""
        if self._pedestal is None:
            rng = np.random.default_rng(self._seed ^ 0x9ED)
            self._pedestal = (
                self.spec.adu_offset + 3.0 * rng.standard_normal(self.spec.frame_shape)
            ).astype(np.float32)
        return self._pedestal

    def gain_map(self) -> np.ndarray:
        """Per-pixel RELATIVE gain (mean 1.0). Raw-mode ADUs carry
        ``spec.adu_gain`` ADUs/photon on top of this map, so the gain
        array that takes a raw frame back to PHOTONS is
        ``spec.adu_gain * gain_map()`` — passing the relative map alone
        to ``ops.calibrate`` yields ADU-scaled output, 35x hotter than
        the calib-mode stream (a real mislabeling trap for photon-scale
        thresholds; see examples/train_peaknet.py)."""
        if self._gain_map is None:
            rng = np.random.default_rng(self._seed ^ 0x6A1)
            self._gain_map = (
                1.0 + 0.02 * rng.standard_normal(self.spec.frame_shape)
            ).astype(np.float32)
        return self._gain_map

    def event(self, idx: int, mode: str = RetrievalMode.CALIB) -> Tuple[np.ndarray, float]:
        """Generate event ``idx`` (globally indexed). Deterministic."""
        data, energy, _ = self.event_with_truth(idx, mode)
        return data, energy

    def event_with_truth(
        self, idx: int, mode: str = RetrievalMode.CALIB
    ) -> Tuple[np.ndarray, float, np.ndarray]:
        """Like :meth:`event`, also returning the PLANTED peak ground
        truth: ``[n_peaks, 4]`` float32 rows ``(panel, cy, cx, amplitude)``
        — the oracle peak-quality metrics score against
        (:func:`psana_ray_tpu.models.peaks.peak_metrics`). Identical rng
        consumption to :meth:`event`, so frames are bit-identical whether
        or not the truth is requested."""
        rng = np.random.default_rng((self._seed << 20) ^ idx)
        spec = self.spec
        p, h, w = spec.frame_shape
        # photon background (scattering) + readout noise, in photons
        photons = rng.poisson(0.08, size=(p, h, w)).astype(np.float32)
        # Bragg-like peaks: a few bright 2-D Gaussians on random panels
        # (a "miss" event, drawn per-event when hit_fraction is set,
        # plants none — its truth is the empty [0, 4] array)
        is_hit = (
            True
            if self.hit_fraction is None
            else bool(rng.random() < self.hit_fraction)
        )
        n_peaks = (
            rng.integers(self.peak_count // 2, self.peak_count + 1)
            if is_hit
            else 0
        )
        yy = np.arange(h, dtype=np.float32)[:, None]
        xx = np.arange(w, dtype=np.float32)[None, :]
        truth = np.zeros((int(n_peaks), 4), dtype=np.float32)
        for j in range(int(n_peaks)):
            pi = int(rng.integers(0, p))
            cy, cx = rng.uniform(4, h - 4), rng.uniform(4, w - 4)
            amp = rng.uniform(50, 800)
            sig = rng.uniform(0.8, 2.2)
            photons[pi] += amp * np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * sig**2))
            truth[j] = (pi, cy, cx, amp)
        photon_energy = float(rng.uniform(8.0, 12.0))  # keV

        if mode == RetrievalMode.CALIB:
            data = photons  # calibrated = photons (what psana calib returns)
        elif mode == RetrievalMode.RAW:
            # raw ADUs: pedestal + gain*photons + common-mode per-panel offset + noise
            cm = rng.uniform(-8.0, 8.0, size=(p, 1, 1)).astype(np.float32)
            noise = 2.5 * rng.standard_normal((p, h, w)).astype(np.float32)
            data = self.pedestal() + spec.adu_gain * photons * self.gain_map() + cm + noise
        elif mode == RetrievalMode.IMAGE:
            # assembled mosaic: panels tiled into one 2-D image (approximate
            # geometry — the reference's 'image' mode returns a 2-D array,
            # promoted to 3-D downstream per producer.py:96-97)
            cols = max(1, int(np.floor(np.sqrt(p))))
            rows = (p + cols - 1) // cols
            img = np.zeros((rows * h, cols * w), dtype=np.float32)
            for pi in range(p):
                r, c = divmod(pi, cols)
                img[r * h : (r + 1) * h, c * w : (c + 1) * w] = photons[pi]
            data = img
        else:
            raise ValueError(f"unknown mode {mode!r}")
        if np.issubdtype(self.dtype, np.integer):
            # detector-native integer ADUs: clip before the cast — common
            # mode / noise can push a float ADU slightly negative, and
            # astype would wrap it to a huge positive count
            info = np.iinfo(self.dtype)
            data = np.clip(data, info.min, info.max)
        return data.astype(self.dtype, copy=False), photon_energy, truth

    def iter_events(self, mode: str = RetrievalMode.CALIB) -> Iterator[Tuple[np.ndarray, float]]:
        """Yield this shard's events (parity: producer.py:88)."""
        for idx in self.shard_event_indices():
            yield self.event(int(idx), mode)

    def iter_indexed_events(
        self, mode: str = RetrievalMode.CALIB
    ) -> Iterator[Tuple[int, np.ndarray, float]]:
        """Yield ``(global_event_idx, data, photon_energy)`` for this shard."""
        for idx in self.shard_event_indices():
            data, energy = self.event(int(idx), mode)
            yield int(idx), data, energy

    def shard_event_indices(self) -> np.ndarray:
        idxs = shard_indices(self.num_events, self.shard_rank, self.num_shards)
        return idxs[idxs >= self.start_event]

    def __len__(self) -> int:
        return len(self.shard_event_indices())
