"""Replay source: stream frames from .npz/.npy files.

Gives the framework a file-backed backend (record once on an LCLS host,
replay anywhere) — a capability the reference lacks entirely (it can only
run live against XTC data, SURVEY.md §4).

File format: ``.npz`` with arrays ``frames [N,P,H,W]`` (or ``[N,H,W]``),
optional ``photon_energy [N]``, optional ``bad_pixel_mask [P,H,W]``;
or a bare ``.npy`` of frames.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Tuple

import numpy as np

from psana_ray_tpu.config import RetrievalMode
from psana_ray_tpu.sources.base import shard_indices


class ReplaySource:
    def __init__(
        self,
        path: str,
        detector_name: str = "epix10k2M",
        shard_rank: int = 0,
        num_shards: int = 1,
        start_event: int = 0,
        **_,
    ):
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        self.path = path
        self.detector_name = detector_name
        self.shard_rank = shard_rank
        self.num_shards = num_shards
        self.start_event = start_event
        if path.endswith(".npz"):
            # npz members decompress lazily on first access; frames stay
            # backed by the zip until indexed (still one big array on use —
            # for runs larger than RAM, record as .npy and get true mmap).
            z = np.load(path)
            self._frames = z["frames"]
            self._energy = z["photon_energy"] if "photon_energy" in z else None
            self._mask = z["bad_pixel_mask"] if "bad_pixel_mask" in z else None
        else:
            # mmap: a shard touches only its strided events, never the full
            # file (10k epix10k2M frames ≈ 86 GB f32 must not load eagerly)
            self._frames = np.load(path, mmap_mode="r")
            self._energy = None
            self._mask = None
        if self._frames.ndim == 3:  # [N,H,W] -> [N,1,H,W]
            self._frames = self._frames[:, None]

    @property
    def num_events(self) -> int:
        return len(self._frames)

    def create_bad_pixel_mask(self) -> np.ndarray:
        if self._mask is not None:
            return self._mask.astype(np.uint8)
        return np.ones(self._frames.shape[1:], dtype=np.uint8)

    def shard_event_indices(self) -> np.ndarray:
        idxs = shard_indices(self.num_events, self.shard_rank, self.num_shards)
        return idxs[idxs >= self.start_event]

    def iter_events(self, mode: str = RetrievalMode.CALIB) -> Iterator[Tuple[np.ndarray, float]]:
        for _, data, energy in self.iter_indexed_events(mode):
            yield data, energy

    def iter_indexed_events(
        self, mode: str = RetrievalMode.CALIB
    ) -> Iterator[Tuple[int, np.ndarray, float]]:
        """Yield ``(global_event_idx, data, photon_energy)`` for this shard."""
        for idx in self.shard_event_indices():
            e = float(self._energy[idx]) if self._energy is not None else 9.5
            yield int(idx), np.asarray(self._frames[int(idx)]), e

    def __len__(self) -> int:
        return len(self.shard_event_indices())
