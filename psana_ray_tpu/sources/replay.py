"""Replay source: stream frames from .npz/.npy files.

Gives the framework a file-backed backend (record once on an LCLS host,
replay anywhere) — a capability the reference lacks entirely (it can only
run live against XTC data, SURVEY.md §4).

File format: ``.npz`` with arrays ``frames [N,P,H,W]`` (or ``[N,H,W]``),
optional ``photon_energy [N]``, optional ``bad_pixel_mask [P,H,W]``;
or a bare ``.npy`` of frames.
"""

from __future__ import annotations

import logging
import os
import zipfile
from typing import Iterator, Optional, Tuple

import numpy as np

from psana_ray_tpu.config import RetrievalMode
from psana_ray_tpu.sources.base import shard_indices

logger = logging.getLogger(__name__)


def _mmap_npz_member(path: str, name: str) -> Optional[np.ndarray]:
    """True mmap of an UNCOMPRESSED ``.npz`` member (``np.savez`` stores
    members ZIP_STORED, so the inner ``.npy`` bytes sit contiguously in
    the file): parse the zip local header + npy header to find the data
    offset and ``np.memmap`` it. Returns None when the member is deflated
    (``savez_compressed``) or anything about the layout surprises us —
    callers fall back to lazy decompression."""
    try:
        with zipfile.ZipFile(path) as zf:
            info = zf.getinfo(name)
            if info.compress_type != zipfile.ZIP_STORED:
                return None
            with zf.open(info) as member:
                version = np.lib.format.read_magic(member)
                if version == (1, 0):
                    shape, fortran, dtype = np.lib.format.read_array_header_1_0(member)
                else:
                    shape, fortran, dtype = np.lib.format.read_array_header_2_0(member)
                npy_header = member.tell()
            if fortran or dtype.hasobject:
                return None
        # data offset = zip local header (30 bytes + name + extra; the
        # LOCAL extra field can differ from the central directory's, so
        # read it from the file) + npy header
        with open(path, "rb") as f:
            f.seek(info.header_offset + 26)
            name_len = int.from_bytes(f.read(2), "little")
            extra_len = int.from_bytes(f.read(2), "little")
        offset = info.header_offset + 30 + name_len + extra_len + npy_header
        return np.memmap(path, dtype=dtype, mode="r", shape=shape, offset=offset)
    except Exception as e:  # malformed/exotic archives: degrade, don't fail
        logger.debug("npz mmap of %s[%s] unavailable: %r", path, name, e)
        return None


def _warn_if_exceeds_ram(path: str, name: str) -> None:
    """Deflated members decompress fully on first access — warn when that
    would blow physical RAM (the 86 GB replay case this source's own
    docstring cites) and point at the .npy / uncompressed-savez fix."""
    try:
        with zipfile.ZipFile(path) as zf:
            nbytes = zf.getinfo(name).file_size
        avail = os.sysconf("SC_AVPHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, KeyError):
        return
    if nbytes > 0.8 * avail:
        logger.warning(
            "replay member %s[%s] is %.1f GB but only %.1f GB RAM is free; "
            "it will decompress fully on first access. Record with np.savez "
            "(uncompressed, mmap-able) or a bare .npy for >RAM runs.",
            path, name, nbytes / 1e9, avail / 1e9,
        )


class ReplaySource:
    def __init__(
        self,
        path: str,
        detector_name: str = "epix10k2M",
        shard_rank: int = 0,
        num_shards: int = 1,
        start_event: int = 0,
        **_,
    ):
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        self.path = path
        self.detector_name = detector_name
        self.shard_rank = shard_rank
        self.num_shards = num_shards
        self.start_event = start_event
        if path.endswith(".npz"):
            z = np.load(path)
            # uncompressed members (np.savez default) get a TRUE mmap: a
            # shard touches only its strided events, never the full array
            frames = _mmap_npz_member(path, "frames.npy")
            if frames is None:
                # deflated (savez_compressed): decompresses fully on first
                # access — warn when that exceeds free RAM
                _warn_if_exceeds_ram(path, "frames.npy")
                frames = z["frames"]
            self._frames = frames
            self._energy = z["photon_energy"] if "photon_energy" in z else None
            self._mask = z["bad_pixel_mask"] if "bad_pixel_mask" in z else None
        else:
            # mmap: a shard touches only its strided events, never the full
            # file (10k epix10k2M frames ≈ 86 GB f32 must not load eagerly)
            self._frames = np.load(path, mmap_mode="r")
            self._energy = None
            self._mask = None
        if self._frames.ndim == 3:  # [N,H,W] -> [N,1,H,W]
            self._frames = self._frames[:, None]

    @property
    def num_events(self) -> int:
        return len(self._frames)

    def create_bad_pixel_mask(self) -> np.ndarray:
        if self._mask is not None:
            return self._mask.astype(np.uint8)
        return np.ones(self._frames.shape[1:], dtype=np.uint8)

    def shard_event_indices(self) -> np.ndarray:
        idxs = shard_indices(self.num_events, self.shard_rank, self.num_shards)
        return idxs[idxs >= self.start_event]

    def iter_events(self, mode: str = RetrievalMode.CALIB) -> Iterator[Tuple[np.ndarray, float]]:
        for _, data, energy in self.iter_indexed_events(mode):
            yield data, energy

    def iter_indexed_events(
        self, mode: str = RetrievalMode.CALIB
    ) -> Iterator[Tuple[int, np.ndarray, float]]:
        """Yield ``(global_event_idx, data, photon_energy)`` for this shard."""
        for idx in self.shard_event_indices():
            e = float(self._energy[idx]) if self._energy is not None else 9.5
            yield int(idx), np.asarray(self._frames[int(idx)]), e

    def __len__(self) -> int:
        return len(self.shard_event_indices())
