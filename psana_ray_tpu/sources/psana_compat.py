"""Adapter for a real psana installation on LCLS hosts.

Wraps the same surface the reference consumes from its external
``psana-wrapper`` dependency (``producer.py:11,150-154``): construct with
(exp, run, detector_name), ``iter_events(mode)``, ``create_bad_pixel_mask``.
Import fails cleanly off-site; :func:`psana_ray_tpu.sources.open_source`
falls back to synthetic/replay backends.

Off-LCLS the adapter's contracts (damaged-event index alignment, eV→keV,
shard striding × start_event, mask dtype) are exercised against a mock
psana module in ``tests/test_psana_compat.py`` — the testable stand-in for
the reference's only oracle, live beamline operation (``README.md:20``).
"""

from __future__ import annotations

from typing import Iterator, Tuple

import numpy as np

try:
    import psana  # type: ignore  # only exists on LCLS hosts
except ImportError as _e:  # pragma: no cover - no psana in CI
    raise ImportError("psana is not installed (expected off LCLS hosts)") from _e

from psana_ray_tpu.config import RetrievalMode


class PsanaSource:
    """Shard-aware psana reader (smalldata parallel mode)."""

    def __init__(self, exp, run, detector_name, shard_rank=0, num_shards=1, start_event=0, **_):
        self.exp, self.run, self.detector_name = exp, run, detector_name
        self.shard_rank, self.num_shards = shard_rank, num_shards
        self.start_event = start_event
        self._ds = psana.DataSource(exp=exp, run=run)
        self._run = next(self._ds.runs())
        self._det = self._run.Detector(detector_name)
        self._ebeam = self._run.Detector("ebeam")

    def create_bad_pixel_mask(self) -> np.ndarray:
        mask = self._det.raw.mask(calib_const=True, status=True)
        return np.asarray(mask, dtype=np.uint8)

    def iter_events(self, mode: str = RetrievalMode.CALIB) -> Iterator[Tuple[np.ndarray, float]]:
        for _, data, energy in self.iter_indexed_events(mode):
            yield data, energy

    def iter_indexed_events(
        self, mode: str = RetrievalMode.CALIB
    ) -> Iterator[Tuple[int, np.ndarray, float]]:
        """Yield ``(global_event_idx, data, photon_energy)`` for this shard.
        Indexing stays aligned when psana yields None for a damaged event —
        the event number is consumed, the record is skipped."""
        for i, evt in enumerate(self._run.events()):
            if i % self.num_shards != self.shard_rank or i < self.start_event:
                continue
            if mode == RetrievalMode.CALIB:
                data = self._det.raw.calib(evt)
            elif mode == RetrievalMode.IMAGE:
                data = self._det.raw.image(evt)
            else:
                data = self._det.raw.raw(evt)
            if data is None:
                continue
            energy = float(self._ebeam.raw.ebeamPhotonEnergy(evt) or 0.0) / 1000.0
            yield i, np.asarray(data, dtype=np.float32), energy
