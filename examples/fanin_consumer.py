"""Multi-detector fan-in consumer (BASELINE config 5).

Two producers stream different detector geometries (epix10k2M +
jungfrau4M) into their own queues; one consumer loop drains both through
a FanInPipeline with a per-detector compiled calibration step. Run it
self-contained (both producers in-process):

    python examples/fanin_consumer.py

or point the DetectorStreams at shm:// / tcp:// queues fed by real
producer processes (see the README runbook).
"""

from __future__ import annotations

import threading

import jax
import numpy as np

from psana_ray_tpu.config import (
    MaskConfig,
    PipelineConfig,
    RetrievalMode,
    SourceConfig,
    TransportConfig,
)
from psana_ray_tpu.infeed import DetectorStream, FanInPipeline
from psana_ray_tpu.ops import fused_calibrate
from psana_ray_tpu.producer import ProducerRuntime
from psana_ray_tpu.sources import SyntheticSource


def make_runtime(detector: str, num_events: int) -> ProducerRuntime:
    return ProducerRuntime(
        PipelineConfig(
            source=SourceConfig(
                exp="synthetic",
                run=1,
                detector_name=detector,
                num_events=num_events,
                mode=RetrievalMode.RAW,  # stream raw ADUs; calibrate on device
            ),
            mask=MaskConfig(uses_bad_pixel_mask=True),
            transport=TransportConfig(
                address="auto", queue_name=f"q_{detector}", queue_size=32
            ),
        )
    )


def make_step(detector: str):
    """One compiled calibration step per detector geometry."""
    src = SyntheticSource(num_events=1, detector_name=detector, seed=0)
    ped = np.asarray(src.pedestal())
    # absolute gain (ADUs/photon): photons out of the calibrate step —
    # the relative map alone would leave output 35x hot (see gain_map())
    gain = np.asarray(src.spec.adu_gain * src.gain_map())
    mask = np.asarray(src.create_bad_pixel_mask())
    step = jax.jit(lambda f: fused_calibrate(f, ped, gain, mask, threshold=10.0))
    return lambda batch: step(batch.frames)


def main():
    runtimes = {
        "epix10k2M": make_runtime("epix10k2M", 24),
        "jungfrau4M": make_runtime("jungfrau4M", 12),
    }
    queues = {name: rt.bootstrap() for name, rt in runtimes.items()}
    threads = [threading.Thread(target=rt.run, daemon=True) for rt in runtimes.values()]
    for t in threads:
        t.start()

    fan = FanInPipeline(
        [
            DetectorStream("epix10k2M", queues["epix10k2M"], batch_size=8),
            DetectorStream("jungfrau4M", queues["jungfrau4M"], batch_size=4),
        ]
    )
    counts = fan.run(
        {name: make_step(name) for name in runtimes},
        on_result=lambda name, out, batch: print(
            f"{name}: batch of {batch.num_valid} calibrated, "
            f"mean={float(out.mean()):.3f}"
        ),
        block_until_ready=True,
    )
    for t in threads:
        t.join()
    print("done:", counts)
    for name, m in fan.metrics.items():
        print(f"  {name}: {m.status_line()}")


if __name__ == "__main__":
    main()
