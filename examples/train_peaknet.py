"""Streaming PeakNet training, end to end: source -> transport -> batcher
-> sharded train step -> checkpoint.

The reference streams frames to opaque per-GPU torch loops
(``project.toml:4`` "Stream psana data ... for distributed, real-time
analysis and inference"); this is the training side of that capability,
TPU-first: a ``ProducerRuntime`` feeds a bounded queue, the infeed
batcher pads tails to fixed shapes, and a donated/jit'd train step runs
``PeakNetUNetTPU`` over a ('data',) mesh — on one chip, a CPU mesh, or a
pod slice with the same code.

Labels here are self-derived on device (peaks := calibrated pixels above
an SNR threshold) so the example runs anywhere without a labeled corpus;
swap ``labels_of`` for real CXI/psocake masks in production. Loss is
focal BCE (Bragg peaks are ~1e-4 of pixels; plain BCE collapses to the
background class).

Run (small, CPU-friendly):
    python examples/train_peaknet.py --steps 4

Convergence scale: on the synthetic oracle this recipe saturates peak
recall/precision around ~300 steps at batch 2 (bench step sweep,
PERF_NOTES.md r5) — the tiny defaults here demonstrate the plumbing,
not a finished detector.
"""

import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8, help="train steps to run")
    ap.add_argument("--batch", type=int, default=2, help="frames per batch")
    ap.add_argument("--detector", default="epix100")
    ap.add_argument("--num_events", type=int, default=32)
    ap.add_argument("--checkpoint_dir", default=None, help="orbax save target")
    ap.add_argument(
        "--norm", default="group", choices=["group", "batch"],
        help="normalization for training: 'group' (row-independent, the "
        "robust default) or 'batch' (running statistics — REQUIRED for "
        "--export-serving, which folds them into the fused-inference "
        "FrozenAffine form)",
    )
    ap.add_argument(
        "--export-serving", default=None, metavar="DIR", dest="export_serving",
        help="after training, fold BatchNorm stats into FrozenAffine "
        "constants (models/fold.py) and save serving params here — the "
        "parameter form peaknet_tpu_fused_infer consumes. Implies --norm "
        "batch.",
    )
    ap.add_argument(
        "--features", default="16,32",
        help="comma-separated encoder widths (default keeps the example "
        "CPU-fast; 64,128,256,512 is the real PeakNet-TPU capacity the "
        "bench and psana-ray-tpu-sfx serve). The exported checkpoint "
        "carries the widths — sfx infers them back, no flag to keep in "
        "sync.",
    )
    ap.add_argument(
        "--s2d", type=int, default=2, choices=[2, 4],
        help="space-to-depth factor: 2 = quality mode, 4 = throughput "
        "mode (the operating point is baked into the trained tree; "
        "psana-ray-tpu-sfx reads it from the checkpoint)",
    )
    ap.add_argument(
        "--focal_alpha", type=float, default=0.95,
        help="focal-loss positive-class weight. At this domain's ~1e-4 "
        "peak-pixel fraction the textbook 0.25 collapses training to "
        "all-background within a few steps (measured on epix10k2M: "
        "recall 0.04 after 320 steps at 0.25 vs 1.00 at 0.95 — the "
        "bench quality probe's calibrated recipe)",
    )
    ap.add_argument(
        "--lr", type=float, default=3e-3,
        help="learning rate (default: the bench probe's measured recipe; "
        "precision is the slow-saturating metric — at 1e-3 a 320-step "
        "epix10k2M run stops around precision 0.4 where 3e-3 saturates)",
    )
    args = ap.parse_args()
    try:
        args.features = tuple(int(f) for f in args.features.split(","))
    except ValueError:
        ap.error(f"--features {args.features!r} is not a comma-separated "
                 f"integer list")
    if args.export_serving:
        args.norm = "batch"

    from psana_ray_tpu.utils.hostmem import enable_large_alloc_reuse

    enable_large_alloc_reuse()

    import os

    import jax

    # some TPU plugins ignore the JAX_PLATFORMS env var; honor it via the
    # config knob so `JAX_PLATFORMS=cpu python examples/train_peaknet.py`
    # really runs on CPU (same mirroring as bench.py)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    import optax

    from psana_ray_tpu.config import PipelineConfig, SourceConfig
    from psana_ray_tpu.infeed import InfeedPipeline, StopStream
    from psana_ray_tpu.models import PeakNetUNetTPU, panels_to_nhwc
    from psana_ray_tpu.models.losses import masked_sigmoid_focal
    from psana_ray_tpu.ops import calibrate
    from psana_ray_tpu.parallel import create_mesh
    from psana_ray_tpu.parallel.steps import create_train_state, make_train_step
    from psana_ray_tpu.producer import ProducerRuntime
    from psana_ray_tpu.sources import SyntheticSource
    from psana_ray_tpu.transport.addressing import open_queue

    # DP over every device; 'model' axis present (width 1) because the
    # models' logical-axis annotations name it — widen it on pod slices
    # for tensor parallelism
    mesh = create_mesh(("data", "model"), (jax.device_count(), 1))
    src = SyntheticSource(num_events=1, detector_name=args.detector, seed=0)
    pedestal = jnp.asarray(src.pedestal())
    # absolute gain (ADUs/photon): calibrate() divides by this, so the
    # net trains on PHOTON-scale inputs — the same scale the calib-mode
    # stream (and therefore psana-ray-tpu-sfx without --calib_npz)
    # serves. The relative map alone would leave outputs 35x hot and
    # the >50 label policy marking Poisson background as peaks.
    gain = jnp.asarray(src.spec.adu_gain * src.gain_map())
    mask = jnp.asarray(src.create_bad_pixel_mask())
    n_panels, h, w = src.spec.frame_shape

    # default widths keep the example training in seconds on CPU;
    # --features 64,128,256,512 is the real PeakNet-TPU capacity
    model = PeakNetUNetTPU(features=args.features, norm=args.norm, s2d=args.s2d)

    def labels_of(frames_nhwc):
        # stand-in ground truth: calibrated intensity over threshold.
        # Real runs: replace with CXI/psocake peak masks joined on
        # (shard_rank, event_idx).
        return (frames_nhwc > 50.0).astype(jnp.float32)

    def loss_fn(logits, batch_aux):
        targets, valid = batch_aux
        return masked_sigmoid_focal(logits, targets, valid, alpha=args.focal_alpha)

    opt = optax.adamw(args.lr)
    sample = jnp.zeros((args.batch * n_panels, h, w, 1))
    state = create_train_state(model, opt, jax.random.key(0), sample, mesh)
    step = make_train_step(model, opt, loss_fn)

    @jax.jit
    def prepare(frames, valid):
        c = calibrate(frames, pedestal, gain, mask, cm_algorithm="mean")
        x = panels_to_nhwc(c, mode="batch")  # [B*P, H, W, 1]
        targets = labels_of(x)
        row_valid = jnp.repeat(valid.astype(jnp.uint8), n_panels)
        return x, targets, row_valid

    # stream: producer -> bounded queue (in-process by default; set
    # cfg.transport.address to shm:///tcp://host:port for real clusters)
    # -> padded fixed-shape batches. The stream carries RAW ADUs because
    # prepare() calibrates on-device: the default calib-mode stream would
    # be calibrated TWICE here (pedestal subtracted from already-clean
    # photons), training the net on a distribution serving never sees —
    # measured on epix10k2M: the doubly-calibrated recipe tops out at
    # recall 0.73 / precision 0.45 where raw-in training saturates.
    cfg = PipelineConfig(
        source=SourceConfig(
            exp="synthetic", num_events=args.num_events,
            detector_name=args.detector, mode="raw",
        )
    )
    ProducerRuntime(cfg).run(block=False)
    queue = open_queue(cfg.transport)

    pipe = InfeedPipeline(
        queue, batch_size=args.batch, place_on_device=False,
        poll_interval_s=0.001,
    )
    losses = []
    t0 = time.perf_counter()

    def train_on(batch):
        if args.norm == "batch" and not all(batch.valid):
            # batch statistics see every row — a padded tail would poison
            # the running stats the serving export folds, so skip partial
            # batches (GroupNorm training has no such constraint)
            return None
        x, targets, row_valid = prepare(
            jnp.asarray(batch.frames), jnp.asarray(batch.valid)
        )
        train_on.state, loss = step(train_on.state, x, (targets, row_valid))
        losses.append(float(loss))
        print(f"step {len(losses)}: loss {losses[-1]:.5f}")
        if len(losses) >= args.steps:
            raise StopStream  # quota reached: stop draining the stream
        return None

    train_on.state = state
    n = pipe.run(train_on)
    state = train_on.state
    dt = time.perf_counter() - t0
    trend = f"; loss {losses[0]:.5f} -> {losses[-1]:.5f}" if losses else ""
    print(
        f"trained {len(losses)} steps on {n} frames in {dt:.1f}s "
        f"(mesh={dict(mesh.shape)}){trend}"
    )

    if args.checkpoint_dir:
        from psana_ray_tpu.checkpoint import save_train_state

        save_train_state(args.checkpoint_dir, state)
        print(f"checkpointed to {args.checkpoint_dir}")

    if args.export_serving:
        from psana_ray_tpu.models import export_serving_params

        export_serving_params(state.variables, args.export_serving)
        print(
            f"serving params (norm='frozen' form) exported to "
            f"{args.export_serving} — consumable by "
            f"PeakNetUNetTPU(norm='frozen').apply and peaknet_tpu_fused_infer"
        )


if __name__ == "__main__":
    main()
