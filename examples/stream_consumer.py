"""Canonical consumer: the reference example (``examples/psana_consumer.py``)
re-done with typed EOS, blocking reads, and a jitted TPU step.

Run (after a producer is up in the same process/deployment):
    python examples/stream_consumer.py <consumer_id>

Differences from the reference example, on purpose:
- ``for rec in reader`` terminates on the typed EOS — the reference's loop
  could not distinguish EOS from starvation and spun forever
  (``psana_consumer.py:38-40``);
- blocking reads instead of 1 s poll-sleep;
- dead transport surfaces as DataReaderError -> clean exit (parity with
  ``psana_consumer.py:41-44``).
"""

import sys
import signal

from psana_ray_tpu.consumer import DataReader, DataReaderError


def consume(consumer_id: int):
    stop = False

    def _sigint(sig, frame):  # parity: psana_consumer.py:24-26
        nonlocal stop
        stop = True

    signal.signal(signal.SIGINT, _sigint)
    try:
        with DataReader() as reader:
            for rec in reader:
                if stop:
                    break
                print(
                    f"consumer {consumer_id}: rank={rec.shard_rank} idx={rec.event_idx} "
                    f"shape={rec.panels.shape} energy={rec.photon_energy:.2f}"
                )
        print(f"consumer {consumer_id}: end of stream")
    except DataReaderError as e:
        print(f"consumer {consumer_id}: queue is dead ({e}); exiting")


if __name__ == "__main__":
    consume(int(sys.argv[1]) if len(sys.argv) > 1 else 0)
