"""Multi-detector fan-in tests (BASELINE config 5).

Two detectors with different geometries stream through one FanInPipeline;
each detector's step must compile exactly once (fixed per-detector shapes
— the whole point of per-detector batchers) and every frame from both
streams must be processed before the loop ends.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from psana_ray_tpu.infeed import DetectorStream, FanInPipeline
from psana_ray_tpu.records import EndOfStream, FrameRecord
from psana_ray_tpu.transport import RingBuffer, TransportClosed

EPIX_SHAPE = (2, 16, 24)  # scaled-down epix10k2M (16, 352, 384)
JF_SHAPE = (1, 32, 8)  # scaled-down jungfrau4M (8, 512, 1024)


def _produce(queue, shape, n, delay_s=0.0, base=0.0):
    # a closed transport is a clean producer exit, same as the real
    # ProducerRuntime (producer.py) — keeps early-close tests warning-free
    try:
        for i in range(n):
            frame = np.full(shape, base + i, dtype=np.float32)
            rec = FrameRecord(0, i, frame, 9.5)
            while not queue.put(rec):
                time.sleep(0.0005)
            if delay_s:
                time.sleep(delay_s)
        assert queue.put_wait(EndOfStream(total_events=n), timeout=30.0)
    except TransportClosed:
        return


def _start_producers(specs):
    """specs: [(queue, shape, n, delay_s), ...] -> joined-later threads."""
    threads = [
        threading.Thread(target=_produce, args=spec, daemon=True) for spec in specs
    ]
    for t in threads:
        t.start()
    return threads


class TestFanInPipeline:
    def test_two_detectors_all_frames_one_compile_each(self):
        n_epix, n_jf = 10, 25
        q_epix, q_jf = RingBuffer(maxsize=16), RingBuffer(maxsize=16)
        producers = _start_producers(
            [(q_epix, EPIX_SHAPE, n_epix, 0.0), (q_jf, JF_SHAPE, n_jf, 0.0)]
        )
        fan = FanInPipeline(
            [
                DetectorStream("epix10k2M", q_epix, batch_size=4, poll_interval_s=0.001),
                DetectorStream("jungfrau4M", q_jf, batch_size=8, poll_interval_s=0.001),
            ]
        )
        traces = {"epix10k2M": 0, "jungfrau4M": 0}
        sums = {"epix10k2M": 0.0, "jungfrau4M": 0.0}

        def make_step(name):
            @jax.jit
            def step(frames, valid):
                traces[name] += 1  # python body runs once per (re)trace
                keep = valid.astype(frames.dtype).reshape(-1, 1, 1, 1)
                return jnp.sum(frames * keep)

            return lambda batch: step(batch.frames, batch.valid)

        steps = {name: make_step(name) for name in traces}

        def on_result(name, out, batch):
            sums[name] += float(out)

        counts = fan.run(steps, on_result=on_result, block_until_ready=True)
        for t in producers:
            t.join(timeout=10.0)

        assert counts == {"epix10k2M": n_epix, "jungfrau4M": n_jf}
        # no recompile churn: one trace per detector despite padded tails
        assert traces == {"epix10k2M": 1, "jungfrau4M": 1}
        # every frame's payload arrived intact (frame i is all-i)
        assert sums["epix10k2M"] == pytest.approx(
            sum(range(n_epix)) * np.prod(EPIX_SHAPE)
        )
        assert sums["jungfrau4M"] == pytest.approx(
            sum(range(n_jf)) * np.prod(JF_SHAPE)
        )
        assert fan.metrics["jungfrau4M"].frames.count == n_jf

    def test_fast_stream_not_blocked_by_slow(self):
        """Ready-ordered merge: the fast detector's whole stream completes
        while the slow producer is still trickling (no head-of-line
        blocking behind the slow stream's pending EOS)."""
        q_fast, q_slow = RingBuffer(maxsize=64), RingBuffer(maxsize=64)
        n_fast, n_slow = 32, 4
        producers = _start_producers(
            [(q_fast, JF_SHAPE, n_fast, 0.0), (q_slow, EPIX_SHAPE, n_slow, 0.05)]
        )
        fan = FanInPipeline(
            [
                DetectorStream("fast", q_fast, batch_size=8, poll_interval_s=0.001),
                DetectorStream("slow", q_slow, batch_size=4, poll_interval_s=0.001),
            ]
        )
        order = []
        for name, batch in fan:
            order.append(name)
        fan.close()
        for t in producers:
            t.join(timeout=10.0)
        # all fast batches arrive before the slow stream's final batch
        last_fast = len(order) - 1 - order[::-1].index("fast")
        last_slow = len(order) - 1 - order[::-1].index("slow")
        assert last_fast < last_slow
        assert order.count("fast") == n_fast // 8

    def test_missing_step_raises(self):
        q = RingBuffer(maxsize=4)
        fan = FanInPipeline([DetectorStream("epix10k2M", q, batch_size=2)])
        with pytest.raises(KeyError, match="epix10k2M"):
            fan.run({"jungfrau4M": lambda b: None})
        fan.close()
        q.close()

    def test_duplicate_names_rejected(self):
        q1, q2 = RingBuffer(maxsize=4), RingBuffer(maxsize=4)
        with pytest.raises(ValueError, match="duplicate"):
            FanInPipeline(
                [DetectorStream("d", q1, batch_size=2), DetectorStream("d", q2, batch_size=2)]
            )
        q1.close(), q2.close()

    def test_stream_error_propagates(self):
        """A mis-shaped frame inside one stream surfaces to the consumer
        (after the other stream drains) instead of hanging the loop."""
        q_ok, q_bad = RingBuffer(maxsize=16), RingBuffer(maxsize=16)
        producers = _start_producers([(q_ok, JF_SHAPE, 8, 0.0)])
        q_bad.put(FrameRecord(0, 0, np.zeros(EPIX_SHAPE, np.float32), 9.5))
        q_bad.put(FrameRecord(0, 1, np.zeros(JF_SHAPE, np.float32), 9.5))  # mismatch
        q_bad.put(EndOfStream())
        fan = FanInPipeline(
            [
                DetectorStream("ok", q_ok, batch_size=4, poll_interval_s=0.001),
                DetectorStream("bad", q_bad, batch_size=4, poll_interval_s=0.001),
            ]
        )
        with pytest.raises(ValueError, match="locked shape"):
            fan.run({"ok": lambda b: None, "bad": lambda b: None})
        for t in producers:
            t.join(timeout=10.0)

    def test_dead_stream_surfaces_while_other_still_live(self):
        """A failed leg raises promptly even though the healthy detector
        keeps streaming with no EOS in sight (continuous multi-run mode —
        a dead detector must not stay silent until global EOS)."""
        q_live, q_bad = RingBuffer(maxsize=64), RingBuffer(maxsize=64)
        stop = threading.Event()

        def trickle():
            i = 0
            while not stop.is_set():
                q_live.put(FrameRecord(0, i, np.zeros(JF_SHAPE, np.float32), 9.5))
                i += 1
                time.sleep(0.002)

        live_thread = threading.Thread(target=trickle, daemon=True)
        live_thread.start()
        q_bad.put(FrameRecord(0, 0, np.zeros(EPIX_SHAPE, np.float32), 9.5))
        q_bad.put(FrameRecord(0, 1, np.zeros(JF_SHAPE, np.float32), 9.5))  # mismatch
        fan = FanInPipeline(
            [
                DetectorStream("live", q_live, batch_size=4, poll_interval_s=0.001),
                DetectorStream("bad", q_bad, batch_size=4, poll_interval_s=0.001),
            ]
        )
        t0 = time.monotonic()
        with pytest.raises(ValueError, match="locked shape"):
            fan.run({"live": lambda b: None, "bad": lambda b: None})
        assert time.monotonic() - t0 < 10.0
        stop.set()
        live_thread.join(timeout=5.0)
        q_live.close()

    def test_cross_thread_close_unblocks_starved_consumer(self):
        """close() from a watchdog thread must wake a consumer blocked on
        the merge queue AND stop a leg parked in a starved transport poll
        (neither EOS nor frames ever arrive)."""
        q = RingBuffer(maxsize=8)
        fan = FanInPipeline(
            [DetectorStream("d", q, batch_size=2, poll_interval_s=0.001)]
        )
        seen = []
        consumer = threading.Thread(
            target=lambda: seen.extend(iter(fan)), daemon=True
        )
        consumer.start()
        time.sleep(0.1)
        t0 = time.monotonic()
        fan.close()
        consumer.join(timeout=5.0)
        assert not consumer.is_alive()
        assert time.monotonic() - t0 < 2.0
        for th in fan._threads:
            assert not th.is_alive()
        assert seen == []
        q.close()

    def test_early_close_joins_threads(self):
        q = RingBuffer(maxsize=8)
        producers = _start_producers([(q, JF_SHAPE, 64, 0.0)])
        fan = FanInPipeline([DetectorStream("d", q, batch_size=4, poll_interval_s=0.001)])
        it = iter(fan)
        next(it)
        fan.close()
        for t in fan._threads:
            assert not t.is_alive()
        q.close()
        for t in producers:
            t.join(timeout=10.0)
