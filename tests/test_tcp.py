"""TCP queue transport: contract parity over a real socket, frame payloads,
concurrent producers/consumers, remote close propagation."""

import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.transport import EMPTY, TransportClosed
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer


@pytest.fixture
def server():
    s = TcpQueueServer(host="127.0.0.1", maxsize=8).serve_background()
    yield s
    s.shutdown()


@pytest.fixture
def client(server):
    c = TcpQueueClient("127.0.0.1", server.port)
    yield c
    c.disconnect()


class TestContract:
    def test_fifo_roundtrip(self, client):
        assert client.get() is EMPTY
        assert client.put({"x": 1})
        assert client.put([1, 2])
        assert client.size() == 2
        assert client.get() == {"x": 1}
        assert client.get() == [1, 2]

    def test_full_backpressure(self, client):
        n = 0
        while client.put(n):
            n += 1
        assert n == 8
        assert client.get() == 0

    def test_frame_payload(self, client):
        panels = np.arange(2 * 4 * 8, dtype=np.float32).reshape(2, 4, 8)
        client.put(FrameRecord(1, 7, panels, 8.8))
        out = client.get()
        assert isinstance(out, FrameRecord)
        np.testing.assert_array_equal(out.panels, panels)
        client.put(EndOfStream(total_events=1))
        assert is_eos(client.get())

    def test_remote_close_propagates(self, server, client):
        other = TcpQueueClient("127.0.0.1", server.port)
        client.close_remote()
        with pytest.raises(TransportClosed):
            other.get()
        with pytest.raises(TransportClosed):
            other.put(1)
        other.disconnect()

    def test_get_wait_timeout(self, client):
        t0 = time.monotonic()
        assert client.get_wait(timeout=0.05) is EMPTY
        assert time.monotonic() - t0 >= 0.04


class TestConcurrent:
    def test_multiple_clients_stream(self, server):
        n = 40

        def producer(rank):
            c = TcpQueueClient("127.0.0.1", server.port)
            for i in range(rank, n, 2):
                rec = FrameRecord(rank, i, np.full((1, 4, 4), float(i), np.float32), 1.0)
                c.put_wait(rec, timeout=10)
            c.disconnect()

        threads = [threading.Thread(target=producer, args=(r,)) for r in range(2)]
        for t in threads:
            t.start()
        consumer = TcpQueueClient("127.0.0.1", server.port)
        got = []
        while len(got) < n:
            item = consumer.get_wait(timeout=5.0)
            assert item is not EMPTY, "starved"
            got.append(item)
        for t in threads:
            t.join()
        consumer.disconnect()
        assert sorted(r.event_idx for r in got) == list(range(n))


class TestBatchedOpcodes:
    """GET_BATCH/PUT_BATCH drain/send N records per round trip, clearing
    the per-event-RPC bottleneck on the cross-host path (VERDICT r1 weak
    #5; reference data_reader.py:35 pays one RPC per frame)."""

    def test_put_batch_then_get_batch(self, server, client):
        recs = [
            FrameRecord(0, i, np.full((1, 4, 4), float(i), np.float32), 1.0)
            for i in range(8)
        ]
        assert client.put_batch(recs) == 8
        out = client.get_batch(8, timeout=1.0)
        assert [r.event_idx for r in out] == list(range(8))

    def test_get_batch_partial_drain(self, client):
        for i in range(3):
            client.put(FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0))
        out = client.get_batch(8, timeout=1.0)
        assert len(out) == 3  # returns what's there, no blocking for more

    def test_get_batch_empty_times_out(self, client):
        t0 = time.monotonic()
        assert client.get_batch(4, timeout=0.05) == []
        assert time.monotonic() - t0 >= 0.04

    def test_put_batch_truncates_when_full(self):
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueServer

        srv = TcpQueueServer(RingBuffer(4)).serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            recs = [
                FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0) for i in range(6)
            ]
            assert c.put_batch(recs) == 4  # queue holds 4; caller retries rest
            assert c.size() == 4
            # FIFO preserved: accepted prefix, not an arbitrary subset
            out = c.get_batch(8, timeout=1.0)
            assert [r.event_idx for r in out] == [0, 1, 2, 3]
            c.disconnect()
        finally:
            srv.shutdown()

    def test_rpc_reduction_vs_single_get(self):
        """The point of the opcode: one round trip for N items."""
        srv = TcpQueueServer(host="127.0.0.1", maxsize=128).serve_background()
        try:
            client = TcpQueueClient("127.0.0.1", srv.port)
            n = 64
            recs = [
                FrameRecord(0, i, np.zeros((1, 8, 8), np.float32), 1.0) for i in range(n)
            ]
            assert client.put_batch(recs) == n
            t0 = time.monotonic()
            out = client.get_batch(n, timeout=2.0)
            t_batch = time.monotonic() - t0
            assert len(out) == n
            assert client.put_batch(recs) == n
            t0 = time.monotonic()
            for _ in range(n):
                assert client.get() is not EMPTY
            t_single = time.monotonic() - t0
            # loopback round trips are ~50us each; batch should win clearly,
            # but keep the margin loose for CI noise
            assert t_batch < t_single
            client.disconnect()
        finally:
            srv.shutdown()


class TestInFlightRequeue:
    def test_requeue_preserves_items(self):
        """Server-side put-back when a response write fails (ADVICE r1
        low: GET popped the item before sendall — a consumer crash between
        pop and write silently lost the frame)."""
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueServer

        srv = TcpQueueServer(RingBuffer(8))
        rec = FrameRecord(0, 7, np.zeros((1, 2, 2), np.float32), 1.0)
        srv._requeue(srv.queue, [rec])
        assert srv.queue.size() == 1
        assert srv.queue.get().event_idx == 7
        srv.shutdown()

    def test_requeue_lands_ahead_of_eos(self):
        """Recovered in-flight frames must be readable BEFORE EOS markers
        already in the queue, or a tally-driven consumer stops early and
        the frames are silently lost (code-review r2 finding)."""
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueServer

        srv = TcpQueueServer(RingBuffer(8))
        srv.queue.put(EndOfStream())
        recs = [FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0) for i in (5, 6)]
        srv._requeue(srv.queue, recs)
        drained = [srv.queue.get() for _ in range(3)]
        assert [r.event_idx for r in drained[:2]] == [5, 6]  # order kept, ahead of EOS
        assert is_eos(drained[2])
        srv.shutdown()


class TestDeadServer:
    def test_killed_server_raises_transport_closed(self):
        """A dead server (no graceful close) must surface as TransportClosed
        so consumers' dead-transport handling fires (code-review r2)."""
        srv = TcpQueueServer(host="127.0.0.1", maxsize=8).serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        assert c.put(1)
        srv.shutdown()
        srv._sock.close()
        with pytest.raises(TransportClosed):
            for _ in range(100):  # OS may buffer a few sends first
                c.put(2)
                c.get()
        c.disconnect()


class TestNamedQueues:
    """One server hosting many named queues (OPEN opcode) — Ray-GCS
    parity: the reference resolves queues by (namespace, name) through one
    GCS (shared_queue.py:33-38, data_reader.py:20); round 2's server held
    exactly one anonymous queue."""

    def test_two_detectors_rendezvous_by_name_one_server(self, server):
        # two producer/consumer pairs, two detectors, ONE server process
        prod_epix = TcpQueueClient("127.0.0.1", server.port, namespace="lcls", queue_name="epix")
        prod_jf = TcpQueueClient("127.0.0.1", server.port, namespace="lcls", queue_name="jungfrau")
        cons_epix = TcpQueueClient("127.0.0.1", server.port, namespace="lcls", queue_name="epix")
        cons_jf = TcpQueueClient("127.0.0.1", server.port, namespace="lcls", queue_name="jungfrau")
        try:
            assert prod_epix.put({"det": "epix", "i": 0})
            assert prod_jf.put({"det": "jf", "i": 0})
            assert prod_epix.put({"det": "epix", "i": 1})
            # streams are isolated per name and FIFO within each
            assert cons_epix.get() == {"det": "epix", "i": 0}
            assert cons_jf.get() == {"det": "jf", "i": 0}
            assert cons_epix.get() == {"det": "epix", "i": 1}
            assert cons_jf.get() is EMPTY
            assert server.named_queues() == [("lcls", "epix"), ("lcls", "jungfrau")]
        finally:
            for c in (prod_epix, prod_jf, cons_epix, cons_jf):
                c.disconnect()

    def test_namespaces_isolate_same_name(self, server):
        a = TcpQueueClient("127.0.0.1", server.port, namespace="run1", queue_name="q")
        b = TcpQueueClient("127.0.0.1", server.port, namespace="run2", queue_name="q")
        try:
            assert a.put("from-run1")
            assert b.get() is EMPTY  # same name, different namespace
            assert a.get() == "from-run1"
        finally:
            a.disconnect()
            b.disconnect()

    def test_default_queue_back_compat(self, server, client):
        # a client that never OPENs talks to the server's default queue
        named = TcpQueueClient("127.0.0.1", server.port, namespace="n", queue_name="q")
        try:
            assert client.put("anon")
            assert named.get() is EMPTY
            assert client.get() == "anon"
        finally:
            named.disconnect()

    def test_close_propagates_per_named_queue(self, server):
        a1 = TcpQueueClient("127.0.0.1", server.port, namespace="n", queue_name="a")
        a2 = TcpQueueClient("127.0.0.1", server.port, namespace="n", queue_name="a")
        b = TcpQueueClient("127.0.0.1", server.port, namespace="n", queue_name="b")
        try:
            a1.close_remote()
            with pytest.raises(TransportClosed):
                a2.get()
            assert b.put("alive") and b.get() == "alive"  # other queue unaffected
        finally:
            for c in (a1, a2, b):
                c.disconnect()

    def test_open_queue_honors_config_for_tcp(self, server):
        """transport/addressing.py must route (namespace, queue_name) to
        the named server queue (round-2 VERDICT missing #1: it ignored
        config for tcp:// addresses)."""
        from psana_ray_tpu.config import TransportConfig
        from psana_ray_tpu.transport.addressing import open_queue

        addr = f"tcp://127.0.0.1:{server.port}"
        cfg_a = TransportConfig(address=addr, namespace="ns", queue_name="det_a")
        cfg_b = TransportConfig(address=addr, namespace="ns", queue_name="det_b")
        qa_prod = open_queue(cfg_a, role="producer")
        qa_cons = open_queue(cfg_a, role="consumer")
        qb_cons = open_queue(cfg_b, role="consumer")
        try:
            assert qa_prod.put(FrameRecord(0, 7, np.ones((1, 4, 4), np.float32), 9.5))
            assert qb_cons.get() is EMPTY
            rec = qa_cons.get()
            assert isinstance(rec, FrameRecord) and rec.event_idx == 7
        finally:
            for c in (qa_prod, qa_cons, qb_cons):
                c.disconnect()


class TestShmBackedNamedQueues:
    """queue_server --shm hybrid: named queues get shm-ring backings named
    <namespace>__<queue_name> (the transport/addressing.shm_ring_name
    derivation), so a LOCAL consumer attaching over shm:// reads the very
    ring REMOTE producers feed over TCP."""

    def test_tcp_producer_shm_consumer_one_queue(self):
        pytest.importorskip("psana_ray_tpu.transport.shm_ring")
        from psana_ray_tpu.transport.shm_ring import ShmRingBuffer, native_available

        if not native_available():
            pytest.skip("native toolchain unavailable")
        import os as _os

        ns = f"hyb{_os.getpid()}"

        def factory(namespace, name, maxsize):
            return ShmRingBuffer.create(f"{namespace}__{name}", maxsize=maxsize)

        srv = TcpQueueServer(host="127.0.0.1", maxsize=8, queue_factory=factory).serve_background()
        prod = TcpQueueClient("127.0.0.1", srv.port, namespace=ns, queue_name="det")
        shm_consumer = None
        try:
            assert prod.put(FrameRecord(0, 3, np.ones((1, 2, 2), np.float32), 9.5))
            # local consumer bypasses TCP entirely: attaches to the ring
            # the server created for (ns, det)
            shm_consumer = ShmRingBuffer.attach(f"{ns}__det", retries=5, interval_s=0.2)
            rec = shm_consumer.get_wait(timeout=5.0)
            assert isinstance(rec, FrameRecord) and rec.event_idx == 3
        finally:
            prod.disconnect()
            if shm_consumer is not None:
                shm_consumer.destroy()
            srv.shutdown()


class TestGracefulDrain:
    """Server shutdown drains instead of dropping: begin_drain refuses
    PUTs (producers see the dead-queue signal, clean exit) while GETs keep
    serving until the queues empty — the in-flight frames the reference's
    `ray stop` would destroy with the actor survive to the consumers."""

    def test_drain_refuses_puts_serves_gets(self, server):
        prod = TcpQueueClient("127.0.0.1", server.port, namespace="n", queue_name="q")
        cons = TcpQueueClient("127.0.0.1", server.port, namespace="n", queue_name="q")
        try:
            for i in range(3):
                assert prod.put({"i": i})
            server.begin_drain()
            with pytest.raises(TransportClosed):
                prod.put({"i": 99})  # producers refused
            # consumers drain everything already queued
            assert [cons.get()["i"] for _ in range(3)] == [0, 1, 2]
            assert server.depth() == 0
        finally:
            prod.disconnect()
            cons.disconnect()

    def test_drain_covers_default_and_named(self, server, client):
        named = TcpQueueClient("127.0.0.1", server.port, namespace="n", queue_name="d")
        try:
            assert client.put("anon")
            assert named.put("named")
            assert server.depth() == 2
            server.begin_drain()
            with pytest.raises(TransportClosed):
                named.put_batch(["x"])
            assert client.get() == "anon"
            assert named.get() == "named"
            assert server.depth() == 0
        finally:
            named.disconnect()


class TestReconnect:
    """Client-side reconnect: transient connection failures are re-dialed
    with backoff and the interrupted exchange retried once; a server that
    stays dead still surfaces TransportClosed."""

    def test_dropped_connection_reconnects_to_live_server(self):
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port, reconnect_base_s=0.05)
            assert c.put(FrameRecord(0, 0, np.zeros((1, 2, 2), np.float32), 1.0))
            # simulate a network drop: kill the client's socket under it
            c._sock.close()
            rec = c.get()  # must reconnect and serve, not raise
            assert rec.event_idx == 0
            c.disconnect()
        finally:
            srv.close_all()
            srv.shutdown()

    def test_named_binding_replayed_after_reconnect(self):
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        try:
            c = TcpQueueClient(
                "127.0.0.1", srv.port, namespace="ns", queue_name="det_a",
                reconnect_base_s=0.05,
            )
            assert c.put(FrameRecord(0, 7, np.zeros((1, 2, 2), np.float32), 1.0))
            c._sock.close()  # drop; next op must re-dial AND re-OPEN
            rec = c.get()
            # lands on the same named queue (the default queue is empty;
            # an unreplayed binding would return EMPTY here)
            assert rec is not EMPTY and rec.event_idx == 7
            assert srv.named_queues() == [("ns", "det_a")]
            c.disconnect()
        finally:
            srv.close_all()
            srv.shutdown()

    def test_dead_server_raises_after_retries(self):
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        c = TcpQueueClient(
            "127.0.0.1", srv.port, reconnect_tries=2, reconnect_base_s=0.02,
        )
        srv.shutdown()  # listening socket gone: reconnects are refused
        c._sock.close()
        t0 = time.monotonic()
        with pytest.raises(TransportClosed, match="reconnect attempts failed"):
            c.get()
        assert time.monotonic() - t0 < 10.0  # bounded, not hanging

    def test_server_restart_on_same_port(self):
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        srv1 = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        port = srv1.port
        c = TcpQueueClient("127.0.0.1", port, reconnect_tries=6, reconnect_base_s=0.05)
        assert c.put(FrameRecord(0, 1, np.zeros((1, 2, 2), np.float32), 1.0))
        srv1.shutdown()
        c._sock.close()
        # supervisor restarts the service on the same port (fresh queue —
        # in-memory contents are gone; shm-backed deployments keep them)
        srv2 = TcpQueueServer(RingBuffer(8), host="127.0.0.1", port=port).serve_background()
        try:
            assert c.get() is EMPTY  # reconnected to the fresh queue
            assert c.put(FrameRecord(0, 2, np.zeros((1, 2, 2), np.float32), 1.0))
            assert c.get().event_idx == 2
            c.disconnect()
        finally:
            srv2.close_all()
            srv2.shutdown()


class TestDeliveryAck:
    """At-least-once GET delivery: the server holds popped frames
    in-flight until the client's next request (or BYE) acknowledges the
    response, and re-enqueues them when the connection dies first."""

    def _mk(self):
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueServer

        q = RingBuffer(8)
        srv = TcpQueueServer(q, host="127.0.0.1").serve_background()
        return q, srv

    def test_unacked_delivery_requeued_on_connection_death(self):
        from psana_ray_tpu.transport.tcp import TcpQueueClient

        q, srv = self._mk()
        try:
            q.put(FrameRecord(0, 5, np.zeros((1, 2, 2), np.float32), 1.0))
            c = TcpQueueClient("127.0.0.1", srv.port)
            rec = c.get()  # response fully read by the client...
            assert rec.event_idx == 5 and q.size() == 0
            c._sock.close()  # ...but the conn dies with no next request/BYE
            deadline = time.monotonic() + 5.0
            while q.size() == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            # server cannot distinguish delivered-then-died from lost:
            # it must requeue (at-least-once — duplicate over silent loss)
            assert q.size() == 1
            assert q.get().event_idx == 5
        finally:
            srv.close_all()
            srv.shutdown()

    def test_clean_disconnect_does_not_requeue(self):
        from psana_ray_tpu.transport.tcp import TcpQueueClient

        q, srv = self._mk()
        try:
            q.put(FrameRecord(0, 6, np.zeros((1, 2, 2), np.float32), 1.0))
            c = TcpQueueClient("127.0.0.1", srv.port)
            assert c.get().event_idx == 6
            c.disconnect()  # BYE acks the delivery
            time.sleep(0.3)
            assert q.size() == 0  # no duplicate
        finally:
            srv.close_all()
            srv.shutdown()

    def test_next_request_acks_previous_delivery(self):
        from psana_ray_tpu.transport.tcp import TcpQueueClient

        q, srv = self._mk()
        try:
            q.put(FrameRecord(0, 7, np.zeros((1, 2, 2), np.float32), 1.0))
            c = TcpQueueClient("127.0.0.1", srv.port)
            assert c.get().event_idx == 7
            assert c.size() == 0  # any next request is the implicit ACK
            c._sock.close()       # dying NOW must not requeue frame 7
            time.sleep(0.3)
            assert q.size() == 0
        finally:
            srv.close_all()
            srv.shutdown()


class TestReconnectContracts:
    def test_initial_dial_backs_off_then_raises_transport_closed(self):
        from psana_ray_tpu.transport.tcp import TcpQueueClient

        # nothing listening on this port: the FIRST dial must go through
        # the backoff machinery and surface TransportClosed (which dead-
        # transport handlers catch), not a raw ConnectionRefusedError
        s = __import__("socket").socket()
        s.bind(("127.0.0.1", 0))
        free_port = s.getsockname()[1]
        s.close()
        with pytest.raises(TransportClosed, match="reconnect attempts failed"):
            TcpQueueClient(
                "127.0.0.1", free_port, reconnect_tries=2, reconnect_base_s=0.02
            )

    def test_initial_dial_waits_out_a_restarting_server(self):
        import socket as socket_mod

        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        s = socket_mod.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        srv_holder = {}

        def bring_up_late():
            time.sleep(0.3)
            srv_holder["srv"] = TcpQueueServer(
                RingBuffer(8), host="127.0.0.1", port=port
            ).serve_background()

        t = threading.Thread(target=bring_up_late, daemon=True)
        t.start()
        c = TcpQueueClient(  # dial starts before the server exists
            "127.0.0.1", port, reconnect_tries=8, reconnect_base_s=0.1
        )
        assert c.size() == 0
        c.disconnect()
        t.join()
        srv_holder["srv"].close_all()
        srv_holder["srv"].shutdown()

    def test_get_wait_timeout_bounds_reconnect_cycle(self):
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

        srv = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
        c = TcpQueueClient(
            "127.0.0.1", srv.port,
            reconnect_tries=10, reconnect_base_s=1.0,  # would be ~60 s unbounded
        )
        srv.close_all()
        srv.shutdown()
        c._sock.close()
        t0 = time.monotonic()
        with pytest.raises(TransportClosed):
            c.get_wait(timeout=0.5)
        assert time.monotonic() - t0 < 3.0  # deadline bounded the backoff


def test_server_shutdown_unblocks_idle_conns_no_zombie():
    """shutdown() must SHUT_RDWR accepted conns: an idle client whose
    server restarted must get a connection error -> reconnect to the NEW
    server, not be silently answered by a zombie serve thread of the old
    one (split-brain)."""
    from psana_ray_tpu.transport.ring import RingBuffer
    from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer

    srv1 = TcpQueueServer(RingBuffer(8), host="127.0.0.1").serve_background()
    port = srv1.port
    c = TcpQueueClient("127.0.0.1", port, reconnect_tries=6, reconnect_base_s=0.05)
    assert c.size() == 0
    srv1.shutdown()  # client does NOT touch its socket — server-side only
    srv2 = TcpQueueServer(RingBuffer(8), host="127.0.0.1", port=port).serve_background()
    try:
        srv2.queue.put(FrameRecord(0, 9, np.zeros((1, 2, 2), np.float32), 1.0))
        rec = c.get_wait(timeout=10.0)
        # only the NEW server has frame 9: receiving it proves the client
        # re-dialed instead of talking to srv1's orphaned thread
        assert rec is not EMPTY and rec.event_idx == 9
        c.disconnect()
    finally:
        srv2.close_all()
        srv2.shutdown()
