"""TCP queue transport: contract parity over a real socket, frame payloads,
concurrent producers/consumers, remote close propagation."""

import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.transport import EMPTY, TransportClosed
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer


@pytest.fixture
def server():
    s = TcpQueueServer(host="127.0.0.1", maxsize=8).serve_background()
    yield s
    s.shutdown()


@pytest.fixture
def client(server):
    c = TcpQueueClient("127.0.0.1", server.port)
    yield c
    c.disconnect()


class TestContract:
    def test_fifo_roundtrip(self, client):
        assert client.get() is EMPTY
        assert client.put({"x": 1})
        assert client.put([1, 2])
        assert client.size() == 2
        assert client.get() == {"x": 1}
        assert client.get() == [1, 2]

    def test_full_backpressure(self, client):
        n = 0
        while client.put(n):
            n += 1
        assert n == 8
        assert client.get() == 0

    def test_frame_payload(self, client):
        panels = np.arange(2 * 4 * 8, dtype=np.float32).reshape(2, 4, 8)
        client.put(FrameRecord(1, 7, panels, 8.8))
        out = client.get()
        assert isinstance(out, FrameRecord)
        np.testing.assert_array_equal(out.panels, panels)
        client.put(EndOfStream(total_events=1))
        assert is_eos(client.get())

    def test_remote_close_propagates(self, server, client):
        other = TcpQueueClient("127.0.0.1", server.port)
        client.close_remote()
        with pytest.raises(TransportClosed):
            other.get()
        with pytest.raises(TransportClosed):
            other.put(1)
        other.disconnect()

    def test_get_wait_timeout(self, client):
        t0 = time.monotonic()
        assert client.get_wait(timeout=0.05) is EMPTY
        assert time.monotonic() - t0 >= 0.04


class TestConcurrent:
    def test_multiple_clients_stream(self, server):
        n = 40

        def producer(rank):
            c = TcpQueueClient("127.0.0.1", server.port)
            for i in range(rank, n, 2):
                rec = FrameRecord(rank, i, np.full((1, 4, 4), float(i), np.float32), 1.0)
                c.put_wait(rec, timeout=10)
            c.disconnect()

        threads = [threading.Thread(target=producer, args=(r,)) for r in range(2)]
        for t in threads:
            t.start()
        consumer = TcpQueueClient("127.0.0.1", server.port)
        got = []
        while len(got) < n:
            item = consumer.get_wait(timeout=5.0)
            assert item is not EMPTY, "starved"
            got.append(item)
        for t in threads:
            t.join()
        consumer.disconnect()
        assert sorted(r.event_idx for r in got) == list(range(n))
