"""TCP queue transport: contract parity over a real socket, frame payloads,
concurrent producers/consumers, remote close propagation."""

import threading
import time

import numpy as np
import pytest

from psana_ray_tpu.records import EndOfStream, FrameRecord, is_eos
from psana_ray_tpu.transport import EMPTY, TransportClosed
from psana_ray_tpu.transport.tcp import TcpQueueClient, TcpQueueServer


@pytest.fixture
def server():
    s = TcpQueueServer(host="127.0.0.1", maxsize=8).serve_background()
    yield s
    s.shutdown()


@pytest.fixture
def client(server):
    c = TcpQueueClient("127.0.0.1", server.port)
    yield c
    c.disconnect()


class TestContract:
    def test_fifo_roundtrip(self, client):
        assert client.get() is EMPTY
        assert client.put({"x": 1})
        assert client.put([1, 2])
        assert client.size() == 2
        assert client.get() == {"x": 1}
        assert client.get() == [1, 2]

    def test_full_backpressure(self, client):
        n = 0
        while client.put(n):
            n += 1
        assert n == 8
        assert client.get() == 0

    def test_frame_payload(self, client):
        panels = np.arange(2 * 4 * 8, dtype=np.float32).reshape(2, 4, 8)
        client.put(FrameRecord(1, 7, panels, 8.8))
        out = client.get()
        assert isinstance(out, FrameRecord)
        np.testing.assert_array_equal(out.panels, panels)
        client.put(EndOfStream(total_events=1))
        assert is_eos(client.get())

    def test_remote_close_propagates(self, server, client):
        other = TcpQueueClient("127.0.0.1", server.port)
        client.close_remote()
        with pytest.raises(TransportClosed):
            other.get()
        with pytest.raises(TransportClosed):
            other.put(1)
        other.disconnect()

    def test_get_wait_timeout(self, client):
        t0 = time.monotonic()
        assert client.get_wait(timeout=0.05) is EMPTY
        assert time.monotonic() - t0 >= 0.04


class TestConcurrent:
    def test_multiple_clients_stream(self, server):
        n = 40

        def producer(rank):
            c = TcpQueueClient("127.0.0.1", server.port)
            for i in range(rank, n, 2):
                rec = FrameRecord(rank, i, np.full((1, 4, 4), float(i), np.float32), 1.0)
                c.put_wait(rec, timeout=10)
            c.disconnect()

        threads = [threading.Thread(target=producer, args=(r,)) for r in range(2)]
        for t in threads:
            t.start()
        consumer = TcpQueueClient("127.0.0.1", server.port)
        got = []
        while len(got) < n:
            item = consumer.get_wait(timeout=5.0)
            assert item is not EMPTY, "starved"
            got.append(item)
        for t in threads:
            t.join()
        consumer.disconnect()
        assert sorted(r.event_idx for r in got) == list(range(n))


class TestBatchedOpcodes:
    """GET_BATCH/PUT_BATCH drain/send N records per round trip, clearing
    the per-event-RPC bottleneck on the cross-host path (VERDICT r1 weak
    #5; reference data_reader.py:35 pays one RPC per frame)."""

    def test_put_batch_then_get_batch(self, server, client):
        recs = [
            FrameRecord(0, i, np.full((1, 4, 4), float(i), np.float32), 1.0)
            for i in range(8)
        ]
        assert client.put_batch(recs) == 8
        out = client.get_batch(8, timeout=1.0)
        assert [r.event_idx for r in out] == list(range(8))

    def test_get_batch_partial_drain(self, client):
        for i in range(3):
            client.put(FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0))
        out = client.get_batch(8, timeout=1.0)
        assert len(out) == 3  # returns what's there, no blocking for more

    def test_get_batch_empty_times_out(self, client):
        t0 = time.monotonic()
        assert client.get_batch(4, timeout=0.05) == []
        assert time.monotonic() - t0 >= 0.04

    def test_put_batch_truncates_when_full(self):
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueServer

        srv = TcpQueueServer(RingBuffer(4)).serve_background()
        try:
            c = TcpQueueClient("127.0.0.1", srv.port)
            recs = [
                FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0) for i in range(6)
            ]
            assert c.put_batch(recs) == 4  # queue holds 4; caller retries rest
            assert c.size() == 4
            # FIFO preserved: accepted prefix, not an arbitrary subset
            out = c.get_batch(8, timeout=1.0)
            assert [r.event_idx for r in out] == [0, 1, 2, 3]
            c.disconnect()
        finally:
            srv.shutdown()

    def test_rpc_reduction_vs_single_get(self):
        """The point of the opcode: one round trip for N items."""
        srv = TcpQueueServer(host="127.0.0.1", maxsize=128).serve_background()
        try:
            client = TcpQueueClient("127.0.0.1", srv.port)
            n = 64
            recs = [
                FrameRecord(0, i, np.zeros((1, 8, 8), np.float32), 1.0) for i in range(n)
            ]
            assert client.put_batch(recs) == n
            t0 = time.monotonic()
            out = client.get_batch(n, timeout=2.0)
            t_batch = time.monotonic() - t0
            assert len(out) == n
            assert client.put_batch(recs) == n
            t0 = time.monotonic()
            for _ in range(n):
                assert client.get() is not EMPTY
            t_single = time.monotonic() - t0
            # loopback round trips are ~50us each; batch should win clearly,
            # but keep the margin loose for CI noise
            assert t_batch < t_single
            client.disconnect()
        finally:
            srv.shutdown()


class TestInFlightRequeue:
    def test_requeue_preserves_items(self):
        """Server-side put-back when a response write fails (ADVICE r1
        low: GET popped the item before sendall — a consumer crash between
        pop and write silently lost the frame)."""
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueServer

        srv = TcpQueueServer(RingBuffer(8))
        rec = FrameRecord(0, 7, np.zeros((1, 2, 2), np.float32), 1.0)
        srv._requeue([rec])
        assert srv.queue.size() == 1
        assert srv.queue.get().event_idx == 7
        srv.shutdown()

    def test_requeue_lands_ahead_of_eos(self):
        """Recovered in-flight frames must be readable BEFORE EOS markers
        already in the queue, or a tally-driven consumer stops early and
        the frames are silently lost (code-review r2 finding)."""
        from psana_ray_tpu.transport.ring import RingBuffer
        from psana_ray_tpu.transport.tcp import TcpQueueServer

        srv = TcpQueueServer(RingBuffer(8))
        srv.queue.put(EndOfStream())
        recs = [FrameRecord(0, i, np.zeros((1, 2, 2), np.float32), 1.0) for i in (5, 6)]
        srv._requeue(recs)
        drained = [srv.queue.get() for _ in range(3)]
        assert [r.event_idx for r in drained[:2]] == [5, 6]  # order kept, ahead of EOS
        assert is_eos(drained[2])
        srv.shutdown()


class TestDeadServer:
    def test_killed_server_raises_transport_closed(self):
        """A dead server (no graceful close) must surface as TransportClosed
        so consumers' dead-transport handling fires (code-review r2)."""
        srv = TcpQueueServer(host="127.0.0.1", maxsize=8).serve_background()
        c = TcpQueueClient("127.0.0.1", srv.port)
        assert c.put(1)
        srv.shutdown()
        srv._sock.close()
        with pytest.raises(TransportClosed):
            for _ in range(100):  # OS may buffer a few sends first
                c.put(2)
                c.get()
        c.disconnect()
