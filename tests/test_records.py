"""Record schema: typed EOS, 2-D promotion, wire round-trip.

Covers the reference quirks SURVEY.md §3 items 1-2: sentinel ambiguity and
payload-schema drift."""

import numpy as np
import pytest

from psana_ray_tpu.records import EndOfStream, EosTally, FrameRecord, decode, is_eos


def test_frame_record_fields():
    panels = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    rec = FrameRecord(shard_rank=3, event_idx=17, panels=panels, photon_energy=9.5)
    assert rec.shard_rank == 3
    assert rec.event_idx == 17
    assert rec.panels.shape == (2, 3, 4)
    assert rec.photon_energy == 9.5
    assert rec.nbytes == 24 * 4


def test_2d_promotion():
    # parity: reference producer.py:96-97 promotes 2-D frames to 3-D
    rec = FrameRecord(0, 0, np.zeros((5, 6), np.float32), 1.0)
    assert rec.panels.shape == (1, 5, 6)


def test_rejects_bad_ndim():
    with pytest.raises(ValueError):
        FrameRecord(0, 0, np.zeros((2, 2, 2, 2), np.float32), 1.0)


def test_eos_is_typed_not_none():
    eos = EndOfStream(producer_rank=0, total_events=100)
    assert is_eos(eos)
    assert not is_eos(None)
    assert not is_eos(FrameRecord(0, 0, np.zeros((1, 2, 2), np.float32), 0.0))


@pytest.mark.parametrize("dtype", [np.float32, np.uint16, np.int32, np.float64])
def test_wire_roundtrip(dtype):
    panels = (np.random.default_rng(0).random((4, 8, 8)) * 100).astype(dtype)
    rec = FrameRecord(1, 42, panels, photon_energy=10.2, timestamp=123.5)
    out = decode(rec.to_bytes())
    assert isinstance(out, FrameRecord)
    assert out.shard_rank == 1 and out.event_idx == 42
    assert out.photon_energy == pytest.approx(10.2)
    assert out.timestamp == pytest.approx(123.5)
    assert out.panels.dtype == dtype
    np.testing.assert_array_equal(out.panels, panels)


def test_eos_wire_roundtrip():
    eos = EndOfStream(producer_rank=2, total_events=512)
    out = decode(eos.to_bytes())
    assert isinstance(out, EndOfStream)
    assert out.producer_rank == 2
    assert out.total_events == 512


def test_decode_rejects_garbage():
    with pytest.raises(ValueError):
        decode(b"\x00\x00\x00\x00garbage....")


class TestEosAggregation:
    """Multi-producer EOS: markers carry shard coverage; EosTally stops
    consumers only when every global shard is accounted for (the role the
    reference's global MPI barrier played, producer.py:119-126)."""

    def test_v2_wire_roundtrip_with_coverage(self):
        eos = EndOfStream(producer_rank=3, total_events=64, shards_done=2, total_shards=6)
        out = decode(eos.to_bytes())
        assert out.shards_done == 2
        assert out.total_shards == 6
        assert out.producer_rank == 3

    def test_v1_wire_decodes_with_default_coverage(self):
        import struct

        from psana_ray_tpu.records import _EOS_HEADER_V1, _EOS_MAGIC

        buf = _EOS_HEADER_V1.pack(_EOS_MAGIC, 1, 5, 100)  # schema v1, no coverage
        out = EndOfStream.from_bytes(buf)
        assert out.producer_rank == 5
        assert out.shards_done == 1 and out.total_shards == 1

    def test_tally_single_producer(self):
        t = EosTally()
        assert t.observe(EndOfStream())  # 1/1 shard -> complete

    def test_tally_waits_for_all_runtimes(self):
        t = EosTally()
        assert not t.observe(EndOfStream(producer_rank=0, shards_done=2, total_shards=4))
        assert not t.complete
        assert t.observe(EndOfStream(producer_rank=2, shards_done=2, total_shards=4))

    def test_tally_flags_duplicates(self):
        t = EosTally()
        eos = EndOfStream(producer_rank=0, shards_done=1, total_shards=2)
        assert not t.is_duplicate(eos)
        t.observe(eos)
        assert t.is_duplicate(eos)
        assert not t.is_duplicate(EndOfStream(producer_rank=1, shards_done=1, total_shards=2))

    def test_tally_idempotent_under_at_least_once_duplicates(self):
        """A transport retry (TCP reconnect) can duplicate an EOS marker;
        coverage is keyed by producer_rank, so N duplicated copies from
        one runtime must never complete the tally in place of the missing
        runtime's marker (tcp.py delivery contract)."""
        t = EosTally()
        eos_a = EndOfStream(producer_rank=0, shards_done=1, total_shards=2)
        assert not t.observe(eos_a)
        for _ in range(3):  # duplicated deliveries of the SAME marker
            assert not t.process(eos_a)
            assert not t.complete
        assert t.observe(EndOfStream(producer_rank=1, shards_done=1, total_shards=2))


class TestZeroCopyCodec:
    """encode_into/encoded_size must produce byte-identical wire data to
    to_bytes() (the zero-copy shm path depends on it)."""

    def test_frame_encode_into_matches_to_bytes(self, rng):
        import numpy as np

        from psana_ray_tpu.records import FrameRecord, decode, encode_into, encoded_size

        rec = FrameRecord(3, 77, rng.normal(size=(2, 8, 8)).astype(np.float32), 9.1,
                          timestamp=123.5)
        ref = rec.to_bytes()
        n = encoded_size(rec)
        assert n == len(ref)
        buf = bytearray(n + 16)
        written = encode_into(rec, memoryview(buf)[:n])
        assert written == n
        assert bytes(buf[:n]) == ref
        back = decode(memoryview(buf)[:n])
        assert back.equals(rec)

    def test_eos_encode_into_matches_to_bytes(self):
        from psana_ray_tpu.records import EndOfStream, decode, encode_into, encoded_size

        eos = EndOfStream(producer_rank=2, total_events=50, shards_done=3, total_shards=8)
        ref = eos.to_bytes()
        n = encoded_size(eos)
        assert n == len(ref)
        buf = bytearray(n)
        assert encode_into(eos, memoryview(buf)) == n
        assert bytes(buf) == ref
        back = decode(memoryview(buf))
        assert back == eos

    def test_non_contiguous_panels(self, rng):
        import numpy as np

        from psana_ray_tpu.records import FrameRecord, decode, encode_into, encoded_size

        big = rng.normal(size=(4, 8, 16)).astype(np.float32)
        rec = FrameRecord(0, 1, big[:, :, ::2], 8.0)  # strided view
        n = encoded_size(rec)
        buf = bytearray(n)
        encode_into(rec, memoryview(buf))
        assert decode(memoryview(buf)).equals(rec)
