"""Record schema: typed EOS, 2-D promotion, wire round-trip.

Covers the reference quirks SURVEY.md §3 items 1-2: sentinel ambiguity and
payload-schema drift."""

import numpy as np
import pytest

from psana_ray_tpu.records import EndOfStream, FrameRecord, decode, is_eos


def test_frame_record_fields():
    panels = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    rec = FrameRecord(shard_rank=3, event_idx=17, panels=panels, photon_energy=9.5)
    assert rec.shard_rank == 3
    assert rec.event_idx == 17
    assert rec.panels.shape == (2, 3, 4)
    assert rec.photon_energy == 9.5
    assert rec.nbytes == 24 * 4


def test_2d_promotion():
    # parity: reference producer.py:96-97 promotes 2-D frames to 3-D
    rec = FrameRecord(0, 0, np.zeros((5, 6), np.float32), 1.0)
    assert rec.panels.shape == (1, 5, 6)


def test_rejects_bad_ndim():
    with pytest.raises(ValueError):
        FrameRecord(0, 0, np.zeros((2, 2, 2, 2), np.float32), 1.0)


def test_eos_is_typed_not_none():
    eos = EndOfStream(producer_rank=0, total_events=100)
    assert is_eos(eos)
    assert not is_eos(None)
    assert not is_eos(FrameRecord(0, 0, np.zeros((1, 2, 2), np.float32), 0.0))


@pytest.mark.parametrize("dtype", [np.float32, np.uint16, np.int32, np.float64])
def test_wire_roundtrip(dtype):
    panels = (np.random.default_rng(0).random((4, 8, 8)) * 100).astype(dtype)
    rec = FrameRecord(1, 42, panels, photon_energy=10.2, timestamp=123.5)
    out = decode(rec.to_bytes())
    assert isinstance(out, FrameRecord)
    assert out.shard_rank == 1 and out.event_idx == 42
    assert out.photon_energy == pytest.approx(10.2)
    assert out.timestamp == pytest.approx(123.5)
    assert out.panels.dtype == dtype
    np.testing.assert_array_equal(out.panels, panels)


def test_eos_wire_roundtrip():
    eos = EndOfStream(producer_rank=2, total_events=512)
    out = decode(eos.to_bytes())
    assert isinstance(out, EndOfStream)
    assert out.producer_rank == 2
    assert out.total_events == 512


def test_decode_rejects_garbage():
    with pytest.raises(ValueError):
        decode(b"\x00\x00\x00\x00garbage....")
