"""Worker process for the 2-process multi-host infeed test.

Each process simulates one TPU host: 4 virtual CPU devices, its own local
batch shard, one global mesh over all 8 devices. Run by
tests/test_multihost.py as ``python multihost_worker.py <port> <rank>
<nprocs>``; prints ``MULTIHOST OK`` on success.
"""

import os
import re
import sys


def main():
    port, rank, nprocs = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    scenario = sys.argv[4] if len(sys.argv) > 4 else "batch"

    # 4 local devices per process (before any jax import); drop an
    # inherited count (the parent pytest env forces 8)
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=4").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")  # axon plugin ignores the env var
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=rank,
    )
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from psana_ray_tpu.infeed.multihost import make_global_batch

    assert jax.process_count() == nprocs, jax.process_count()
    devices = jax.devices()
    assert len(devices) == 4 * nprocs, devices
    assert len(jax.local_devices()) == 4

    mesh = Mesh(np.asarray(devices).reshape(2 * nprocs, 2), ("data", "model"))

    if scenario == "stream":
        _stream_scenario(jax, jnp, np, mesh, rank, nprocs)
        return
    if scenario == "fanin":
        _fanin_scenario(jax, jnp, np, mesh, rank, nprocs)
        return

    b_local = 4
    local = (
        np.arange(b_local * 3 * 5, dtype=np.float32).reshape(b_local, 3, 5)
        + 1000.0 * rank
    )
    g = make_global_batch(local, mesh)
    assert g.shape == (b_local * nprocs, 3, 5), g.shape

    # every addressable shard must hold rows from THIS host's local data
    lo, hi = 1000.0 * rank, 1000.0 * rank + b_local * 3 * 5
    for shard in g.addressable_shards:
        vals = np.asarray(shard.data)
        assert vals.min() >= lo and vals.max() < hi, (rank, vals.min(), vals.max())

    # SPMD reduction across both hosts' shards (rides the collective path)
    total = float(jax.jit(jnp.sum)(g))
    expected = sum(
        float(np.sum(np.arange(b_local * 3 * 5, dtype=np.float32) + 1000.0 * r))
        for r in range(nprocs)
    )
    assert abs(total - expected) < 1e-3, (total, expected)

    # model-axis replication: each data-group's shard pair is identical
    if rank == 0:
        by_row = {}
        for shard in g.addressable_shards:
            by_row.setdefault(shard.index[0], []).append(np.asarray(shard.data))
        for row, datas in by_row.items():
            for d in datas[1:]:
                np.testing.assert_array_equal(datas[0], d)

    print(f"MULTIHOST OK rank={rank} total={total}", flush=True)


def _stream_scenario(jax, jnp, np, mesh, rank, nprocs):
    """The ASSEMBLED multi-host streaming loop (round-2 VERDICT missing
    #2): per-host producers -> local queue -> GlobalStreamConsumer ->
    global-batch SPMD step, with UNEVEN per-host stream lengths (rank 0
    streams 10 frames, rank 1 only 6 — rank 1 must pad the final round)."""
    import threading
    import time

    from psana_ray_tpu.infeed.multihost import GlobalStreamConsumer
    from psana_ray_tpu.records import EndOfStream, FrameRecord
    from psana_ray_tpu.transport import RingBuffer

    shape = (2, 4, 8)
    n_frames = 10 if rank == 0 else 6  # uneven tails across hosts
    local_bs = 4

    q = RingBuffer(maxsize=8)

    def produce():
        for i in range(n_frames):
            # +1 keeps every real frame sum nonzero (padding rows are 0)
            frame = np.full(shape, 100.0 * rank + i + 1, np.float32)
            while not q.put(FrameRecord(rank, i, frame, 9.5)):
                time.sleep(0.001)
        assert q.put_wait(EndOfStream(total_events=n_frames), timeout=30.0)

    t = threading.Thread(target=produce, daemon=True)
    t.start()

    consumer = GlobalStreamConsumer(
        q, local_batch_size=local_bs, mesh=mesh, frame_shape=shape
    )

    # SPMD step: masked per-row frame sums, sharded like the batch rows
    @jax.jit
    def _row_sums(frames, valid):
        m = valid.astype(jnp.float32)[:, None, None, None]
        return jnp.sum(frames * m, axis=(1, 2, 3))

    step = lambda batch: _row_sums(batch.frames, batch.valid)  # noqa: E731

    seen = []
    n_local = consumer.run(step, on_result=lambda out, g: seen.append((out, g)))
    t.join(timeout=30)

    assert n_local == n_frames, (rank, n_local)
    # every host ran the same number of rounds: the longest stream's
    # batch count (rank 1 padded its tail rounds)
    expected_rounds = -(-10 // local_bs)
    assert len(seen) == expected_rounds, (rank, len(seen))
    for out, g in seen:
        assert out.shape == (local_bs * nprocs,), out.shape
        assert g.frames.shape == (local_bs * nprocs, *shape), g.frames.shape

    # this host's addressable output rows carry exactly its frame sums
    # (frames are constant-filled: sum = value * prod(shape))
    px = float(np.prod(shape))
    got_rows = {}
    for out, _ in seen:
        for shard in out.addressable_shards:
            lo = shard.index[0].start or 0
            for j, v in enumerate(np.asarray(shard.data)):
                if v > 0:
                    got_rows.setdefault(lo + j, set()).add(float(v))
    flat = sorted(v for vals in got_rows.values() for v in vals)
    want = sorted((100.0 * rank + i + 1) * px for i in range(n_frames))
    assert flat == want, (rank, flat[:4], want[:4])

    print(f"MULTIHOST-STREAM OK rank={rank} frames={n_local}", flush=True)


def _fanin_scenario(jax, jnp, np, mesh, rank, nprocs):
    """Multi-host × multi-detector (round-3 VERDICT weak #5): every host
    runs TWO detector streams with different geometries and uneven lengths
    (per host AND per detector); MultiDetectorGlobalConsumer drives both
    to global completion on one deterministic collective schedule."""
    import threading
    import time

    from psana_ray_tpu.infeed.multihost import (
        GlobalStreamConsumer,
        MultiDetectorGlobalConsumer,
    )
    from psana_ray_tpu.records import EndOfStream, FrameRecord
    from psana_ray_tpu.transport import RingBuffer

    dets = {
        # name: (frame shape, frames on THIS host)  — all lengths uneven
        "epix": ((2, 4, 8), 10 if rank == 0 else 6),
        "jungfrau": ((1, 8, 8), 3 if rank == 0 else 7),
    }
    local_bs = 4
    queues = {name: RingBuffer(maxsize=8) for name in dets}

    def produce(name):
        shape, n = dets[name]
        q = queues[name]
        for i in range(n):
            frame = np.full(shape, 100.0 * rank + i + 1, np.float32)
            while not q.put(FrameRecord(rank, i, frame, 9.5)):
                time.sleep(0.001)
        assert q.put_wait(EndOfStream(total_events=n), timeout=30.0)

    threads = [threading.Thread(target=produce, args=(n,), daemon=True) for n in dets]
    for t in threads:
        t.start()

    legs = {
        name: GlobalStreamConsumer(
            queues[name], local_batch_size=local_bs, mesh=mesh,
            frame_shape=dets[name][0],
        )
        for name in dets
    }

    def make_step():
        @jax.jit
        def _row_sums(frames, valid):
            m = valid.astype(jnp.float32).reshape(-1, *([1] * (frames.ndim - 1)))
            return jnp.sum(frames * m, axis=tuple(range(1, frames.ndim)))

        return lambda batch: _row_sums(batch.frames, batch.valid)

    seen = {name: [] for name in dets}
    counts = MultiDetectorGlobalConsumer(legs).run(
        {name: make_step() for name in dets},
        on_result=lambda name, out, g: seen[name].append((out, g)),
    )
    for t in threads:
        t.join(timeout=30)

    for name, (shape, n) in dets.items():
        assert counts[name] == n, (rank, name, counts)
        # rounds = the LONGEST host's batch count for this detector
        n_max = max(10 if name == "epix" else 3, 6 if name == "epix" else 7)
        assert len(seen[name]) == -(-n_max // local_bs), (rank, name, len(seen[name]))
        # this host's addressable rows carry exactly its own frame sums;
        # dedupe by (round, row) — the model axis replicates each row
        # into multiple addressable shards
        px = float(np.prod(shape))
        rows = {}
        for ri, (out, _) in enumerate(seen[name]):
            for shard in out.addressable_shards:
                lo = shard.index[0].start or 0
                for j, v in enumerate(np.asarray(shard.data)):
                    if v > 0:
                        rows[(ri, lo + j)] = float(v)
        got = sorted(rows.values())
        want = sorted((100.0 * rank + i + 1) * px for i in range(n))
        assert got == want, (rank, name, got[:4], want[:4])

    print(f"MULTIHOST-FANIN OK rank={rank} counts={counts}", flush=True)


if __name__ == "__main__":
    main()
