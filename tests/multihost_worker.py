"""Worker process for the 2-process multi-host infeed test.

Each process simulates one TPU host: 4 virtual CPU devices, its own local
batch shard, one global mesh over all 8 devices. Run by
tests/test_multihost.py as ``python multihost_worker.py <port> <rank>
<nprocs>``; prints ``MULTIHOST OK`` on success.
"""

import os
import re
import sys


def main():
    port, rank, nprocs = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])

    # 4 local devices per process (before any jax import); drop an
    # inherited count (the parent pytest env forces 8)
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=4").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")  # axon plugin ignores the env var
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=rank,
    )
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from psana_ray_tpu.infeed.multihost import make_global_batch

    assert jax.process_count() == nprocs, jax.process_count()
    devices = jax.devices()
    assert len(devices) == 4 * nprocs, devices
    assert len(jax.local_devices()) == 4

    mesh = Mesh(np.asarray(devices).reshape(2 * nprocs, 2), ("data", "model"))

    b_local = 4
    local = (
        np.arange(b_local * 3 * 5, dtype=np.float32).reshape(b_local, 3, 5)
        + 1000.0 * rank
    )
    g = make_global_batch(local, mesh)
    assert g.shape == (b_local * nprocs, 3, 5), g.shape

    # every addressable shard must hold rows from THIS host's local data
    lo, hi = 1000.0 * rank, 1000.0 * rank + b_local * 3 * 5
    for shard in g.addressable_shards:
        vals = np.asarray(shard.data)
        assert vals.min() >= lo and vals.max() < hi, (rank, vals.min(), vals.max())

    # SPMD reduction across both hosts' shards (rides the collective path)
    total = float(jax.jit(jnp.sum)(g))
    expected = sum(
        float(np.sum(np.arange(b_local * 3 * 5, dtype=np.float32) + 1000.0 * r))
        for r in range(nprocs)
    )
    assert abs(total - expected) < 1e-3, (total, expected)

    # model-axis replication: each data-group's shard pair is identical
    if rank == 0:
        by_row = {}
        for shard in g.addressable_shards:
            by_row.setdefault(shard.index[0], []).append(np.asarray(shard.data))
        for row, datas in by_row.items():
            for d in datas[1:]:
                np.testing.assert_array_equal(datas[0], d)

    print(f"MULTIHOST OK rank={rank} total={total}", flush=True)


if __name__ == "__main__":
    main()
