"""Known-bad fixture for event-loop-blocking rooted at the ISSUE 17
additions: the kernel pass-through pump (``_EvConn._pump_span``) and the
worker supervisor loop (``WorkerSupervisor._supervise``) are audited
roots of their own — blocking idioms inside them must flag even when
nothing links them back to ``EventLoop.run``."""

import os
import time


class _EvConn:
    def _pump_span(self, span):
        # BAD: a retry sleep inside the splice pump freezes every
        # connection on the loop — sendfile must return short or raise
        # BlockingIOError, never be waited for
        while True:
            try:
                sent = os.sendfile(
                    self.sock.fileno(), span.fileno(), span.pos, span.nbytes
                )
                break
            except BlockingIOError:
                time.sleep(0.001)  # BAD: busy-wait on the loop thread
        self._ack_reader.join()  # BAD: unbounded join in the pump
        return sent


class WorkerSupervisor:
    def _supervise(self):
        while True:
            pid, status = os.waitpid(-1, 0)
            time.sleep(1.0)  # BAD: respawn backoff held on the reap loop
            self._lock.acquire()  # BAD: lock wait with no timeout
            self._respawn(pid)

    def _respawn(self, pid):
        self._spawn_thread.join()  # BAD: unbounded join before respawn
