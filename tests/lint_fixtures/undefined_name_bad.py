"""BAD: `List` is loaded but never imported — the seed's utils/metrics.py
bug shape (`from __future__ import annotations` hides it at runtime
until someone introspects the annotations)."""

from __future__ import annotations


def quantiles(samples) -> List[float]:
    return list(sorted(samples))


LEVELS: List[float] = [0.5, 0.9, 0.99]
