"""Known-bad fixture for the telemetry-discipline checker.

Both rules must flag: an obs source whose ``snapshot()`` reads mutable
counter state outside the class lock (a torn federated scrape), and a
``# lint: sample-path`` function that allocates per sample.
"""

import threading


class TornSource:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._bytes = 0

    def observe(self, n):
        with self._lock:
            self._count += 1
            self._bytes += n

    def snapshot(self):
        # BAD: mutable counters read bare — the 1 Hz sampler can record
        # a count/bytes pair no single instant ever had
        return {"count_total": self._count, "bytes_total": self._bytes}


class AllocatingRing:
    def __init__(self, capacity):
        self.rows = [None] * capacity
        self.i = 0

    def append(self, t, v):  # lint: sample-path
        # BAD: a fresh list per sample — the sample path must stay
        # counter arithmetic into preallocated storage
        self.rows[self.i] = [t, v]
        self.i = (self.i + 1) % len(self.rows)
