"""known-bad: inconsistent inferred locksets, no annotation needed.

``_count`` is written under ``self._lock`` in ``add`` and read bare in
``read`` — the PR 3/8 recurring class (correct until a scrape or
teardown thread hits the bare access). ``_peak`` shows the annotation-
assertion arm: declared ``guarded-by: _other_lock`` while every access
holds ``_lock`` — the annotation names the wrong lock.
"""

import threading


class SharedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._other_lock = threading.Lock()
        self._count = 0
        self._peak = 0  # guarded-by: _other_lock

    def add(self, n):
        with self._lock:
            self._count += n
            if self._count > self._peak:
                self._peak = self._count

    def read(self):
        return self._count  # bare: no lock held
