"""GOOD: the sanctioned segment-ownership patterns — try/finally close,
exception-path close with ownership transfer by return, hand-off to the
tracked ring, an owning constructor, and a context-managed mapping."""

import mmap

from psana_ray_tpu.storage.segment import Segment


def scan_once(path):
    seg = Segment.open_existing(path, 0)
    try:
        return seg.scan(0)
    finally:
        seg.close()


def open_mapped(path, f):
    mm = mmap.mmap(f.fileno(), 1 << 20)
    try:
        return Segment(path, f, mm, 0)  # the constructor takes ownership
    except BaseException:
        mm.close()
        raise


def fresh_tail(path):
    return Segment.allocate(path, 1 << 20, 0)  # caller owns


def roll(log):
    seg = log._new_segment(log.next_offset)
    log._segments.append(seg)  # the ring owns (closed by log.close)
    return seg


def retire_oldest(log, free_path):
    seg = log._segments.pop(0)
    seg.retire(free_path)
    log._free.append(seg)


def peek_header(f):
    with mmap.mmap(f.fileno(), 4096) as mm:
        return mm[0]
