"""known-bad: a wire surface with an opcode the model fleet ignores.

``_OP_FROB`` is dispatched by the server but no protocol model declares
it and ``lint.model.drift.NON_MODELED`` carries no justification — the
drift gate must flag the blind spot (this is the "added an opcode to
the transport without modeling it" class).
"""

_OP_PUT_SEQ = b"W"
_OP_FROB = b"f"
_ST_OK = b"1"


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("eof")
        buf += chunk
    return buf


class FrobServerConn:
    def __init__(self, sock, queue):
        self._sock = sock
        self.queue = queue

    def _dispatch(self):
        op = _recv_exact(self._sock, 1)[0]
        name = _OPS.get(op)
        if name is None:
            raise ConnectionError("unknown opcode")
        getattr(self, name)()

    def _op_put_seq(self):
        item = _recv_exact(self._sock, 12)
        self.queue.put(item)
        self._sock.sendall(_ST_OK)

    def _op_frob(self):
        # a whole new stateful exchange, invisible to the model fleet
        self._sock.sendall(_ST_OK)


_OPS = {
    _OP_PUT_SEQ[0]: "_op_put_seq",
    _OP_FROB[0]: "_op_frob",
}
