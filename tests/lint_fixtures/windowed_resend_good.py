"""GOOD: the sanctioned windowed-put idiom — the reconnect path resends
the entire unacknowledged tail in order before anything else uses the
fresh connection, and acks prune the tail as they arrive."""

import socket
import struct
from collections import deque


class WindowedClient:
    def __init__(self, host, port):
        self._addr = (host, port)
        self._sock = socket.create_connection(self._addr)
        self._seq = 0
        self._unacked = deque()  # (seq, payload)

    def put_pipelined(self, payload):
        self._seq += 1
        self._unacked.append((self._seq, payload))
        header = struct.pack("<QI", self._seq, len(payload))
        try:
            self._sock.sendall(header + payload)
        except OSError:
            self._reconnect()
        return True

    def _drain_acks(self, max_unacked):
        while len(self._unacked) > max_unacked:
            (acked,) = struct.unpack("<Q", self._sock.recv(8))
            while self._unacked and self._unacked[0][0] <= acked:
                self._unacked.popleft()  # window advance

    def _reconnect(self):
        self._sock.close()
        self._sock = socket.create_connection(self._addr)
        # resend invariant: the whole tail, in sequence order, FIRST
        for seq, payload in list(self._unacked):
            header = struct.pack("<QI", seq, len(payload))
            self._sock.sendall(header + payload)
