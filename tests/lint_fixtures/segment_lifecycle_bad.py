"""BAD: segment/mmap acquisitions that leak their mapping — an assigned
segment with no close/retire/reset on any path, a dropped acquisition,
and a raw mmap that never reaches an owner."""

import mmap

from psana_ray_tpu.storage.segment import Segment


def scan_orphans(path):
    seg = Segment.open_existing(path, 0)
    n, torn = seg.scan(0)  # mapping stranded: nothing ever closes it
    return n, torn


def probe(path):
    Segment.allocate(path, 1 << 20, 0)  # result dropped on the floor


def peek_header(f):
    mm = mmap.mmap(f.fileno(), 4096)
    first = mm[0]
    return first  # the BYTE escapes, the mapping leaks


def roll_without_tracking(log):
    seg = log._new_segment(log.next_offset)
    seg.append(log.next_offset, b"x")  # never appended to the ring
