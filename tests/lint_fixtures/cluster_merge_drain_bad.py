"""KNOWN-BAD: a sleep smuggled into a cluster merge drain.

The batcher's drain loop reaches the cluster client through the same
``get_batch_stream`` seed edge as the single-server stream reader, so a
``time.sleep`` pacing the partition sweep — instead of a caller-bounded
socket timeout or an interruptible Event wait — must flag as a stall in
the audited graph (blocking-hot-path)."""

import time


def batches_from_queue(queue, batch_size):
    pop = getattr(queue, "get_batch_stream", None) or queue.get_batch
    while True:
        items = pop(batch_size, timeout=0.01)
        if not items:
            return
        yield items


class ClusterishClient:
    def get_batch_stream(self, max_items, timeout=None):
        return self._merge_drain(max_items, timeout)

    def _merge_drain(self, max_items, timeout):
        out = []
        for p in self._partitions:
            out.extend(self._pop(p, max_items - len(out), 0.0))
            if not out:
                time.sleep(0.05)  # MUST FLAG: unbounded pacing in the drain
        return out

    def _pop(self, p, n, t):
        return self._clients[p].get_batch(n, timeout=t)
