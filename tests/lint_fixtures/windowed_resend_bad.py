"""BAD: a windowed-put client that tracks its unacked tail and
reconnects — but the reconnect path never resends the tail (holes after
a drop mid-window) and nothing ever prunes it (unbounded growth +
whole-session duplication on every reconnect)."""

import socket
import struct


class LeakyWindowedClient:
    def __init__(self, host, port):
        self._sock = socket.create_connection((host, port))
        self._seq = 0
        self._unacked = []  # (seq, payload) — appended, never resent/pruned

    def put_pipelined(self, payload):
        self._seq += 1
        self._unacked.append((self._seq, payload))
        header = struct.pack("<QI", self._seq, len(payload))
        try:
            self._sock.sendall(header + payload)
        except OSError:
            self._reconnect()
        return True

    def _reconnect(self):
        # fresh socket, but the unacked tail is forgotten: every frame
        # that was in flight when the link dropped is silently lost
        self._sock.close()
        self._sock = socket.create_connection((self._sock.getpeername()))
