"""Known-good fixture for event-loop-blocking at the ISSUE 17 roots:
the sanctioned splice-pump and supervisor shapes — one non-blocking
sendfile per pump call (short returns and BlockingIOError are the
flow control), and a reap loop whose only park is ``os.waitpid``
(event-driven reaping) with every other wait deadline-bounded."""

import os


class _EvConn:
    def _pump_span(self, span):
        # one attempt per readiness event: a short send advances the
        # span in place, EAGAIN propagates to flush_out, which keeps
        # EPOLLOUT armed — the selector drives the retry, not a wait
        sent = os.sendfile(
            self.sock.fileno(), span.fileno(), span.pos, span.nbytes
        )
        if sent < span.nbytes:
            span.advance(sent)
        else:
            self.out.popleft()
        return sent


class WorkerSupervisor:
    def _supervise(self):
        while True:
            try:
                pid, status = os.waitpid(-1, 0)  # parked reaping, not sleeping
            except ChildProcessError:
                if self._stop.wait(0.2):  # bounded: shutdown poll slice
                    return
                continue
            self._respawn(pid)

    def _respawn(self, pid):
        if self._spawn_thread is not None:
            self._spawn_thread.join(timeout=2.0)  # bounded join
