"""BAD (ISSUE 11): a replication half-protocol — the replica-append
opcode is shipped by the owner's link but the follower's dispatch never
matches it (the first 'V' on the wire is a runtime protocol error that
kills the replication link), and the promote opcode has a dispatch arm
nobody sends (dead failover surface: a replica that can never be
promoted is a replica that never serves)."""

_OP_RSUB = b"h"  # replica-subscribe: wired both ways (the control case)
_OP_RAPP = b"v"  # replica-append: SENT below, never dispatched
_OP_RPROMOTE = b"y"  # promote: dispatched below, never sent


class Link:
    def subscribe(self, sock, name):
        sock.sendall(_OP_RSUB + name)

    def ship(self, sock, offset, payload):
        sock.sendall(_OP_RAPP + offset + payload)


class Server:
    def dispatch(self, op, conn):
        if op == _OP_RSUB:
            return self.open_replica(conn)
        elif op == _OP_RPROMOTE:
            return self.promote_replica(conn)

    def open_replica(self, conn):
        return conn

    def promote_replica(self, conn):
        return conn
