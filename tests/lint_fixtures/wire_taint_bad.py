"""known-bad: wire-parsed sizes reaching allocation sinks unchecked.

The PR 9 class: a hostile peer picks the RLE count / payload length and
the server allocates whatever it says — ``np.repeat`` amplification,
frame-buffer ``bytearray``, pool lease sizing and zero-fill
amplification, all straight from ``struct.unpack`` with no cap.
"""

import struct

import numpy as np


def decode_rle(buf, values):
    (count,) = struct.unpack_from("<I", buf, 0)
    # BUG: count is attacker-chosen; repeat amplifies a 4-byte field
    # into count elements
    return np.repeat(values, count)


def read_frame(sock, hdr):
    size, flags = struct.unpack("<QH", hdr)
    # BUG: a 64-bit length allocates before any sanity check
    payload = bytearray(size)
    sock.recv_into(payload)
    return payload, flags


def lease_for(pool, hdr):
    n = struct.unpack_from("<I", hdr)[0]
    # BUG: pool lease sized by the unchecked wire field
    return pool.lease(n)


def zero_fill(hdr):
    (n,) = struct.unpack("<I", hdr)
    # BUG: bytes amplification from a 4-byte field
    return b"\x00" * n
