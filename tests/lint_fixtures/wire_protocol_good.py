"""GOOD: every opcode has both a sender and a dispatch arm."""

_OP_PUT = b"P"
_OP_GET = b"G"


def request(sock, payload):
    sock.sendall(_OP_PUT + payload)


def poll(sock):
    sock.sendall(_OP_GET)


def serve(op, queue):
    if op == _OP_PUT:
        return queue.put
    elif op == _OP_GET:
        return queue.get
