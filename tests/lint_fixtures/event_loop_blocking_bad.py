"""Known-bad fixture for event-loop-blocking: blocking idioms reachable
from the loop dispatch. Every marked line must flag."""

import time


def _sendmsg_all(sock, parts):
    sock.sendall(parts)


class EventLoop:
    def run(self):
        while True:
            events = self._sel.select(0.1)
            for key, mask in events:
                self._dispatch(key.data)

    def _dispatch(self, conn):
        conn.handle()


class _Conn:
    def handle(self):
        time.sleep(0.01)  # BAD: a bounded sleep still freezes every conn
        self._lock.acquire()  # BAD: lock wait with no timeout
        self.sock.sendall(b"1")  # BAD: blocking send on the loop thread
        _sendmsg_all(self.sock, [b"x"])  # BAD: blocking send helper
        self._cond.wait()  # BAD: unbounded Condition wait
        self._reader.join()  # BAD: unbounded join
