"""Known-good fixture for the telemetry-discipline checker.

The sanctioned patterns: snapshot() copies mutable state under the
class lock (or the attribute is `# guarded-by` annotated, handing the
proof to the lock-discipline checker); the sample path is pure index
arithmetic into preallocated columns; set-once configuration from
``__init__`` needs no lock.
"""

import threading


class ConsistentSource:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._bytes = 0
        self._gw = None  # guarded-by: _lock
        self.name = "consistent"  # set-once: assigned only here

    def attach(self, gw):
        with self._lock:
            self._gw = gw

    def observe(self, n):
        with self._lock:
            self._count += 1
            self._bytes += n

    def snapshot(self):
        with self._lock:
            count, nbytes = self._count, self._bytes
        return {"source": self.name, "count_total": count, "bytes_total": nbytes}


class PreallocatedRing:
    def __init__(self, capacity):
        self.t = [0.0] * capacity
        self.v = [0.0] * capacity
        self.i = 0
        self.cap = capacity

    def append(self, t, v):  # lint: sample-path
        i = self.i
        self.t[i] = t
        self.v[i] = v
        self.i = i + 1 if i + 1 < self.cap else 0
