"""GOOD: every touch of the guarded attribute holds the lock — directly,
through a Condition constructed over it (aliasing), or inside a helper
whose callers provably hold it (`guarded-by-caller`)."""

import threading


class Ring:
    def __init__(self):
        self._handle_lock = threading.Lock()
        self._cv = threading.Condition(self._handle_lock)
        self._handle = object()  # guarded-by: _handle_lock
        self._gets, self._puts = 0, 0  # guarded-by: _handle_lock

    def bump(self):
        with self._handle_lock:
            self._gets += 1
            self._puts += 1

    def stats(self):
        with self._handle_lock:
            return id(self._live())

    def wait_attached(self):
        with self._cv:  # Condition over _handle_lock: counts as held
            return self._handle is not None

    def _live(self):
        # guarded-by-caller: _handle_lock
        if self._handle is None:
            raise RuntimeError("detached")
        return self._handle

    def disconnect(self):
        with self._handle_lock:
            self._handle = None
