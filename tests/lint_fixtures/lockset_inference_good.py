"""sanctioned: every lockset pattern the checker must NOT flag.

- ``_count``: every access holds ``self._lock``, either lexically or
  through a ``# guarded-by-caller`` waiver;
- ``capacity``: set once in ``__init__`` and only read after — Eraser's
  init-phase exclusion (config fields need no lock);
- ``_cv``: a Condition aliasing the lock — ``with self._cv:`` counts as
  holding ``_lock``.
"""

import threading


class ConsistentCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._count = 0
        self.capacity = 8

    def add(self, n):
        with self._lock:
            self._count += n

    def wait_nonzero(self):
        with self._cv:
            while self._count == 0:
                self._cv.wait(0.1)
            return self._count

    def _bump_locked(self):
        # guarded-by-caller: _lock
        self._count += 1

    def headroom(self):
        with self._lock:
            return self.capacity - self._count
