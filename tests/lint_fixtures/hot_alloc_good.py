# lint: hot-path
"""GOOD: the sanctioned zero-copy shapes — scatter-gather parts out,
recv_into a pooled lease in, and size-derived bookkeeping (nbytes,
from_bytes) that the banned-idiom lookbehind must not misread."""


def send_frame(sock, rec):
    sock.sendmsg(rec.wire_parts())


def read_payload(sock, mv):
    got = 0
    while got < len(mv):
        got += sock.recv_into(mv[got:])


def sizes(rec):
    return rec.nbytes(), len(rec.from_bytes(b""))
