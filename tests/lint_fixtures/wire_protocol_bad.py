"""BAD: three asymmetric opcodes — sent with no dispatch arm (runtime
protocol error on first use), dispatched with no sender (dead surface),
and defined on neither side."""

_OP_PUT = b"P"
_OP_GET = b"G"
_OP_FLUSH = b"L"  # sent below, never dispatched
_OP_LEGACY = b"Y"  # dispatched below, never sent
_OP_GHOST = b"Z"  # defined, used nowhere


def request(sock, payload):
    sock.sendall(_OP_PUT + payload)
    sock.sendall(_OP_FLUSH)


def poll(sock):
    sock.sendall(_OP_GET)


def serve(op, queue):
    if op == _OP_PUT:
        return queue.put
    elif op == _OP_GET:
        return queue.get
    elif op == _OP_LEGACY:
        return queue.size
