"""GOOD: the same module with the import present."""

from __future__ import annotations

from typing import List


def quantiles(samples) -> List[float]:
    return list(sorted(samples))


LEVELS: List[float] = [0.5, 0.9, 0.99]
