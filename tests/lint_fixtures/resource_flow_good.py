"""sanctioned: every acquire→release pattern the checker must accept.

- except-release-reraise covering the whole acquire→hand-off window;
- try/finally release;
- hand-off to a collection the caller owns (``out.append(lease)``);
- ``with`` management (context manager releases);
- escape into an attribute (object-lifetime hand-off).
"""


def guarded_decode(pool, sock, n):
    lease = pool.lease(n)
    try:
        sock.recv_into(lease.mv)
        return decode_payload(lease.mv, lease=lease)
    except BaseException:
        lease.release()
        raise


def finally_release(pool, sock, n):
    lease = pool.lease(n)
    try:
        sock.recv_into(lease.mv)
        return bytes(lease.mv[:n])
    finally:
        lease.release()


def staged(pool, n, out):
    lease = pool.lease(n)
    out.append(lease)
    return len(out)


def managed(pool, n):
    lease = pool.lease(n)
    with lease:
        return bytes(lease.mv[:n])


def liveness_guarded(pool, sock, n):
    out = None
    try:
        out = pool.lease(n)
        sock.recv_into(out.mv)
    except BaseException:
        if out is not None:  # branch on the lease's OWN liveness
            out.release()
        raise
    return out


class Holder:
    def __init__(self, pool, n):
        self._lease = pool.lease(n)

    def attach(self, pool, n):
        lease = pool.lease(n)
        self._lease = lease  # object-lifetime hand-off
        return self._lease


def decode_payload(mv, lease=None):
    return bytes(mv[:4])
