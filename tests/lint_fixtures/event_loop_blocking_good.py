"""Known-good fixture for event-loop-blocking: the sanctioned
non-blocking loop shapes — bounded select, incremental recv_into,
write-queue sends, deferred waits as timer state, `with lock:`
micro-sections, and deadline-bounded joins."""

import heapq
import time


class EventLoop:
    def run(self):
        while not self._stop.is_set():
            events = self._sel.select(self._select_timeout())
            for key, mask in events:
                self._dispatch(key.data)
            self._fire_timers()

    def _select_timeout(self):
        if self._timers:
            return max(0.0, self._timers[0][0] - time.monotonic())
        return 0.5

    def _dispatch(self, conn):
        conn.on_readable()

    def _fire_timers(self):
        now = time.monotonic()
        while self._timers and self._timers[0][0] <= now:
            _, conn = heapq.heappop(self._timers)
            conn.expire()


class _Conn:
    def on_readable(self):
        try:
            k = self.sock.recv_into(self._target)  # non-blocking socket
        except BlockingIOError:
            return
        if k == 0:
            raise ConnectionError("peer closed")
        with self._lock:  # micro-section, not an explicit wait
            self._got += k
        self.out.append(self._target)  # deferred: write queue, not sendall
        self._reader.join(timeout=2.0)  # bounded join is fine
        self._cond.wait(timeout=0.2)  # bounded wait is fine
