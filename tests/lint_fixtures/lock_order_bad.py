"""known-bad: the SIGUSR2-dump lock-order cycle (PR 4 class).

The dump path iterates the registry under the registry lock and
snapshots each connection under the connection lock; the connection
close path nests the same two locks the other way around.  One SIGUSR2
while a connection is closing and both threads sleep forever.
"""

import threading


class ConnRegistry:
    def __init__(self):
        self._reg_lock = threading.Lock()
        self._conns = []

    def register(self, conn):
        with self._reg_lock:
            self._conns.append(conn)

    def dump_all(self):
        # BUG: registry lock outside, connection lock inside ...
        with self._reg_lock:
            lines = []
            for conn in self._conns:
                with conn._conn_lock:
                    lines.append(conn.describe())
            return lines


class Conn:
    def __init__(self, registry):
        self.registry = registry
        self._conn_lock = threading.Lock()
        self.open = True

    def describe(self):
        return "conn open=%s" % self.open

    def close(self):
        # ... while close nests them the other way: deadlock with a
        # concurrent dump_all
        with self._conn_lock:
            self.open = False
            with self.registry._reg_lock:
                self.registry._conns.remove(self)
