"""GOOD: the sanctioned ownership patterns — exception-path release with
the lease riding the decoded record, context-manager scope, ownership
transfer by return, and a batch drain that pushes every record through
the owner that copies-then-releases."""


def recv_one(pool, sock, n, decode_payload):
    lease = pool.lease(n)
    try:
        sock.recv_into(lease.mv)
        return decode_payload(lease.mv, lease=lease)
    except BaseException:
        lease.release()
        raise


def scratch(pool, n):
    with pool.lease(n) as lease:
        return len(lease.mv)


def handoff(pool, n):
    return pool.lease(n)  # caller owns it (checked at ITS call site)


def drain(queue, batcher):
    items = queue.get_batch_view(32)
    for rec in items:
        batcher.push_view(rec)  # copies into the arena, then releases
