"""GOOD (ISSUE 11): the replication opcode triple with both arms —
subscribe and append shipped by the owner's link, promote sent on the
failover path, every one matched by a dispatch comparison."""

_OP_RSUB = b"h"
_OP_RAPP = b"v"
_OP_RPROMOTE = b"y"


class Link:
    def subscribe(self, sock, name):
        sock.sendall(_OP_RSUB + name)

    def ship(self, sock, offset, payload):
        sock.sendall(_OP_RAPP + offset + payload)


class Failover:
    def promote(self, sock, name):
        sock.sendall(_OP_RPROMOTE + name)


class Server:
    def dispatch(self, op, conn):
        if op == _OP_RSUB:
            return self.open_replica(conn)
        elif op == _OP_RAPP:
            return self.append_replica(conn)
        elif op == _OP_RPROMOTE:
            return self.promote_replica(conn)

    def open_replica(self, conn):
        return conn

    def append_replica(self, conn):
        return conn

    def promote_replica(self, conn):
        return conn
