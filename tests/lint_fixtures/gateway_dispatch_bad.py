"""KNOWN-BAD: a sleep smuggled into the serving gateway dispatch loop.

The gateway's dispatch loop sits directly on the latency SLO (ISSUE
12): a ``time.sleep`` pacing the idle wait — instead of the bounded,
offer()-woken Event wait — holds every tenant's admitted frames toward
their deadlines, and an unbounded queue pop in the transport pump does
the same through the ``get_batch`` seed edge (blocking-hot-path)."""

import time


class ServingGateway:
    def offer(self, rec, tenant="default"):
        self._q.append((tenant, rec))
        return True

    def dispatch_once(self):
        if not self._q:
            return 0
        tenant, rec = self._q.popleft()
        self._dispatch([rec], 1)
        return 1

    def run(self, stop=None):
        while stop is None or not stop.is_set():
            if self.dispatch_once() == 0:
                time.sleep(0.02)  # MUST FLAG: unbounded idle pacing

    def serve_queue(self, queue):
        pop = getattr(queue, "get_batch_stream", None) or queue.get_batch
        while True:
            items = pop(16, timeout=0.01)
            if not items:
                return
            for item in items:
                self.offer(item)
            while self.dispatch_once():
                pass
