"""known-bad: acquires that leak on a path the syntactic checkers miss.

``leaky_decode`` DOES hand its lease to a known owner — on the happy
path. The ``recv_into`` between acquire and hand-off can raise, and
nothing releases on that edge: PR 9's corrupt-head shape, visible only
to the CFG's exception edges. ``leaky_branch`` leaks on the untaken
branch: one path returns the lease, the other falls off the end.
``leaky_handler_branch`` releases only under a guard UNRELATED to the
lease — the handler's other branch re-raises with the lease stranded.
``leaky_alias`` takes a local alias of the VIEW: deriving ``.mv``
moves no ownership, so both the exception and fall-through paths leak.
"""


def leaky_decode(pool, sock, n):
    lease = pool.lease(n)
    sock.recv_into(lease.mv)  # can raise: the lease is stranded
    return decode_payload(lease.mv, lease=lease)


def leaky_branch(pool, n, want_lease):
    lease = pool.lease(n)
    if want_lease:
        return lease
    # falls through: lease dropped to the GC backstop


def leaky_handler_branch(pool, sock, n, flag):
    lease = pool.lease(n)
    try:
        sock.recv_into(lease.mv)
    except BaseException:
        if flag:  # guard unrelated to the lease: the other branch leaks
            lease.release()
        raise
    return decode_payload(lease.mv, lease=lease)


def leaky_alias(pool, sock, n):
    lease = pool.lease(n)
    mv = lease.mv  # a view, not a hand-off: the obligation stays here
    sock.recv_into(mv)
    return bytes(mv[:n])


def decode_payload(mv, lease=None):
    return bytes(mv[:4])
