"""BAD: leases that only the GC backstop would ever free — an assigned
lease with no release on any path, a dropped result, and a batch-view
drain that never routes records to an owner."""


def recv_one(pool, sock, n):
    lease = pool.lease(n)
    sock.recv_into(lease.mv)
    return n  # lease stranded: returned value does not carry it


def peek(queue):
    queue.get_view()  # result dropped on the floor


def drain(queue):
    total = 0
    items = queue.get_batch_view(32)
    for rec in items:
        total += rec.event_idx  # never released / materialized / pushed
    return total
