"""BAD: the PR 1 pytest-exit hang shape — a non-daemon worker in a
module whose only join is unbounded (the hang just moves from
interpreter exit to the join site)."""

import threading


def start_worker(target):
    t = threading.Thread(target=target, name="worker")
    t.start()
    return t


def stop_worker(t):
    t.join()  # unbounded: a wedged target hangs shutdown forever
