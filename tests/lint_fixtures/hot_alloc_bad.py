# lint: hot-path
"""BAD: the pre-ISSUE-2 datapath idioms — a frame-sized serialization
copy, contiguous request assembly, a fresh bytes object per recv chunk,
and a bytes(...) materialization of a buffer."""


def send_frame(sock, rec):
    payload = rec.panels.tobytes()
    sock.sendall(rec.to_bytes())
    return payload


def read_exact(sock, n):
    chunks = []
    while n:
        c = sock.recv(n)
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def snapshot(mv):
    return bytes(mv)


def framed(sep, arr):
    # a '#' inside a string literal must not hide the banned call behind
    # naive comment stripping
    return sep.join([b"#", arr.tobytes()])
