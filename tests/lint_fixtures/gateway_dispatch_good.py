"""SANCTIONED: the serving gateway's bounded-wait idioms.

Idle pauses ride a bounded Event wait that ``offer`` wakes (an idle
gateway reacts to a new frame immediately, and the timeout bounds the
worst case); transport pops carry explicit timeouts. None may flag
(blocking-hot-path)."""

import threading


class ServingGateway:
    def __init__(self):
        self._q = []
        self._work = threading.Event()

    def offer(self, rec, tenant="default"):
        self._q.append((tenant, rec))
        self._work.set()
        return True

    def dispatch_once(self):
        if not self._q:
            return 0
        tenant, rec = self._q.pop(0)
        self._dispatch([rec], 1)
        return 1

    def run(self, stop=None):
        while stop is None or not stop.is_set():
            if self.dispatch_once() == 0:
                self._work.wait(timeout=0.02)  # bounded + offer()-woken
                self._work.clear()

    def serve_queue(self, queue):
        pop = getattr(queue, "get_batch_stream", None) or queue.get_batch
        while True:
            items = pop(16, timeout=0.01)
            if not items:
                return
            for item in items:
                self.offer(item)
            while self.dispatch_once():
                pass
