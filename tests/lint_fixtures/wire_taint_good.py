"""sanctioned: the same wire parses with bounds enforced first.

Every size parsed off the wire passes an explicit cap (raise on
oversize) or a ``min()`` clamp before it sizes anything; u16-width
fields are structurally bounded and need no check.
"""

import struct

import numpy as np

_MAX_RLE = 1 << 20
_MAX_FRAME = 256 << 20
_MAX_LEASE = 64 << 20


def decode_rle(buf, values):
    (count,) = struct.unpack_from("<I", buf, 0)
    if count > _MAX_RLE:
        raise ValueError("rle count exceeds decode cap")
    return np.repeat(values, count)


def read_frame(sock, hdr):
    size, flags = struct.unpack("<QH", hdr)
    if size > _MAX_FRAME:
        raise ValueError("frame exceeds wire cap")
    payload = bytearray(size)
    sock.recv_into(payload)
    return payload, flags


def lease_for(pool, hdr):
    n = struct.unpack_from("<I", hdr)[0]
    n = min(n, _MAX_LEASE)
    return pool.lease(n)


def name_buf(hdr):
    # u16 length: structurally capped at 64 KiB, no check required
    (n,) = struct.unpack("<H", hdr)
    return bytearray(n)
