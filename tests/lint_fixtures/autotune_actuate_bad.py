"""KNOWN-BAD: a sleep smuggled into the autotune actuation path.

The controller tick and every knob setter it reaches run on the
autotune daemon's loop (ISSUE 15) — and client-side setters run under
the transport client's lock, which the DATA path shares. A setter that
sleeps "to let the change settle" (or a tick that paces itself with
``time.sleep``) therefore stalls tuning AND the hot path behind the
shared lock (blocking-hot-path)."""

import time


class KnobRegistry:
    def knob(self, name):
        return self._knobs[name]

    def apply(self, name, value, why="probe"):
        knob = self.knob(name)
        knob.set(value)
        time.sleep(0.1)  # MUST FLAG: "let the change settle" on the loop
        return value


class HillClimber:
    def __init__(self, registry):
        self.registry = registry

    def tick(self):
        self.registry.apply("k", 2.0)
        time.sleep(1.0)  # MUST FLAG: self-pacing belongs to the daemon wait
        return None
