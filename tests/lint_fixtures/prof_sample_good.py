"""SANCTIONED: the continuous profiler's sampling-loop idioms.

Pacing is a bounded, stoppable ``Event.wait`` with drift correction;
the sample body only walks frames and preallocated arrays; thread join
at shutdown is timeout-bounded. None may flag (blocking-hot-path)."""

import sys
import threading
import time


class FlameSampler:
    def __init__(self, trie, period):
        self.trie = trie
        self.period = period
        self._stop = threading.Event()
        self._thread = None

    def _run(self):
        nxt = time.monotonic() + self.period
        while True:
            delay = nxt - time.monotonic()
            if delay < 0.0:
                nxt = time.monotonic() + self.period
                delay = 0.0
            if self._stop.wait(delay):  # bounded, stoppable pacing
                break
            self._sample_once()
            nxt += self.period

    def _sample_once(self):
        frames = sys._current_frames()
        for ident in frames:
            self.trie.sample(frames[ident], True, 0)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)  # bounded shutdown join
