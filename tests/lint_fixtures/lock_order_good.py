"""sanctioned: the same two locks with ONE global order, declared.

Every path nests registry-before-connection; the ``# lock-order:``
annotation turns the convention into a checked assertion.  The close
path drops to a snapshot-then-act shape instead of nesting backwards.
"""

import threading


class ConnRegistry:
    def __init__(self):
        self._reg_lock = threading.Lock()
        self._conns = []

    def register(self, conn):
        with self._reg_lock:
            self._conns.append(conn)

    def unregister(self, conn):
        with self._reg_lock:
            if conn in self._conns:
                self._conns.remove(conn)

    def dump_all(self):
        # lock-order: ConnRegistry._reg_lock -> Conn._conn_lock
        with self._reg_lock:
            lines = []
            for conn in self._conns:
                with conn._conn_lock:
                    lines.append(conn.describe())
            return lines


class Conn:
    def __init__(self, registry):
        self.registry = registry
        self._conn_lock = threading.Lock()
        self.open = True

    def describe(self):
        return "conn open=%s" % self.open

    def close(self):
        # mark closed under the connection lock, THEN unregister with no
        # lock held — the declared order is never contradicted
        with self._conn_lock:
            self.open = False
        self.registry.unregister(self)
