"""GOOD: every wait under the drain loop carries a deadline — timed
acquire, timed join, timeout-bearing queue ops, and `with lock:`
micro-sections (not flagged: the timeout-expressible explicit-wait form
is the banned one)."""


def _settle(lock):
    if not lock.acquire(timeout=1.0):
        raise TimeoutError("lock held past deadline")
    try:
        pass
    finally:
        lock.release()


def _flush_leg(thread):
    thread.join(2.0)


def _account(lock, counters):
    with lock:
        counters["batches"] += 1


def batches_from_queue(queue, lock, thread, counters):
    while True:
        _settle(lock)
        _flush_leg(thread)
        _account(lock, counters)
        if not queue.get(timeout=0.05):
            return
