"""SANCTIONED: the autotune actuation idioms.

Setters are bounded — an assignment under a lock, or a deadline-bounded
wire exchange owned by the transport client; the controller tick never
sleeps (pacing lives in the daemon's stoppable Event wait). None may
flag (blocking-hot-path)."""

import threading


class KnobRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._knobs = {}

    def knob(self, name):
        with self._lock:
            return self._knobs[name]

    def apply(self, name, value, why="probe"):
        knob = self.knob(name)
        knob.set(value)  # bounded by the setter's own contract
        return value


class HillClimber:
    def __init__(self, registry):
        self.registry = registry

    def tick(self):
        self.registry.apply("k", 2.0)
        return None


class AutotuneDaemon:
    def __init__(self, controller):
        self.controller = controller
        self._stop = threading.Event()

    def _run(self):
        while not self._stop.wait(2.0):  # bounded, stoppable pacing
            self.controller.tick()
