"""GOOD: both sanctioned shapes — a daemon thread the process may exit
without, and a non-daemon worker whose module joins with a deadline."""

import threading


def start_sidecar(target):
    t = threading.Thread(target=target, daemon=True, name="sidecar")
    t.start()
    return t


def start_worker(target):
    t = threading.Thread(target=target, name="worker")
    t.start()
    return t


def stop_worker(t):
    t.join(timeout=5.0)
    if t.is_alive():
        raise RuntimeError("worker did not stop in 5s")
