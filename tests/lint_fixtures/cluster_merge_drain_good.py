"""SANCTIONED: the cluster merge drain's bounded-wait idioms.

Sweeping partitions with ``timeout=0.0`` pops, blocking one
caller-bounded slice on a rotating partition, and pausing an idle
member on an interruptible ``Event.wait`` are all deadline-bounded —
none may flag (blocking-hot-path)."""

import threading
import time


def batches_from_queue(queue, batch_size):
    pop = getattr(queue, "get_batch_stream", None) or queue.get_batch
    while True:
        items = pop(batch_size, timeout=0.01)
        if not items:
            return
        yield items


class ClusterishClient:
    def __init__(self):
        self._idle = threading.Event()

    def get_batch_stream(self, max_items, timeout=None):
        return self._merge_drain(max_items, timeout)

    def _merge_drain(self, max_items, timeout):
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        while True:
            for p in self._partitions:
                out.extend(self._pop(p, max_items - len(out), 0.0))
            if out:
                return out
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return []
            if not self._partitions:
                self._idle.wait(0.05)  # interruptible, bounded pause
                continue
            out.extend(self._pop(self._partitions[0], max_items, 0.05))

    def _pop(self, p, n, t):
        return self._clients[p].get_batch(n, timeout=t)
