"""sanctioned: a wire surface the model fleet fully accounts for.

Both dispatched opcodes are covered — ``_OP_PUT_SEQ`` by the windowed
model, ``_OP_PUT`` by a written ``NON_MODELED`` justification — so the
drift gate has nothing to say.  (The model->code direction only runs
against the real transport, never against fixture-sized protocols.)
"""

_OP_PUT_SEQ = b"W"
_OP_PUT = b"P"
_ST_OK = b"1"


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("eof")
        buf += chunk
    return buf


class CoveredServerConn:
    def __init__(self, sock, queue):
        self._sock = sock
        self.queue = queue

    def _dispatch(self):
        op = _recv_exact(self._sock, 1)[0]
        name = _OPS.get(op)
        if name is None:
            raise ConnectionError("unknown opcode")
        getattr(self, name)()

    def _op_put_seq(self):
        item = _recv_exact(self._sock, 12)
        self.queue.put(item)
        self._sock.sendall(_ST_OK)

    def _op_put(self):
        item = _recv_exact(self._sock, 4)
        self.queue.put(item)
        self._sock.sendall(_ST_OK)


_OPS = {
    _OP_PUT_SEQ[0]: "_op_put_seq",
    _OP_PUT[0]: "_op_put",
}
