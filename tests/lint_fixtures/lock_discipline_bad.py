"""BAD: the PR 1 scrape-vs-teardown shape — `_handle` is declared
guarded but the stats read and the teardown write both touch it without
the lock (check-then-use passes a freed handle to C)."""

import threading


class Ring:
    def __init__(self):
        self._handle_lock = threading.Lock()
        self._handle = object()  # guarded-by: _handle_lock
        # tuple targets must not silently drop the annotation
        self._gets, self._puts = 0, 0  # guarded-by: _handle_lock

    def bump(self):
        self._gets += 1  # unlocked counter write

    def stats(self):
        if self._handle is None:
            raise RuntimeError("detached")
        return id(self._handle)

    def disconnect(self):
        self._handle = None
