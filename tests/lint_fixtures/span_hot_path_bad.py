# lint: hot-path
"""BAD: per-frame allocation idioms on the tracing span hot path —
serializing a trace context through frame-sized copies or fresh bytes.
The span path runs inside the transport hot loop; the same hot-alloc
bans apply to it as to the datapath (ISSUE 4 satellite)."""


def attach_context_to_wire(rec, ctx_struct):
    # materializing the whole record to splice a 25-byte context in is a
    # frame-sized copy per sampled frame
    return rec.to_bytes() + ctx_struct


def read_span_record(sock, n):
    # a fresh bytes object per chunk on the spool-reader hot loop
    return sock.recv(n)


def spool_span(f, payload_mv):
    # bytes(...) materialization of the span buffer before writing
    f.write(bytes(payload_mv))


def pack_context_slow(panels):
    # frame-sized ndarray -> bytes serialization to hash a trace id
    return hash(panels.tobytes())
