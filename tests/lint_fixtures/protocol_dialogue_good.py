"""sanctioned: the same wire dialogue with both sides matched.

Every reply arm the server can emit has a client branch, and every
opcode the server restricts to a mode is guarded by the client's mode
attribute (redirect/raise at the entry) — the shape
``transport/tcp.py`` / ``transport/evloop.py`` ship.
"""

import struct

_OP_PUT = b"P"
_OP_PROBE = b"Q"
_OP_SUB = b"M"
_OP_ACK = b"K"
_ST_OK = b"1"
_ST_NO = b"0"


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("eof")
        buf += chunk
    return buf


class _StreamState:
    def __init__(self):
        self.seq = 0


class GoodServerConn:
    def __init__(self, sock, queue):
        self._sock = sock
        self.queue = queue
        self.stream = None

    def _dispatch(self):
        op = _recv_exact(self._sock, 1)[0]
        if self.stream is not None:
            if op == _OP_ACK[0]:
                self._op_ack()
                return
            raise ConnectionError("bad opcode on streamed connection")
        name = _OPS.get(op)
        if name is None:
            raise ConnectionError("unknown opcode")
        getattr(self, name)()

    def _op_put(self):
        item = _recv_exact(self._sock, 4)
        ok = self.queue.put(item)
        self._sock.sendall(_ST_OK if ok else _ST_NO)

    def _op_probe(self):
        if self.queue.empty():
            self._sock.sendall(_ST_NO)
            return
        self._sock.sendall(_ST_OK + struct.pack("<I", self.queue.depth()))

    def _op_sub(self):
        self.stream = _StreamState()

    def _op_ack(self):
        _recv_exact(self._sock, 8)


_OPS = {
    _OP_PUT[0]: "_op_put",
    _OP_PROBE[0]: "_op_probe",
    _OP_SUB[0]: "_op_sub",
    _OP_ACK[0]: "_op_ack",
}


class GoodClient:
    def __init__(self, sock):
        self._sock = sock
        self._stream = None

    def put(self, payload):
        if self._stream is not None:
            raise RuntimeError("puts are illegal on a streamed client")
        self._sock.sendall(_OP_PUT + payload)
        st = _recv_exact(self._sock, 1)
        return st == _ST_OK

    def probe(self):
        if self._stream is not None:
            raise RuntimeError("probes are illegal on a streamed client")
        self._sock.sendall(_OP_PROBE)
        st = _recv_exact(self._sock, 1)
        if st != _ST_OK:  # NO answer carries no payload: stop here
            return 0
        (depth,) = struct.unpack("<I", _recv_exact(self._sock, 4))
        return depth

    def subscribe(self):
        if self._stream is not None:  # idempotent: first subscription wins
            return self._stream
        self._sock.sendall(_OP_SUB)
        self._stream = StreamReader(self)
        return self._stream


class StreamReader:
    def __init__(self, client):
        self._c = client

    def ack(self, seq):
        self._c._sock.sendall(_OP_ACK + struct.pack("<Q", seq))
