# lint: hot-path
"""GOOD: the sanctioned span-path idioms — a fixed-size struct pack for
the wire context (no frame-sized copies), counter-only gating for
unsampled frames (no allocation), buffered JSONL spool writes."""

import json
import struct

_CTX = struct.Struct("<QIB12s")


def attach_context_to_wire(header, ctx):
    # the context is its own small header part; the frame payload stays
    # a zero-copy memoryview (scatter-gather send)
    return header + _CTX.pack(ctx.trace_id, ctx.origin_pid, 1, b"host")


def maybe_trace(state):
    # unsampled gate: counter arithmetic only, no objects
    state.count += 1
    if state.count % state.every:
        return None
    return state.make_context()


def spool_span(buf, trace_id, name, t0, t1):
    # buffered JSONL append; flushed in batches, never per span
    buf.append(json.dumps({"t": "s", "id": trace_id, "n": name, "a": t0, "b": t1}))
