# lint: hot-path
"""GOOD: the sanctioned wire-compression idioms — codec transforms
stage through caller-owned buffers (pool leases), array pieces land in
the destination memoryview via ``.data.cast("B")`` views, and receives
fill pooled leases with ``recv_into`` (ISSUE 9 satellite)."""

import struct

import numpy as np

_HDR = struct.Struct("<BBII")


def compress_frame(parts, codec, itemsize, pool):
    # compress the payload PART into a lease; the head stays its own
    # small part — no contiguous assembly of the frame
    head, body = parts
    out = pool.lease(body.nbytes)
    n = codec.compress(body, itemsize, out.mv)
    if n is None:
        out.release()
        return parts, None
    return [head, out.mv[:n]], out


def emit_plane(dst, off, arr):
    # array pieces land via a zero-copy memoryview of the array
    a = np.ascontiguousarray(arr)
    end = off + a.nbytes
    dst[off:end] = a.data.cast("B")
    return end


def recv_compressed(sock, lease):
    # the compressed payload fills a pooled lease in place
    got = 0
    mv = lease.mv
    while got < len(mv):
        got += sock.recv_into(mv[got:])
    return mv
