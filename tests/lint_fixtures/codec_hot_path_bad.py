# lint: hot-path
"""BAD: per-frame allocation idioms inside a wire-compression codec —
the compress/decompress hot path runs once per brokered frame, so
frame-sized serialization copies, raw recv, and bytes materialization
are exactly as banned here as on the rest of the datapath (ISSUE 9
satellite)."""


def compress_frame(rec, dst):
    # serializing the record to bytes before compressing is a
    # frame-sized copy the scatter-gather parts already avoid
    raw = rec.to_bytes()
    dst[: len(raw)] = raw
    return len(raw)


def compress_panels(panels, dst):
    # frame-sized ndarray -> bytes copy just to feed the encoder
    blob = panels.tobytes()
    dst[: len(blob)] = blob
    return len(blob)


def recv_compressed(sock, n):
    # a fresh bytes object per compressed payload; recv_into a pooled
    # lease is the sanctioned receive
    return sock.recv(n)


def stage_compressed(mv):
    # bytes(...) materialization of the staging buffer before sending
    return bytes(mv)
