"""BAD: unbounded waits reachable from the drain loop — a sleep two
calls deep, a bare lock acquire, and an unbounded join, all under a
function the call graph roots at."""

import time
from time import sleep as _zzz


def _nap():
    _zzz(0.25)  # bare-name sleep: same stall as time.sleep


def _settle(lock):
    lock.acquire()  # no timeout: a stuck peer stalls the drain forever
    try:
        time.sleep(0.5)
    finally:
        lock.release()


def _settle_explicit(lock):
    # acquire(True) is the SAME unbounded wait — the first positional is
    # `blocking`, not a timeout, and must not be mistaken for a bound
    lock.acquire(True)
    lock.release()


def _flush_leg(thread):
    thread.join()  # unbounded


def batches_from_queue(queue, lock, thread):
    while True:
        _settle(lock)
        _settle_explicit(lock)
        _nap()
        _flush_leg(thread)
        if queue.empty():
            return
