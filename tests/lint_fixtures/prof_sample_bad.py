"""KNOWN-BAD: blocking primitives in the continuous profiler's loop.

The flame sampler runs ~97 times a second in EVERY pipeline process.
Pacing it with ``time.sleep`` makes it unstoppable for up to a period
at shutdown and drifts against the sample clock; an unbounded ``join``
in the sampling path can wedge the whole process behind a stuck
sampled thread (blocking-hot-path)."""

import sys
import time


class FlameSampler:
    def __init__(self, trie):
        self.trie = trie
        self._stopping = False

    def _run(self):
        while not self._stopping:
            self._sample_once()
            time.sleep(0.0103)  # MUST FLAG: unstoppable pacing on the loop

    def _sample_once(self):
        frames = sys._current_frames()
        for ident in frames:
            self._bill(frames[ident])

    def _bill(self, frame):
        self.trie.sample(frame, True, 0)
        time.sleep(0)  # MUST FLAG: yielding the GIL mid-sample skews counts
