"""known-bad: client/server opcode dialogue with seeded desyncs.

Two bug classes the protocol-dialogue checker must flag:

1. ``_op_probe`` can answer ``_ST_NO`` (no payload) but the client's
   ``probe()`` never branches on the status byte before reading the
   4-byte depth — one NO answer and every later byte is misframed
   (the seeded "server arm with no client handler" desync);
2. ``probe()``/``subscribe()`` send opcodes the server kills on a
   streamed connection without checking ``self._stream`` anywhere —
   the replay-on-streamed class of kill.
"""

import struct

_OP_PUT = b"P"
_OP_PROBE = b"Q"
_OP_SUB = b"M"
_OP_ACK = b"K"
_ST_OK = b"1"
_ST_NO = b"0"


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("eof")
        buf += chunk
    return buf


class _StreamState:
    def __init__(self):
        self.seq = 0


class BadServerConn:
    def __init__(self, sock, queue):
        self._sock = sock
        self.queue = queue
        self.stream = None

    def _dispatch(self):
        op = _recv_exact(self._sock, 1)[0]
        if self.stream is not None:
            # a streamed connection carries only acks upstream
            if op == _OP_ACK[0]:
                self._op_ack()
                return
            raise ConnectionError("bad opcode on streamed connection")
        name = _OPS.get(op)
        if name is None:
            raise ConnectionError("unknown opcode")
        getattr(self, name)()

    def _op_put(self):
        item = _recv_exact(self._sock, 4)
        ok = self.queue.put(item)
        self._sock.sendall(_ST_OK if ok else _ST_NO)

    def _op_probe(self):
        if self.queue.empty():
            self._sock.sendall(_ST_NO)  # reply arm with no client branch
            return
        self._sock.sendall(_ST_OK + struct.pack("<I", self.queue.depth()))

    def _op_sub(self):
        self.stream = _StreamState()

    def _op_ack(self):
        _recv_exact(self._sock, 8)


_OPS = {
    _OP_PUT[0]: "_op_put",
    _OP_PROBE[0]: "_op_probe",
    _OP_SUB[0]: "_op_sub",
    _OP_ACK[0]: "_op_ack",
}


class BadClient:
    def __init__(self, sock):
        self._sock = sock
        self._stream = None

    def put(self, payload):
        if self._stream is not None:
            raise RuntimeError("puts are illegal on a streamed client")
        self._sock.sendall(_OP_PUT + payload)
        st = _recv_exact(self._sock, 1)
        return st == _ST_OK

    def probe(self):
        # BUG: bare status read, then an unconditional payload read —
        # and no stream guard anywhere on the call chain
        self._sock.sendall(_OP_PROBE)
        _recv_exact(self._sock, 1)
        (depth,) = struct.unpack("<I", _recv_exact(self._sock, 4))
        return depth

    def subscribe(self):
        # BUG: not idempotent and not stream-guarded: a second call on a
        # subscribed connection is killed server-side
        self._sock.sendall(_OP_SUB)
        self._stream = StreamReader(self)
        return self._stream


class StreamReader:
    def __init__(self, client):
        self._c = client

    def ack(self, seq):
        self._c._sock.sendall(_OP_ACK + struct.pack("<Q", seq))
