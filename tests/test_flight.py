"""Tests for ISSUE 4: the crash flight recorder (postmortem black box).

Satellite checklist coverage: dump-on-stall (StallDetector ``on_event``
wiring) and dump-on-SIGUSR2, plus the dump contents contract — the
triggering StallEvent, a metrics-registry snapshot, and every thread's
stack."""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import pytest

from psana_ray_tpu.obs.flight import DUMP_MIN_INTERVAL_S, FlightRecorder
from psana_ray_tpu.obs.registry import MetricsRegistry
from psana_ray_tpu.obs.stall import EVENT_BACKPRESSURE, StallDetector, StallEvent


@pytest.fixture
def recorder(tmp_path):
    fl = FlightRecorder()
    fl.install(str(tmp_path), process="test")
    yield fl, tmp_path
    fl.uninstall()


def _dumps(tmp_path):
    return sorted(tmp_path.glob("flight-*.json"))


class TestRing:
    def test_bounded_ring_keeps_last_n(self):
        fl = FlightRecorder(maxlen=4)
        for i in range(10):
            fl.record("evt", i=i)
        evts = fl.events()
        assert len(evts) == 4 and [e["i"] for e in evts] == [6, 7, 8, 9]
        assert fl.event_count == 10  # total survives eviction

    def test_events_carry_wall_and_mono(self):
        fl = FlightRecorder()
        fl.record("reconnect", host="h")
        (e,) = fl.events()
        assert e["kind"] == "reconnect" and e["wall"] > 0 and e["mono"] > 0
        assert e["host"] == "h"

    def test_snapshot_is_a_registry_source(self):
        fl = FlightRecorder()
        fl.record("eos_complete")
        fl.record("eos_complete")
        fl.record("reconnect")
        snap = fl.snapshot()
        assert snap["events_total"] == 3
        assert snap["events_eos_complete_total"] == 2
        assert snap["armed"] is False

    def test_unarmed_dump_returns_none(self):
        assert FlightRecorder().dump("nothing") is None


class TestDumpOnStall:
    def test_stall_event_triggers_dump_with_contents(self, recorder):
        fl, tmp_path = recorder
        MetricsRegistry.default().register("unit", {"frames_total": 7})
        fl.record("reconnect", host="queue-host")
        # a simulated stall: drive the detector's poll loop over a queue
        # that sits pegged at maxsize past the threshold
        det = StallDetector(full_threshold_s=1.0, on_event=fl.on_stall)

        class Full:
            def stats(self):
                return {"depth": 8, "maxsize": 8, "puts": 1, "gets": 0}

        det.watch("q", Full())
        det.poll_once(now=100.0)
        det.poll_once(now=102.0)  # threshold crossed -> event -> dump
        dumps = _dumps(tmp_path)
        assert len(dumps) == 1
        doc = json.loads(dumps[0].read_text())
        assert doc["reason"] == "stall"
        # the triggering StallEvent rides the dump
        assert doc["trigger"]["kind"] == EVENT_BACKPRESSURE
        assert doc["trigger"]["queue"] == "q" and doc["trigger"]["depth"] == 8
        # the ring (incl. pre-stall breadcrumbs) is in the dump
        kinds = [e["kind"] for e in doc["events"]]
        assert "reconnect" in kinds and "stall" in kinds
        # a metrics-registry snapshot is embedded
        assert doc["metrics"]["unit"]["frames_total"] == 7
        # every thread's stack, including this one
        assert doc["threads"]
        assert any(
            "test_stall_event_triggers_dump" in "\n".join(stack)
            for stack in doc["threads"].values()
        )

    def test_dump_appends_timeseries_tail(self, recorder):
        """ISSUE 13: when a history sampler runs, dumps carry the last N
        samples per key — the minutes BEFORE the trigger, not just the
        instant. Without one, the key is present and null (the dump
        shape is stable either way)."""
        from psana_ray_tpu.obs import timeseries as ts_mod
        from psana_ray_tpu.obs.flight import TAIL_SAMPLES

        fl, tmp_path = recorder
        # no sampler -> tail is null, dump still lands
        p0 = fl.dump("pretail", force=True)
        assert json.loads(open(p0).read())["timeseries_tail"] is None
        reg = MetricsRegistry()
        reg.register("unit", lambda: {"frames_total": 1})
        sampler = ts_mod.start_default_history(
            interval_s=60.0, registry=reg  # manual sweeps only
        )
        try:
            for i in range(TAIL_SAMPLES + 10):  # overfill: tail must bound
                sampler.sample_once(now=100.0 + i)
            path = fl.dump("history", force=True)
            doc = json.loads(open(path).read())
            tail = doc["timeseries_tail"]
            assert tail is not None
            series = tail["unit.frames_total"]
            assert len(series) == TAIL_SAMPLES  # bounded
            # time-ordered, ending at the LAST pre-trigger sample
            assert series[-1][0] == pytest.approx(100.0 + TAIL_SAMPLES + 9)
            assert series[0][0] < series[-1][0]
        finally:
            ts_mod.stop_default_history()

    def test_dump_rate_limit(self, recorder):
        fl, tmp_path = recorder
        ev = StallEvent(EVENT_BACKPRESSURE, "q", 1.0, 8, 8)
        fl.on_stall(ev)
        fl.on_stall(ev)  # within DUMP_MIN_INTERVAL_S: suppressed
        assert len(_dumps(tmp_path)) == 1
        assert DUMP_MIN_INTERVAL_S > 0
        # both events still recorded even when the dump was suppressed
        assert fl.snapshot()["events_stall_total"] == 2


class TestDumpOnSignal:
    def test_sigusr2_dumps(self, recorder):
        fl, tmp_path = recorder
        fl.record("eos_complete")
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.monotonic() + 5.0
        while not _dumps(tmp_path) and time.monotonic() < deadline:
            time.sleep(0.01)
        dumps = _dumps(tmp_path)
        assert dumps, "SIGUSR2 did not produce a flight dump"
        doc = json.loads(dumps[0].read_text())
        assert doc["reason"] == "signal"
        assert any(e["kind"] == "sigusr2" for e in doc["events"])
        assert doc["threads"]

    def test_uninstall_restores_handler(self, tmp_path):
        prev = signal.getsignal(signal.SIGUSR2)
        fl = FlightRecorder()
        fl.install(str(tmp_path), process="t")
        assert signal.getsignal(signal.SIGUSR2) == fl._on_signal
        fl.uninstall()
        assert signal.getsignal(signal.SIGUSR2) == prev

    def test_install_off_main_thread_still_arms_dumps(self, tmp_path):
        # signal.signal is main-thread-only; install must degrade to
        # excepthook + programmatic triggers instead of raising
        fl = FlightRecorder()
        err = []

        def go():
            try:
                fl.install(str(tmp_path), process="bg", excepthook=False)
            except BaseException as e:  # noqa: BLE001
                err.append(e)

        t = threading.Thread(target=go)
        t.start()
        t.join(timeout=5.0)
        assert not err
        assert fl.dump("manual", force=True) is not None


class TestDumpOnException:
    def test_excepthook_dumps_and_chains(self, tmp_path):
        fl = FlightRecorder()
        seen = []
        import sys

        prev_hook = sys.excepthook
        sys.excepthook = lambda *a: seen.append(a)
        try:
            fl.install(str(tmp_path), process="t")
            try:
                raise RuntimeError("boom")
            except RuntimeError:
                sys.excepthook(*sys.exc_info())
        finally:
            fl.uninstall()
            sys.excepthook = prev_hook
        dumps = _dumps(tmp_path)
        assert dumps
        doc = json.loads(dumps[0].read_text())
        assert doc["reason"] == "exception"
        assert doc["trigger"]["exc_type"] == "RuntimeError"
        assert "boom" in doc["trigger"]["message"]
        assert seen, "previous excepthook was not chained"


class TestDumpOnThreadException:
    def test_worker_thread_crash_dumps(self, tmp_path):
        # sys.excepthook never fires for non-main threads; the recorder
        # must chain threading.excepthook to catch crashing workers
        fl = FlightRecorder()
        # park a no-op as the chained hook: the recorder must still call
        # the previous hook, but pytest's own threading hook would turn
        # this deliberate crash into a test error
        prev = threading.excepthook
        threading.excepthook = lambda args: None
        fl.install(str(tmp_path), process="t")
        try:
            t = threading.Thread(
                target=lambda: (_ for _ in ()).throw(ValueError("worker boom")),
                name="doomed-worker",
            )
            t.start()
            t.join(timeout=5.0)
        finally:
            fl.uninstall()
            threading.excepthook = prev
        dumps = _dumps(tmp_path)
        assert dumps, "worker-thread crash did not produce a flight dump"
        doc = json.loads(dumps[0].read_text())
        assert doc["reason"] == "thread_exception"
        assert doc["trigger"]["thread"] == "doomed-worker"
        assert doc["trigger"]["exc_type"] == "ValueError"

    def test_uninstall_restores_threading_hook(self, tmp_path):
        prev = threading.excepthook
        fl = FlightRecorder()
        fl.install(str(tmp_path), process="t")
        assert threading.excepthook == fl._on_thread_exception
        fl.uninstall()
        assert threading.excepthook == prev


class TestWiring:
    def test_tcp_reconnect_records_breadcrumb(self):
        from psana_ray_tpu.obs import flight as flight_mod
        from psana_ray_tpu.transport.registry import TransportClosed
        from psana_ray_tpu.transport.tcp import TcpQueueClient

        before = flight_mod.FLIGHT.snapshot().get("events_reconnect_total", 0)
        with pytest.raises(TransportClosed):
            TcpQueueClient(
                "127.0.0.1", 1, timeout_s=0.2,
                reconnect_tries=1, reconnect_base_s=0.01,
            )
        after = flight_mod.FLIGHT.snapshot().get("events_reconnect_total", 0)
        assert after > before

    def test_queue_server_wires_stall_dumps(self):
        # the CLI passes FLIGHT.on_stall into its StallDetector — pin the
        # wiring so a refactor can't silently drop the black box. The
        # serve body lives in _serve (main dispatches to it directly or
        # per worker via --workers), so inspect the module, not main
        import inspect

        import psana_ray_tpu.queue_server as qs

        src = inspect.getsource(qs)
        assert "on_event=FLIGHT.on_stall" in src
        # and the wiring sits on the path every worker runs, not in a
        # single-process-only branch: _serve is the shared serve body
        assert "on_event=FLIGHT.on_stall" in inspect.getsource(qs._serve)
